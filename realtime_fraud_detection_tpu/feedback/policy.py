"""Retrain triggers, candidate training, and the promotion gate.

The control loop's decision layer:

- :class:`RetrainPolicy` — watches the prequential snapshot and the drift
  monitor; fires an auditable trigger on prequential degradation (sliding
  AUC falling under the fading-window baseline) or feature drift, with a
  cooldown and a minimum-labels floor so one noisy window can't thrash
  the trainer.
- :class:`Retrainer` — fits a candidate (gbdt + isolation forest, and
  optionally the LSTM branch when the buffer stores history) on the
  labeled buffer's past, selects the combine strategy for the candidate
  blend — weighted average vs the stacked combiner
  (ensemble/combine.py STACKING, which the offline protocol now also
  exercises) — on a selection split, and leaves the most recent slice
  untouched for the gate.
- :class:`PromotionGate` — the A/B gate in front of the serving blend:
  candidate scores vs the scores that ACTUALLY served (the buffer's
  as-served record) on the held-out most-recent labels. Non-regression on
  AUC and on recall at the pinned operating point, plus a minimum
  positive count. A failed gate changes nothing, anywhere.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Mapping, Optional

import numpy as np

from realtime_fraud_detection_tpu.feedback.prequential import sliding_auc

__all__ = ["RetrainPolicy", "Retrainer", "PromotionGate"]


@dataclasses.dataclass
class RetrainPolicy:
    """Degradation/drift watcher -> retrain triggers."""

    auc_drop: float = 0.08          # sliding below fading by this much
    auc_floor: float = 0.0          # absolute sliding-AUC alarm (0 = off)
    min_labels: int = 300           # labeled examples before any trigger
    cooldown_s: float = 600.0       # stream-time between triggers
    use_drift: bool = True

    last_trigger_ts: float = -math.inf

    def ready(self, labeled_total: int, now: float) -> bool:
        """The cheap pre-check (plain counter + cooldown): callers on the
        scoring hot path gate the expensive snapshot/drift computation on
        this, so a not-yet-eligible policy costs O(1) per batch."""
        return (labeled_total >= self.min_labels
                and now - self.last_trigger_ts >= self.cooldown_s)

    def observe(self, snapshot: Mapping[str, Any], drift_report: Any,
                now: float) -> Optional[Dict[str, Any]]:
        """One policy evaluation; returns a trigger event dict or None."""
        if not self.ready(int(snapshot.get("labeled_total", 0)), now):
            return None
        s_auc = float(snapshot.get("sliding", {}).get("auc", float("nan")))
        f_auc = float(snapshot.get("fading", {}).get("auc", float("nan")))
        reason = None
        details: Dict[str, Any] = {"sliding_auc": s_auc, "fading_auc": f_auc}
        if not math.isnan(s_auc):
            if (not math.isnan(f_auc)
                    and f_auc - s_auc >= self.auc_drop):
                reason = "prequential_auc_drop"
                details["drop"] = round(f_auc - s_auc, 4)
            elif self.auc_floor > 0.0 and s_auc < self.auc_floor:
                reason = "prequential_auc_floor"
        if reason is None and self.use_drift and drift_report is not None \
                and getattr(drift_report, "drifted", False):
            reason = "feature_drift"
            details["max_psi"] = float(drift_report.max_psi)
            details["top_features"] = list(drift_report.top_features[:5])
        if reason is None:
            return None
        self.last_trigger_ts = now
        return {"type": "retrain_trigger", "reason": reason, "ts": now,
                **details}


def _branch_scores(candidate: Mapping[str, Any],
                   arrays: Mapping[str, np.ndarray],
                   sl: slice) -> Dict[str, np.ndarray]:
    """Per-branch candidate probabilities on a buffer slice."""
    import jax

    from realtime_fraud_detection_tpu.models.isolation_forest import (
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.trees import (
        tree_ensemble_predict,
    )

    x = arrays["x"][sl]
    out = {
        "xgboost_primary": np.asarray(
            jax.jit(tree_ensemble_predict)(candidate["trees"], x)),
        "isolation_forest": np.asarray(
            jax.jit(iforest_predict)(candidate["iforest"], x)),
    }
    if candidate.get("lstm") is not None and "history" in arrays:
        from realtime_fraud_detection_tpu.models.lstm import lstm_logits

        z = np.asarray(jax.jit(lstm_logits)(
            candidate["lstm"], np.clip(arrays["history"][sl], -10, 10),
            arrays["history_len"][sl]))
        out["lstm_sequential"] = 1.0 / (1.0 + np.exp(-z))
    return out


def blend_scores(branch_scores: Mapping[str, np.ndarray],
                 weights: Mapping[str, float],
                 strategy: str = "weighted_average") -> np.ndarray:
    """Serving-parity combine of candidate branch scores: the shared
    ``blend_branch_scores`` recipe (ensemble/combine.py — the same one the
    offline protocol's ``_blend_fn`` curries), running the SAME jitted
    combine the fused device program does, at any strategy — including
    the stacked combiner."""
    from realtime_fraud_detection_tpu.ensemble.combine import (
        blend_branch_scores,
    )

    return blend_branch_scores(dict(branch_scores), dict(weights), strategy)


@dataclasses.dataclass
class Retrainer:
    """Candidate trainer over the labeled buffer.

    Splits the time-ordered buffer into train (oldest ``1 - select_frac -
    holdout_frac``), strategy-selection, and gate-holdout (most recent)
    segments; the holdout is NEVER seen by training or selection — it
    belongs to the gate.
    """

    n_trees: int = 48
    depth: int = 5
    iforest_trees: int = 60
    seed: int = 11
    select_frac: float = 0.2
    holdout_frac: float = 0.2
    train_neural: bool = False
    neural_hidden: int = 64
    neural_epochs: int = 2
    try_stacking: bool = True

    def retrain(self, arrays: Mapping[str, np.ndarray],
                weights: Optional[Mapping[str, float]] = None,
                label_noise_seed: Optional[int] = None) -> Dict[str, Any]:
        """Fit a candidate; returns the candidate dict (models + blend +
        per-split evidence + the holdout slice for the gate).

        ``label_noise_seed`` permutes the TRAINING labels — the drill's
        negative control: a candidate trained on garbage must be caught by
        the gate, never by luck.
        """
        from realtime_fraud_detection_tpu.models.isolation_forest import (
            IsolationForestTrainer,
        )
        from realtime_fraud_detection_tpu.training import GBDTTrainer

        n = len(arrays["y"])
        n_hold = max(int(n * self.holdout_frac), 1)
        n_sel = max(int(n * self.select_frac), 1)
        n_train = n - n_hold - n_sel
        if n_train < 50:
            raise ValueError(
                f"labeled buffer too small to retrain: {n} examples "
                f"({n_train} would remain for training)")
        tr, sel, hold = (slice(0, n_train), slice(n_train, n_train + n_sel),
                         slice(n_train + n_sel, n))
        y_tr = arrays["y"][tr]
        if label_noise_seed is not None:
            y_tr = np.random.default_rng(label_noise_seed).permutation(y_tr)
        trees = GBDTTrainer(n_estimators=self.n_trees, max_depth=self.depth,
                            seed=self.seed).fit(arrays["x"][tr], y_tr)
        normals = arrays["x"][tr][y_tr < 0.5][:6000]
        iforest = IsolationForestTrainer(
            n_estimators=self.iforest_trees, seed=self.seed + 1).fit(normals)
        candidate: Dict[str, Any] = {"trees": trees, "iforest": iforest,
                                     "lstm": None}
        if self.train_neural and "history" in arrays:
            candidate["lstm"] = self._train_lstm(arrays, tr, y_tr)

        if weights is None:
            from realtime_fraud_detection_tpu.utils.config import Config

            weights = Config().normalized_weights()
        cand_names = ["xgboost_primary", "isolation_forest"] + (
            ["lstm_sequential"] if candidate["lstm"] is not None else [])
        blend_w = {nm: float(weights.get(nm, 0.0)) or 0.05
                   for nm in cand_names}

        # strategy selection on the selection split — weighted average vs
        # the stacked combiner, the candidate's one free structural choice
        sel_scores = _branch_scores(candidate, arrays, sel)
        y_sel = arrays["y"][sel]
        select_auc = {"weighted_average": sliding_auc(
            y_sel, blend_scores(sel_scores, blend_w, "weighted_average"))}
        strategy = "weighted_average"
        if self.try_stacking:
            select_auc["stacking"] = sliding_auc(
                y_sel, blend_scores(sel_scores, blend_w, "stacking"))
            if not math.isnan(select_auc["stacking"]) and (
                    math.isnan(select_auc["weighted_average"])
                    or select_auc["stacking"]
                    > select_auc["weighted_average"]):
                strategy = "stacking"

        hold_scores = _branch_scores(candidate, arrays, hold)
        candidate.update({
            "weights": blend_w,
            "strategy": strategy,
            "select_auc": {k: (None if math.isnan(v) else round(v, 4))
                           for k, v in select_auc.items()},
            "trained_on": n_train,
            "label_noise": label_noise_seed is not None,
            "holdout": {
                "y": arrays["y"][hold],
                "as_served": arrays["score"][hold],
                "candidate": blend_scores(hold_scores, blend_w, strategy),
                "n": n - (n_train + n_sel),
            },
        })
        return candidate

    def _train_lstm(self, arrays, tr: slice, y_tr: np.ndarray):
        import jax
        import jax.numpy as jnp
        import optax

        from realtime_fraud_detection_tpu.models.lstm import (
            init_lstm_params,
            lstm_logits,
        )
        from realtime_fraud_detection_tpu.training.neural import NeuralTrainer

        pos_w = float((1.0 - y_tr.mean()) / max(float(y_tr.mean()), 1e-6))
        params = init_lstm_params(jax.random.PRNGKey(self.seed),
                                  arrays["x"].shape[-1], self.neural_hidden)

        def loss(p, inputs, y):
            seq, length = inputs
            per = optax.sigmoid_binary_cross_entropy(
                lstm_logits(p, seq, length), y)
            return (per * jnp.where(y > 0.5, pos_w, 1.0)).mean()

        return NeuralTrainer(epochs=self.neural_epochs,
                             seed=self.seed).train(
            params, loss,
            (np.clip(arrays["history"][tr], -10, 10),
             arrays["history_len"][tr]), y_tr)


@dataclasses.dataclass
class PromotionGate:
    """Non-regression A/B gate on the held-out most-recent labels."""

    auc_margin: float = 0.0        # candidate must beat served AUC by this
    recall_tolerance: float = 0.02  # allowed recall give-back at threshold
    min_positives: int = 12
    operating_threshold: float = 0.5

    def evaluate(self, candidate: Mapping[str, Any]) -> Dict[str, Any]:
        hold = candidate["holdout"]
        y = np.asarray(hold["y"], np.float64)
        served = np.asarray(hold["as_served"], np.float64)
        cand = np.asarray(hold["candidate"], np.float64)
        pos = y > 0.5
        n_pos = int(pos.sum())
        verdict: Dict[str, Any] = {
            "type": "gate_verdict",
            "holdout_n": int(len(y)),
            "holdout_positives": n_pos,
            "strategy": candidate.get("strategy"),
        }
        if n_pos < self.min_positives:
            verdict.update(passed=False,
                           reason=f"insufficient labeled fraud in holdout "
                                  f"({n_pos} < {self.min_positives})")
            return verdict
        auc_served = sliding_auc(y, served)
        auc_cand = sliding_auc(y, cand)

        def recall(s):
            flag = s >= self.operating_threshold
            return float((flag & pos).sum()) / n_pos

        rec_served, rec_cand = recall(served), recall(cand)
        verdict.update(
            auc_as_served=round(auc_served, 4),
            auc_candidate=round(auc_cand, 4),
            recall_as_served=round(rec_served, 4),
            recall_candidate=round(rec_cand, 4),
        )
        if math.isnan(auc_cand) or auc_cand < auc_served + self.auc_margin:
            verdict.update(passed=False, reason="auc_regression")
            return verdict
        if rec_cand < rec_served - self.recall_tolerance:
            verdict.update(passed=False, reason="recall_regression")
            return verdict
        verdict.update(passed=True, reason="non_regression")
        return verdict

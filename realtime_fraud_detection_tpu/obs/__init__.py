"""Observability plane: metrics, structured logs, profiling, drift detection.

TPU-native replacement for the reference's L5 (SURVEY.md §5.1/§5.5): the
Prometheus registry in metrics.py:62-124, the dictConfig logging in
logging_config.py:11-93, coarse timing (ensemble_predictor.py:185-215), and
the configured-but-unimplemented drift detection (config.py:110-116).
"""

from realtime_fraud_detection_tpu.obs.drift import (
    DriftConfig,
    DriftReport,
    FeatureDriftMonitor,
)
from realtime_fraud_detection_tpu.obs.logs import (
    JsonFormatter,
    log_batch_scored,
    log_model_event,
    log_prediction_result,
    setup_logging,
)
from realtime_fraud_detection_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsCollector,
    Registry,
)
from realtime_fraud_detection_tpu.obs.profiling import (
    SpanTimer,
    annotate,
    device_trace,
)
from realtime_fraud_detection_tpu.obs.tracing import (
    SloTracker,
    TraceBatch,
    TraceContext,
    Tracer,
)

__all__ = [
    "Counter",
    "DriftConfig",
    "DriftReport",
    "FeatureDriftMonitor",
    "Gauge",
    "Histogram",
    "JsonFormatter",
    "MetricsCollector",
    "Registry",
    "SloTracker",
    "SpanTimer",
    "TraceBatch",
    "TraceContext",
    "Tracer",
    "annotate",
    "device_trace",
    "log_batch_scored",
    "log_model_event",
    "log_prediction_result",
    "setup_logging",
]

"""Deterministic tracing drill: prove the plane on a virtual clock.

Drives the REAL stream path — MicrobatchAssembler → StreamJob.dispatch_batch/
complete_batch → tracing plane → QoS SLO gate → fan-out — with the two
substitutions every drill in this repo makes (qos/drill.py, feedback/drill.py):
time is a virtual clock, and the device is a deterministic stand-in scorer
whose per-stage costs are exact virtual durations. That makes the drill
reproducible bit-for-bit on any CPU, and lets it INJECT a slow stage:

- a slow-assembly run must be attributed to ``assemble`` by the
  critical-path analyzer (``Tracer.breakdown``),
- a slow-device run to ``device_wait``, with the SLO burn rate spiking
  over the threshold (the injected violation), engaging the QoS gate, and
  recovering once the violation clears,
- FIFO order and shed decisions must be IDENTICAL with tracing on vs off
  (the plane observes, never perturbs),
- the wall-clock overhead of the tracing plane itself must stay under the
  pinned per-transaction bound (and the disabled path under an even
  tighter one — the measured no-op contract).

Used by ``rtfd trace-drill`` (final stdout line: a compact <2 KB JSON
verdict, the bench.py convention) and smoke-tested in tier-1.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.obs.tracing import Tracer
from realtime_fraud_detection_tpu.utils.config import (
    QosSettings,
    TracingSettings,
)

__all__ = ["TraceDrillConfig", "run_trace_drill", "compact_trace_summary"]


@dataclasses.dataclass
class TraceDrillConfig:
    seed: int = 7
    max_batch: int = 64
    max_delay_ms: float = 5.0
    bursts_per_phase: int = 24
    # injected per-batch virtual stage costs (ms)
    fast_ms: float = 1.0
    slow_assemble_ms: float = 12.0
    slow_device_ms: float = 30.0
    pack_ms: float = 0.2
    dispatch_ms: float = 0.2
    finalize_ms: float = 0.3
    per_txn_us: float = 5.0
    # SLO objective + drill-scale windows (virtual seconds)
    objective_ms: float = 20.0
    slo_fast_window_s: float = 0.4
    slo_slow_window_s: float = 1.6
    slo_bucket_s: float = 0.02
    slo_burn_threshold: float = 2.0
    # wall-clock overhead pins: the enabled plane per scored txn, and the
    # disabled fast path (which must be near-free)
    overhead_txns: int = 4096
    overhead_bound_us: float = 75.0
    noop_bound_us: float = 5.0

    @staticmethod
    def fast() -> "TraceDrillConfig":
        return TraceDrillConfig(bursts_per_phase=8, overhead_txns=1536)


class _NoCache:
    """The drill generates unique transaction ids; dedupe never hits."""

    def get_transaction(self, txn_id, now=None):
        return None


class _DrillPending:
    __slots__ = ("records", "n", "features", "done_at", "trace", "cost_s")

    def __init__(self, records, done_at, trace, cost_s):
        self.records = list(records)
        self.n = len(self.records)
        self.features = None
        self.done_at = done_at
        self.trace = trace
        self.cost_s = cost_s


class TraceDrillScorer:
    """Deterministic FraudScorer stand-in with injectable stage costs.

    Advances the shared virtual clock through assemble/pack/dispatch on
    ``dispatch`` and through the device wait + finalize on ``finalize``,
    making the SAME trace marks the real scorer makes — the clock
    advances are unconditional, so traced and untraced runs follow
    identical virtual timelines (the FIFO/shed-equality pin depends on
    it). The QoS ladder's rungs genuinely buy device capacity
    (``SPEEDUP``), so the SLO gate closes a real control loop.
    """

    SPEEDUP = (1.0, 2.0, 4.0, 8.0)

    def __init__(self, clock: List[float], cfg: TraceDrillConfig):
        self.clock = clock
        self.cfg = cfg
        self.assemble_ms = cfg.fast_ms
        self.device_ms = cfg.fast_ms
        self.model_valid = np.ones(5, bool)
        self.txn_cache = _NoCache()
        self.qos_level = 0
        self.max_level_seen = 0     # did the gate actually degrade us?
        self._qos_rules_only = False

    def set_degradation(self, mask, rules_only: bool = False,
                        level: int = 0) -> None:
        self.qos_level = int(level)
        self.max_level_seen = max(self.max_level_seen, self.qos_level)
        self._qos_rules_only = bool(rules_only)

    def batch_cost_s(self, n: int) -> float:
        c = self.cfg
        host = (self.assemble_ms + c.pack_ms + c.dispatch_ms
                + n * c.per_txn_us / 1e3)
        dev = self.device_ms / self.SPEEDUP[self.qos_level]
        return (host + dev + c.finalize_ms) / 1e3

    def dispatch(self, records, now: Optional[float] = None,
                 trace: Optional[Any] = None) -> _DrillPending:
        c = self.cfg
        n = len(records)
        if trace is not None:
            trace.mark("assemble")
        self.clock[0] += (self.assemble_ms + n * c.per_txn_us / 1e3) / 1e3
        if trace is not None:
            trace.mark("pack")
        self.clock[0] += c.pack_ms / 1e3
        if trace is not None:
            trace.mark("dispatch")
        self.clock[0] += c.dispatch_ms / 1e3
        if trace is not None:
            trace.mark("device_wait")
        dev_s = (self.device_ms / self.SPEEDUP[self.qos_level]) / 1e3
        return _DrillPending(records, self.clock[0] + dev_s, trace,
                             self.batch_cost_s(n))

    def finalize(self, pending: _DrillPending,
                 now: Optional[float] = None, lock=None) -> List[Dict]:
        self.clock[0] = max(self.clock[0], pending.done_at)
        if pending.trace is not None:
            pending.trace.mark("finalize")
        self.clock[0] += self.cfg.finalize_ms / 1e3
        results = []
        for r in pending.records:
            tid = str(r.get("transaction_id", ""))
            score = (zlib.crc32(tid.encode()) % 650) / 1000.0
            results.append({
                "transaction_id": tid,
                "fraud_probability": score,
                "fraud_score": score,
                "risk_level": "LOW" if score < 0.3 else "MEDIUM",
                "decision": "APPROVE" if score < 0.6
                            else "APPROVE_WITH_MONITORING",
                "model_predictions": {},
                "confidence": 0.9,
                "processing_time_ms": pending.cost_s * 1e3
                                      / max(pending.n, 1),
                "explanation": {"drill": True,
                                "ladder_level": self.qos_level},
            })
        return results


def _burst_arrivals(cfg: TraceDrillConfig, t0: float, gap_s: float,
                    prefix: str, amount_fn=None
                    ) -> List[Tuple[float, Dict[str, Any]]]:
    """``bursts_per_phase`` bursts of exactly ``max_batch`` records, one
    burst per virtual instant: each burst closes one full (size-triggered)
    microbatch, so per-stage costs are deterministic and no backlog forms
    unless a phase injects one."""
    arrivals = []
    i = 0
    for b in range(cfg.bursts_per_phase):
        ts = t0 + b * gap_s
        for _ in range(cfg.max_batch):
            amount = amount_fn(i) if amount_fn is not None else 60.0
            arrivals.append((ts, {
                "transaction_id": f"{prefix}-{i}",
                "user_id": f"u{i % 97}",
                "merchant_id": f"m{i % 31}",
                "amount": amount,
                "timestamp": str(ts),
            }))
            i += 1
    return arrivals


def _make_job(clock, scorer, tracer, qos_plane, cfg: TraceDrillConfig):
    from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
    from realtime_fraud_detection_tpu.stream.microbatch import (
        MicrobatchAssembler,
    )
    from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker

    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=cfg.max_batch, max_delay_ms=cfg.max_delay_ms,
        emit_features=False, emit_enriched=False,
        qos=qos_plane, tracing=tracer))
    job.assembler = MicrobatchAssembler(
        job.consumer, max_batch=cfg.max_batch,
        max_delay_ms=cfg.max_delay_ms, clock=lambda: clock[0])
    return broker, job


def _drive(clock, broker, job, arrivals) -> None:
    from realtime_fraud_detection_tpu.stream import topics as T

    next_i = 0
    idle_step = 0.001
    while True:
        while next_i < len(arrivals) and arrivals[next_i][0] <= clock[0]:
            ts, txn = arrivals[next_i]
            broker.produce(T.TRANSACTIONS, txn, key=txn["user_id"],
                           timestamp=ts)
            next_i += 1
        batch = job.assembler.next_batch(block=False)
        if not batch and next_i >= len(arrivals):
            batch = job.assembler.flush()
        if batch:
            ctx = job.dispatch_batch(batch, now=clock[0])
            if ctx is not None:
                job.complete_batch(ctx, now=clock[0])
            continue
        if next_i >= len(arrivals) and job.consumer.lag() == 0:
            return
        clock[0] = (max(clock[0] + idle_step, arrivals[next_i][0])
                    if next_i < len(arrivals) else clock[0] + idle_step)


def _tracing_settings(cfg: TraceDrillConfig) -> TracingSettings:
    return TracingSettings(
        enabled=True, ring_size=8192, slowest_n=16,
        slo_objective_ms=cfg.objective_ms,
        slo_fast_window_s=cfg.slo_fast_window_s,
        slo_slow_window_s=cfg.slo_slow_window_s,
        slo_bucket_s=cfg.slo_bucket_s,
        slo_burn_threshold=cfg.slo_burn_threshold,
        slo_gate_patience=2, slo_gate_up_patience=4)


def _measure_overhead(cfg: TraceDrillConfig) -> Dict[str, float]:
    """Wall-clock cost of the tracing plane itself, per transaction:
    begin + batch + the five batch marks + finish, at the drill's batch
    size — exactly the per-batch work the hot path pays. The disabled
    path runs the identical loop against an off tracer (every call
    returns None immediately)."""
    def loop(tracer: Tracer, n_txns: int) -> float:
        bs = cfg.max_batch
        # rtfd-lint: allow[wall-clock] measures real host overhead (the drill's pinned bound)
        t0 = time.perf_counter()
        done = 0
        i = 0
        while done < n_txns:
            ctxs = [tracer.begin(f"oh-{i + k}") for k in range(bs)]
            i += bs
            tb = tracer.batch(ctxs, batch_size=bs)
            if tb is not None:
                for s in ("assemble", "pack", "dispatch", "device_wait",
                          "finalize"):
                    tb.mark(s)
            tracer.finish_batch(tb)
            done += bs
        # rtfd-lint: allow[wall-clock] measures real host overhead (the drill's pinned bound)
        return (time.perf_counter() - t0) / done * 1e6

    on = Tracer(_tracing_settings(cfg))
    off = Tracer(dataclasses.replace(_tracing_settings(cfg), enabled=False))
    # best of 3: the bound pins the plane's cost, not scheduler noise
    on_us = min(loop(on, cfg.overhead_txns) for _ in range(3))
    off_us = min(loop(off, cfg.overhead_txns) for _ in range(3))
    return {"enabled_us_per_txn": round(on_us, 3),
            "disabled_us_per_txn": round(off_us, 4),
            "bound_us": cfg.overhead_bound_us,
            "noop_bound_us": cfg.noop_bound_us}


def run_trace_drill(cfg: Optional[TraceDrillConfig] = None) -> Dict[str, Any]:
    from realtime_fraud_detection_tpu.qos import QosPlane
    from realtime_fraud_detection_tpu.stream import topics as T

    cfg = cfg or TraceDrillConfig()
    clock = [0.0]
    tracer = Tracer(_tracing_settings(cfg), clock=lambda: clock[0])
    qos = QosPlane(QosSettings(enabled=True, budget_ms=cfg.objective_ms,
                               ladder_high_backlog=1e9,   # gate drives, not
                               ladder_low_backlog=1e8))   # the backlog signal
    scorer = TraceDrillScorer(clock, cfg)
    summary: Dict[str, Any] = {"config": dataclasses.asdict(cfg)}

    def run_phase(name: str, assemble_ms: float, device_ms: float,
                  gap_s: float) -> Dict[str, Any]:
        scorer.assemble_ms = assemble_ms
        scorer.device_ms = device_ms
        scorer.max_level_seen = scorer.qos_level
        tracer.reset()      # fresh attribution window; SLO history persists
        broker, job = _make_job(clock, scorer, tracer, qos, cfg)
        t_start = clock[0]
        arrivals = _burst_arrivals(cfg, clock[0] + 0.01, gap_s, name)
        _drive(clock, broker, job, arrivals)
        bd = tracer.breakdown()
        # peak burn over the phase, reconstructed from the retained SLO
        # buckets (the gate may have already degraded the scorer and let
        # the burn decay by phase end — the PEAK is what "reacted" means)
        burn_peak = 0.0
        t = t_start
        while t <= clock[0] + cfg.slo_bucket_s:
            burn_peak = max(burn_peak, tracer.slo.burn_rate(
                cfg.slo_fast_window_s, now=t))
            t += cfg.slo_bucket_s
        return {
            "scored": job.counters["scored"],
            "breakdown_p99": bd["quantiles"].get("p99", {}),
            "dominant_stage": bd["quantiles"].get("p99", {}).get(
                "dominant_stage"),
            "burn_fast": round(
                tracer.slo.burn_rate(cfg.slo_fast_window_s), 3),
            "burn_peak": round(burn_peak, 3),
            "gate_engaged": qos.slo_engaged,
            "max_degradation_level": scorer.max_level_seen,
            "traces_recorded": len(tracer.traces()),
        }

    # phase 1: injected slow assembly — analyzer must name `assemble`
    gap_slow_a = (cfg.slow_assemble_ms + cfg.fast_ms + 5.0) / 1e3 * 1.5
    phase_a = run_phase("slowasm", cfg.slow_assemble_ms, cfg.fast_ms,
                        gap_slow_a)
    summary["slow_assembly"] = phase_a

    # phase 2: injected slow device — analyzer must name `device_wait`,
    # and every e2e blows the objective: the burn rate must spike over
    # the threshold and engage the QoS gate
    gap_slow_d = (cfg.slow_device_ms + cfg.fast_ms + 5.0) / 1e3 * 1.5
    phase_d = run_phase("slowdev", cfg.fast_ms, cfg.slow_device_ms,
                        gap_slow_d)
    summary["slow_device"] = phase_d

    # phase 3: violation cleared — fresh fast traffic, then let the fast
    # window age out; the burn rate must fall back under the threshold
    # and the gate must disengage (the run loops feed the gate once per
    # batch; the drill's tail is that loop made explicit)
    phase_r = run_phase("recover", cfg.fast_ms, cfg.fast_ms, 0.01)
    clock[0] += cfg.slo_fast_window_s + cfg.slo_bucket_s
    recovery_obs = 0
    while qos.slo_engaged and recovery_obs < 32:
        qos.observe_slo_burn(
            tracer.slo.burn_rate(cfg.slo_fast_window_s),
            threshold=cfg.slo_burn_threshold, patience=2, up_patience=4)
        recovery_obs += 1
    burn_final = tracer.slo.burn_rate(cfg.slo_fast_window_s)
    summary["recovery"] = {**phase_r,
                           "burn_final": round(burn_final, 3),
                           "recovery_observations": recovery_obs,
                           "gate_engaged_final": qos.slo_engaged}
    summary["slo"] = tracer.slo.snapshot()

    # phase 4: FIFO + shed equality, traced vs untraced — identical
    # arrival schedule, identical admission-limited QoS plane, fresh
    # virtual clocks; the predictions topic must read back identically
    def shed_run(traced: bool) -> Tuple[List[tuple], set, int]:
        run_clock = [0.0]
        run_scorer = TraceDrillScorer(run_clock, cfg)
        run_scorer.assemble_ms = cfg.fast_ms
        run_scorer.device_ms = cfg.fast_ms
        capacity = cfg.max_batch / run_scorer.batch_cost_s(cfg.max_batch)
        run_qos = QosPlane(QosSettings(
            enabled=True, budget_ms=cfg.objective_ms,
            admission_rate=capacity * 0.25,
            admission_burst=cfg.max_batch * 1.5))
        run_tracer = (Tracer(_tracing_settings(cfg),
                             clock=lambda: run_clock[0])
                      if traced else None)
        broker, job = _make_job(run_clock, run_scorer, run_tracer,
                                run_qos, cfg)

        def amount_fn(i: int) -> float:
            return (1000.0, 60.0, 5.0)[(0 if i % 10 < 2 else
                                        1 if i % 10 < 7 else 2)]

        arrivals = _burst_arrivals(cfg, 0.01, 0.01, "shed", amount_fn)
        _drive(run_clock, broker, job, arrivals)
        preds = broker.consumer([T.PREDICTIONS], "check").poll(
            len(arrivals) + 10)
        seq = [(str(r.value["transaction_id"]),
                round(float(r.value["fraud_score"]), 6)) for r in preds]
        shed_ids = {str(r.value["transaction_id"]) for r in preds
                    if (r.value.get("explanation") or {}).get("shed")}
        return seq, shed_ids, job.counters["shed"]

    seq_off, shed_off, n_shed_off = shed_run(traced=False)
    seq_on, shed_on, n_shed_on = shed_run(traced=True)
    summary["fifo_shed"] = {
        "emitted": len(seq_on),
        "shed_traced": n_shed_on,
        "shed_untraced": n_shed_off,
        "fifo_identical": seq_on == seq_off,
        "shed_identical": shed_on == shed_off and n_shed_on == n_shed_off,
    }

    # phase 5: the tracing plane's own wall-clock cost per transaction
    summary["overhead"] = _measure_overhead(cfg)

    checks = {
        "slow_assembly_attributed":
            phase_a["dominant_stage"] == "assemble",
        "slow_device_attributed":
            phase_d["dominant_stage"] == "device_wait",
        "slo_burn_reacted":
            phase_d["burn_peak"] > cfg.slo_burn_threshold
            and phase_d["max_degradation_level"] >= 1,
        "slo_recovered":
            not qos.slo_engaged
            and burn_final <= cfg.slo_burn_threshold,
        "fifo_identical": summary["fifo_shed"]["fifo_identical"],
        "shed_identical": summary["fifo_shed"]["shed_identical"],
        "sheds_nonzero": n_shed_on > 0,
        "overhead_under_bound":
            summary["overhead"]["enabled_us_per_txn"]
            < cfg.overhead_bound_us,
        "noop_under_bound":
            summary["overhead"]["disabled_us_per_txn"]
            < cfg.noop_bound_us,
    }
    summary["checks"] = checks
    summary["passed"] = all(checks.values())
    return summary


def compact_trace_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line verdict (bench.py convention)."""
    oh = summary["overhead"]
    return {
        "drill": "trace",
        "passed": summary["passed"],
        "checks": summary["checks"],
        "dominant": {
            "slow_assembly": summary["slow_assembly"]["dominant_stage"],
            "slow_device": summary["slow_device"]["dominant_stage"],
        },
        "burn": {
            "slow_device_peak": summary["slow_device"]["burn_peak"],
            "final": summary["recovery"]["burn_final"],
            "threshold": summary["config"]["slo_burn_threshold"],
        },
        "shed": {
            "traced": summary["fifo_shed"]["shed_traced"],
            "untraced": summary["fifo_shed"]["shed_untraced"],
        },
        "overhead_us_per_txn": oh["enabled_us_per_txn"],
        "noop_us_per_txn": oh["disabled_us_per_txn"],
        "bound_us": oh["bound_us"],
    }

"""Distributed observability drill: prove the fleet tracing plane end to end.

``rtfd obs-drill`` is the acceptance artifact for the fleet observability
plane — the thirteenth lockwatch drill. One seeded timeline drives ≥ 2
REAL OS worker processes (``rtfd cluster-worker`` over the TCP netbroker,
the PR 12 process fleet) with the distributed tracing plane live:

1. **cross-process trace propagation**: the driver plays the ingress
   edge — every produced record carries a wire trace carrier (trace id +
   ``ingress`` origin + produce wall stamp); workers re-hydrate it at
   consume time, so each stitched trace spans ingest → broker transit
   (producer stamp vs consume stamp — nonzero by construction) → the
   consuming worker's queue/assemble/pack/dispatch/device_wait → emit,
   with remote ``GraphFetchClient`` RPCs to the OTHER worker's fetch
   server recorded as ``remote_fetch`` child spans (server-side share in
   the reply frame).
2. **carrier loss under a fault window**: inside the drill's netfault
   window the ingress stops stamping carriers (the lossy-edge model)
   while one worker's broker link is latency-degraded — every un-carried
   record degrades to a counted fresh LOCAL root
   (``trace_carrier_lost``), never a gap, and the count is pinned
   EXACTLY against the schedule.
3. **fleet metrics + critical path**: workers stream counter-delta
   ``metrics`` events the coordinator folds (seq-deduped) into fleet
   sums pinned EXACTLY equal to the bye-frame counters; one worker runs
   with an inflated device cost, and the stitched fleet breakdown must
   attribute the p99 tail to THAT worker's ``device_wait``.

Checked contract (fast AND full): real distinct processes; stitched
traces cross ≥ 2 processes with nonzero broker transit and a remote
graph-fetch child span; carrier losses exactly equal the stripped
count and adoptions exactly equal the carried count; no trace attaches
to two workers' batches; the tracer never wedges (per-worker started ==
closed, graceful byes); fleet counter sums exactly equal the per-worker
byes; the slow worker owns the p99 tail with ``device_wait`` dominant;
the merged Chrome export carries one named track per process and one
broker-transit flow arrow per stitched trace; traced-vs-untraced
makespan ratio under the pinned bound (wall timings reported, NEVER
digested); and a second fully fresh traced run producing the same
sha256 digest over the content invariants.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.chaos.faults import ChaosPlan, FaultWindow
from realtime_fraud_detection_tpu.cluster.procfleet import ProcessFleet
from realtime_fraud_detection_tpu.obs.fleetmetrics import FleetTraceStore
from realtime_fraud_detection_tpu.obs.tracing import make_carrier
from realtime_fraud_detection_tpu.stream import topics as T

__all__ = ["ObsDrillConfig", "run_obs_drill", "compact_obs_summary",
           "build_obs_schedule"]


def _wall() -> float:
    # rtfd-lint: allow[wall-clock] real OS processes over real TCP are paced on the wall clock by definition
    return time.time()


@dataclasses.dataclass
class ObsDrillConfig:
    """Drill sizes. Defaults = the full drill; ``fast()`` = the tier-1
    smoke — same shape (≥ 2 processes, carrier-strip window, slow-worker
    attribution, both traced and untraced runs), compressed timeline."""

    seed: int = 7
    n_partitions: int = 12          # the transactions topic's contract
    n_workers: int = 3
    num_users: int = 40_000
    num_merchants: int = 400
    hot_users: int = 800
    hot_frac: float = 0.35
    # offered load: constant-rate seeded Poisson arrivals
    duration_s: float = 14.0
    tps: float = 170.0
    # the netfault window, relative to the announced epoch: the ingress
    # stops stamping carriers (deterministic, schedule-counted) while the
    # degrade target's broker link gains per-frame latency
    fault_start: float = 5.0
    fault_end: float = 8.0
    degrade_latency_s: float = 0.004
    degrade_jitter_s: float = 0.0015
    # every Nth carried record arrives with one 421-redirect hop already
    # on its ledger (rh=1 + accumulated redirect seconds) — the stitched
    # rows must book them under redirect_hops, pinned exactly
    redirect_every: int = 50
    redirect_s: float = 0.0005
    # worker knobs (wall-time service-cost model, paid for real); the
    # LAST worker runs with slow_base_ms instead — the p99-attribution
    # target whose device_wait must dominate the fleet tail
    batch: int = 48
    max_delay_ms: float = 15.0
    checkpoint_every: int = 6
    base_ms: float = 4.0
    per_txn_ms: float = 0.4
    slow_base_ms: float = 110.0
    heartbeat_s: float = 0.3
    # graph-fetch plane: per-batch remote neighbor resolution knobs
    fetch_ids: int = 8
    fetch_deadline_ms: float = 50.0
    # traced-vs-untraced wall bound (tracing + carriers + fetch spans
    # must stay a small tax on an identical workload)
    overhead_bound: float = 1.5
    ring_size: int = 65536
    ack_timeout_s: float = 120.0
    drain_timeout_s: float = 150.0
    # second, fully fresh traced run compared digest-for-digest
    replay_check: bool = True
    # directory to write per-worker flight-recorder ring dumps into
    # ({worker, pid, traces} JSON — the ``rtfd trace-export --merge``
    # input shape); empty = don't write
    rings_out: str = ""

    @classmethod
    def fast(cls) -> "ObsDrillConfig":
        """Tier-1 smoke: 2 processes, same windows and checks, timeline
        and id space shrink."""
        return cls(n_workers=2, num_users=8_000, num_merchants=150,
                   hot_users=300, duration_s=6.0, tps=110.0,
                   fault_start=2.5, fault_end=4.0,
                   slow_base_ms=95.0, heartbeat_s=0.25)

    def validate(self) -> None:
        if self.n_workers < 2:
            raise ValueError("obs drill needs >= 2 worker processes "
                             "(a stitched trace must cross a boundary)")
        if not self.duration_s > self.fault_end > self.fault_start >= 0:
            raise ValueError(
                f"fault window [{self.fault_start}, {self.fault_end}) "
                f"must sit inside the {self.duration_s}s timeline")
        if self.redirect_every < 2 or self.overhead_bound <= 1.0:
            raise ValueError("redirect_every >= 2 and overhead_bound > 1 "
                             "required")

    def windows(self) -> List[FaultWindow]:
        return [FaultWindow("carrier_strip", "netfault",
                            self.fault_start, self.fault_end)]


def build_obs_schedule(cfg: ObsDrillConfig,
                       ) -> List[Tuple[float, Dict[str, Any]]]:
    """Seeded (event_ts, txn) timeline — the partition drill's synthetic
    stream shape (hot cohort + long tail), schema-complete."""
    rng = np.random.default_rng(cfg.seed)
    n_est = int(cfg.tps * cfg.duration_s * 1.3) + 64
    gaps = rng.exponential(1.0 / cfg.tps, size=n_est)
    times = np.cumsum(gaps)
    times = times[times < cfg.duration_s]
    n = len(times)
    hot_pool = rng.integers(0, cfg.num_users, size=max(1, cfg.hot_users))
    take_hot = rng.random(n) < cfg.hot_frac
    uid_idx = np.where(
        take_hot,
        hot_pool[rng.integers(0, len(hot_pool), size=n)],
        rng.integers(0, cfg.num_users, size=n))
    mid_idx = rng.integers(0, cfg.num_merchants, size=n)
    amounts = np.round(rng.lognormal(3.2, 0.9, size=n), 2)
    sched: List[Tuple[float, Dict[str, Any]]] = []
    for i in range(n):
        t = round(float(times[i]), 9)
        sched.append((t, {
            "transaction_id": f"otx_{i}",
            "user_id": f"user_{int(uid_idx[i])}",
            "merchant_id": f"m_{int(mid_idx[i])}",
            "amount": float(amounts[i]),
            "payment_method": "card",
            "event_ts": t,
        }))
    return sched


def _carrier_plan(cfg: ObsDrillConfig,
                  sched: List[Tuple[float, Dict[str, Any]]],
                  ) -> Dict[int, str]:
    """Pure function of (config, schedule): which schedule indices carry
    a trace carrier ("carried"), carry one with a redirect ledger
    ("redirect"), or are stripped inside the fault window ("stripped").
    The drill's exact carrier-loss pin comes from here."""
    plan: Dict[int, str] = {}
    carried = 0
    for i, (t_ev, _) in enumerate(sched):
        if cfg.fault_start <= t_ev < cfg.fault_end:
            plan[i] = "stripped"
            continue
        carried += 1
        plan[i] = "redirect" if carried % cfg.redirect_every == 0 \
            else "carried"
    return plan


# ------------------------------------------------------------- fleet run


def _run_obs_fleet(cfg: ObsDrillConfig,
                   sched: List[Tuple[float, Dict[str, Any]]],
                   plan: Dict[int, str],
                   traced: bool) -> Dict[str, Any]:
    """One fresh fleet run over the schedule: own broker + handoff +
    worker processes. ``traced=False`` runs the IDENTICAL workload
    (carriers still produced, fetch plane still live) with the workers'
    tracing plane off — the overhead-ratio baseline."""
    from realtime_fraud_detection_tpu.cluster.handoff import HandoffServer
    from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer

    ids = [f"w{i}" for i in range(cfg.n_workers)]
    slow_wid = ids[-1]
    degrade_wid = ids[0]
    broker_srv = BrokerServer(port=0).start()
    tmp = tempfile.mkdtemp(prefix="rtfd-obs-")
    handoff_srv = None
    fleet = None
    try:
        handoff_srv = HandoffServer(
            blob_dir=os.path.join(tmp, "blobs")).start()
        fetch_spec = {"edge": "user->device", "k": 4,
                      "ids": cfg.fetch_ids,
                      "deadline_ms": cfg.fetch_deadline_ms}
        worker_spec: Dict[str, Any] = {
            "batch": cfg.batch, "max_delay_ms": cfg.max_delay_ms,
            "checkpoint_every": cfg.checkpoint_every,
            "seq_len": 4, "feature_dim": 4,
            "base_ms": cfg.base_ms, "per_txn_ms": cfg.per_txn_ms,
            "heartbeat_s": cfg.heartbeat_s,
            "fetch": fetch_spec,
        }
        if traced:
            worker_spec["tracing"] = {"ring_size": cfg.ring_size}
            worker_spec["expect_carrier"] = True
        per_worker: Dict[str, Dict[str, Any]] = {
            slow_wid: {"base_ms": cfg.slow_base_ms},
        }
        per_worker.setdefault(degrade_wid, {})["netfaults"] = {
            "seed": cfg.seed, "windows": [{
                "name": "carrier_strip", "kind": "degrade",
                "t_start": cfg.fault_start, "t_end": cfg.fault_end,
                "latency_s": cfg.degrade_latency_s,
                "jitter_s": cfg.degrade_jitter_s,
            }]}
        fleet = ProcessFleet(
            f"127.0.0.1:{broker_srv.port}",
            f"127.0.0.1:{handoff_srv.port}",
            n_partitions=cfg.n_partitions,
            ack_timeout_s=cfg.ack_timeout_s,
            spawn_env={**os.environ, "JAX_PLATFORMS": "cpu"},
            worker_spec=worker_spec,
            per_worker_spec=per_worker)
        fleet.start(cfg.n_workers, now=0.0)
        fleet.wait_fetch_addrs(ids)
        fleet.broadcast_peers()
        chaos = ChaosPlan(cfg.windows())

        t0 = _wall()
        fleet.announce_epoch(t0)
        next_i, n = 0, len(sched)
        produced = 0
        while True:
            now_ev = _wall() - t0
            if next_i < n:
                j = next_i
                items = []
                now_wall = _wall()
                while j < n and sched[j][0] <= now_ev:
                    t_ev, txn = sched[j]
                    kind = plan[j]
                    if kind != "stripped":
                        # the ingress edge: a fresh root carrier with the
                        # PRODUCE wall stamp (consume-minus-it == the
                        # broker_transit stage); the redirect cohort
                        # arrives with one 421 hop already on the ledger
                        txn = dict(txn)
                        txn["trace_carrier"] = make_carrier(
                            f"ting-{j:08x}", origin="ingress",
                            produced_ts=now_wall,
                            hops=1 if kind == "redirect" else 0,
                            redirect_s=(cfg.redirect_s
                                        if kind == "redirect" else 0.0))
                    items.append((txn["user_id"], txn, t0 + t_ev))
                    j += 1
                if items:
                    fleet.client.produce_batch_stamped(T.TRANSACTIONS,
                                                       items)
                    produced += len(items)
                    next_i = j
            chaos.poll(now_ev)
            fleet.tick(now_ev)
            if next_i >= n and now_ev > cfg.fault_end:
                lag = fleet.client.lag(fleet.group_id, T.TRANSACTIONS)
                if lag == 0:
                    break
                if now_ev > cfg.duration_s + cfg.drain_timeout_s:
                    raise RuntimeError(f"drain timeout: lag={lag}")
            time.sleep(0.01)
        makespan = _wall() - t0

        fleet.shutdown_all(now=_wall() - t0)
        byes = fleet.all_byes()
        digests: Dict[int, str] = {}
        for bye in byes.values():
            for p, d in (bye.get("digests") or {}).items():
                digests[int(p)] = d

        # ---- predictions ledger: coverage + per-txn content ----------
        inner = broker_srv.broker
        preds: Dict[str, List[Tuple[float, str, str]]] = {}
        for p in range(inner.partitions(T.PREDICTIONS)):
            off = 0
            while True:
                recs = inner.read(T.PREDICTIONS, p, off, 4096)
                if not recs:
                    break
                off = recs[-1].offset + 1
                for r in recs:
                    v = r.value if isinstance(r.value, dict) else {}
                    ex = v.get("explanation") or {}
                    kind = ("replayed" if ex.get("replayed_from_cache")
                            else "error" if ex.get("error") else "scored")
                    preds.setdefault(str(v.get("transaction_id", "")),
                                     []).append(
                        (round(float(v.get("fraud_score", -1.0)), 6),
                         str(v.get("decision", "")), kind))
        tx_ends = inner.end_offsets(T.TRANSACTIONS)
        committed = [inner.committed(fleet.group_id, T.TRANSACTIONS, p)
                     for p in range(len(tx_ends))]

        return {
            "ids": ids,
            "slow_worker": slow_wid,
            "degrade_worker": degrade_wid,
            "produced": produced,
            "preds": preds,
            "committed": committed,
            "tx_ends": tx_ends,
            "digests": digests,
            "byes": byes,
            "fleet_snapshot": fleet.snapshot(),
            "fleet_metrics": fleet.fleet_metrics.snapshot(),
            "fleet_metrics_render": fleet.fleet_metrics.render(),
            "makespan_s": round(makespan, 3),
            "chaos": chaos.snapshot(now=makespan),
        }
    finally:
        if fleet is not None:
            fleet.terminate()
        if handoff_srv is not None:
            handoff_srv.stop()
        broker_srv.stop()


def _stitch(out: Dict[str, Any], cfg: ObsDrillConfig) -> FleetTraceStore:
    store = FleetTraceStore(ring_size=max(cfg.ring_size * cfg.n_workers,
                                          1024))
    for wid, bye in sorted(out["byes"].items()):
        store.ingest(wid, bye.get("trace_ring") or [],
                     pid=int(bye.get("pid", 0) or 0))
    return store


def _traced_digest(cfg: ObsDrillConfig, out: Dict[str, Any],
                   carrier_ledger: Dict[str, int]) -> str:
    """sha256 over the run's CONTENT invariants — schedule-pinned carrier
    accounting, per-transaction scores, offsets, state digests. Wall
    timings (e2e, stage ms, makespans) are reported, never digested."""
    return hashlib.sha256(json.dumps({
        "produced": out["produced"],
        "preds": sorted((tid, sorted({(s, d) for s, d, _ in e}))
                        for tid, e in out["preds"].items()),
        "committed": out["committed"],
        "state": sorted((p, d) for p, d in out["digests"].items()),
        "carriers": carrier_ledger,
        "windows": [[w.name, w.t_start, w.t_end] for w in cfg.windows()],
    }, sort_keys=True).encode()).hexdigest()


def _analyze_traced(cfg: ObsDrillConfig, out: Dict[str, Any],
                    plan: Dict[int, str]) -> Dict[str, Any]:
    store = _stitch(out, cfg)
    rows = store.rows()
    stitch = store.stitch_stats()
    breakdown = store.breakdown()
    export = store.export_chrome_trace()

    stripped = sum(1 for k in plan.values() if k == "stripped")
    redirects = sum(1 for k in plan.values() if k == "redirect")
    carried = len(plan) - stripped

    lost_total = adopted_total = 0
    wedged: List[str] = []
    for wid, bye in sorted(out["byes"].items()):
        tc = bye.get("tracer_counters") or {}
        lost_total += int(tc.get("carrier_lost", 0))
        adopted_total += int(tc.get("carrier_adopted", 0))
        closed = sum(int(tc.get(k, 0)) for k in
                     ("completed", "shed", "errors", "cached"))
        if int(tc.get("started", 0)) != closed:
            wedged.append(wid)

    # no cross-attachment: a trace id consumed by one worker's batches
    # must never surface in another worker's ring
    owner: Dict[str, str] = {}
    cross_attached = 0
    for r in rows:
        tid, w = str(r.get("trace_id")), str(r.get("worker"))
        if owner.setdefault(tid, w) != w:
            cross_attached += 1

    redirect_rows = sum(
        1 for r in rows if "redirect_hops" in (r.get("stages") or {}))
    workers_with_stitched = sorted(
        {str(r.get("worker")) for r in rows
         if r.get("origin") == "ingress"})
    flow_starts = sum(1 for e in export["traceEvents"]
                      if e.get("ph") == "s")
    track_names = [e["args"]["name"] for e in export["traceEvents"]
                   if e.get("ph") == "M"]

    # fleet-metrics exactness: the coordinator's streamed (delta, seq)
    # fold must EQUAL each worker's bye-frame counters, key for key
    fm_workers = (out["fleet_metrics"] or {}).get("workers") or {}
    metrics_exact = True
    metrics_diffs: List[str] = []
    for wid, bye in sorted(out["byes"].items()):
        want: Dict[str, float] = {
            str(k): float(v)
            for k, v in (bye.get("counters") or {}).items()}
        for k, v in (bye.get("tracer_counters") or {}).items():
            want[f"trace_{k}"] = float(v)
        fetch = bye.get("fetch") or {}
        if fetch:
            want["remote_fetch"] = float(fetch.get("remote_fetch_total", 0))
            want["remote_fetch_errors"] = float(
                fetch.get("fetch_error_total", 0))
        got = {str(k): float(v)
               for k, v in (fm_workers.get(wid) or {}).items()}
        if got != want:
            metrics_exact = False
            metrics_diffs.append(wid)

    carrier_ledger = {"stripped": stripped, "carried": carried,
                      "redirects": redirects,
                      "lost_total": lost_total,
                      "adopted_total": adopted_total,
                      "stitched_rows": len(rows),
                      "redirect_rows": redirect_rows}
    return {
        "stitch": stitch,
        "breakdown_quantiles": breakdown.get("quantiles") or {},
        "per_worker": breakdown.get("per_worker") or {},
        "exemplars": (breakdown.get("exemplars") or [])[:4],
        "carrier_ledger": carrier_ledger,
        "wedged_workers": wedged,
        "cross_attached": cross_attached,
        "workers_with_stitched": workers_with_stitched,
        "flow_starts": flow_starts,
        "track_names": track_names,
        "metrics_exact": metrics_exact,
        "metrics_diffs": metrics_diffs,
        "digest": _traced_digest(cfg, out, carrier_ledger),
    }


# ------------------------------------------------------------------ drill


def run_obs_drill(config: Optional[ObsDrillConfig] = None,
                  fast: bool = False) -> Dict[str, Any]:
    """Run the obs drill: untraced baseline fleet, traced fleet with the
    full observability plane, stitched-trace + fleet-metrics pins, plus
    the fresh-run determinism check."""
    cfg = config or (ObsDrillConfig.fast() if fast else ObsDrillConfig())
    cfg.validate()
    sched = build_obs_schedule(cfg)
    plan = _carrier_plan(cfg, sched)

    untraced = _run_obs_fleet(cfg, sched, plan, traced=False)
    out = _run_obs_fleet(cfg, sched, plan, traced=True)
    if cfg.rings_out:
        os.makedirs(cfg.rings_out, exist_ok=True)
        for wid, bye in sorted(out["byes"].items()):
            with open(os.path.join(cfg.rings_out,
                                   f"ring_{wid}.json"), "w") as f:
                json.dump({"worker": wid,
                           "pid": int(bye.get("pid", 0) or 0),
                           "traces": bye.get("trace_ring") or []}, f)
    res = _analyze_traced(cfg, out, plan)
    ledger = res["carrier_ledger"]
    stitch = res["stitch"]

    produced_ids = {txn["transaction_id"] for _, txn in sched}
    preds = out["preds"]
    lost = len(produced_ids - set(preds))
    errors = sum(1 for emits in preds.values()
                 for _, _, kind in emits if kind == "error")

    p99 = (res["breakdown_quantiles"].get("p99") or {})
    slow = out["slow_worker"]
    slow_row = (res["per_worker"].get(slow) or {})
    transit = stitch.get("broker_transit_ms") or {}

    overhead_ratio = round(
        out["makespan_s"] / max(untraced["makespan_s"], 1e-9), 3)

    replay_identical = None
    second_digest = None
    if cfg.replay_check:
        second_out = _run_obs_fleet(cfg, sched, plan, traced=True)
        second = _analyze_traced(cfg, second_out, plan)
        second_digest = second["digest"]
        replay_identical = second_digest == res["digest"]

    pids = {st["pid"]
            for st in out["fleet_snapshot"]["workers"].values()}
    checks = {
        "processes_real": (len(pids) == cfg.n_workers
                          and os.getpid() not in pids),
        # the stitched plane: adopted traces landed on >= 2 distinct
        # worker processes, every one with a REAL produce->consume
        # transit, and remote graph-fetch child spans present
        "stitched_crosses_processes": (
            len(res["workers_with_stitched"]) >= 2
            and stitch.get("crossed_process", 0) > 0),
        "broker_transit_nonzero": (transit.get("n", 0) > 0
                                   and transit.get("p99", 0.0) > 0.0),
        "remote_fetch_spans": stitch.get("with_remote_span", 0) > 0,
        # carrier accounting pinned EXACTLY against the schedule
        "carrier_loss_exact": (ledger["stripped"] > 0
                               and ledger["lost_total"]
                               == ledger["stripped"]),
        "carrier_adopt_exact": (ledger["adopted_total"]
                                == ledger["carried"]),
        "redirects_booked": (ledger["redirects"] > 0
                             and ledger["redirect_rows"]
                             == ledger["redirects"]),
        "no_cross_attachment": res["cross_attached"] == 0,
        "tracer_never_wedged": (not res["wedged_workers"]
                                and all(b.get("graceful")
                                        for b in out["byes"].values())),
        "fleet_counters_exact": res["metrics_exact"],
        # slow-worker attribution: the inflated-cost worker owns the
        # fleet's p99 tail, and its own dominant stage is device_wait
        "slow_worker_attributed": (
            p99.get("dominant_worker") == slow
            and slow_row.get("dominant_stage") == "device_wait"),
        "export_tracks_and_flows": (
            len(res["track_names"]) >= cfg.n_workers + 1
            and res["flow_starts"] == stitch.get("crossed_process", 0)),
        "zero_lost": lost == 0,
        "zero_errors": errors == 0,
        "offsets_gap_free": out["committed"] == out["tx_ends"],
        "overhead_bounded": overhead_ratio <= cfg.overhead_bound,
    }
    if replay_identical is not None:
        checks["replay_deterministic"] = bool(replay_identical)

    summary: Dict[str, Any] = {
        "metric": "obs_drill",
        "passed": all(bool(v) for v in checks.values()),
        "checks": checks,
        "n_workers": cfg.n_workers,
        "n_partitions": cfg.n_partitions,
        "slow_worker": slow,
        "degrade_worker": out["degrade_worker"],
        "produced": out["produced"],
        "lost": lost,
        "errors": errors,
        "carriers": ledger,
        "stitch": stitch,
        "breakdown_p99": p99,
        "per_worker": res["per_worker"],
        "exemplars": res["exemplars"],
        "tracks": res["track_names"],
        "flow_arrows": res["flow_starts"],
        "fleet_metrics": out["fleet_metrics"],
        "chaos": out["chaos"],
        # wall-clock report (NEVER in the digest)
        "wall": {
            "makespan_traced_s": out["makespan_s"],
            "makespan_untraced_s": untraced["makespan_s"],
            "overhead_ratio": overhead_ratio,
            "broker_transit_ms": transit,
        },
        "replay_identical": replay_identical,
        "digest": res["digest"],
        "second_digest": second_digest,
    }
    return summary


def compact_obs_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line verdict (bench.py convention: full
    result on the preceding line, compact parseable verdict last)."""
    wall = summary.get("wall") or {}
    stitch = summary.get("stitch") or {}
    compact = {
        "metric": "obs_drill",
        "passed": summary.get("passed"),
        "checks": {k: bool(v)
                   for k, v in (summary.get("checks") or {}).items()},
        "produced": summary.get("produced"),
        "carriers": summary.get("carriers"),
        "stitch_rate": stitch.get("stitch_rate"),
        "crossed": stitch.get("crossed_process"),
        "slow_worker": summary.get("slow_worker"),
        "p99_dominant": (summary.get("breakdown_p99") or {}).get(
            "dominant_stage"),
        "overhead_ratio": wall.get("overhead_ratio"),
        "broker_transit_p99_ms": (wall.get("broker_transit_ms") or {}
                                  ).get("p99"),
        "makespan_s": wall.get("makespan_traced_s"),
        "digest": (summary.get("digest") or "")[:16],
        "summary_of": "full result JSON on the preceding stdout line",
    }
    line = json.dumps(compact, separators=(",", ":"))
    while len(line.encode()) >= 2048:
        for victim in ("checks", "carriers", "summary_of", "digest"):
            if compact.pop(victim, None) is not None:
                break
        else:
            compact = {"metric": "obs_drill",
                       "passed": summary.get("passed")}
        line = json.dumps(compact, separators=(",", ":"))
    return compact

"""Feature-distribution drift detection over the (B, 64) feature stream.

The reference *configures* drift detection but never implements it
(config.py:110-116: ``drift_detection_enabled`` / ``drift_threshold`` in the
monitoring block, consumed by nothing). This module supplies the real thing,
vectorized over whole microbatches:

- warmup: per-feature baseline via Welford mean/variance + fixed PSI bin
  edges at baseline mean ± {0.5, 1, 2}σ;
- steady state: a rolling window of per-bin counts; drift score per feature
  is the Population Stability Index between window and baseline bin masses;
- report: per-feature PSI, the worst offenders, and an overall flag against
  the configured threshold (PSI rule of thumb: <0.1 stable, >0.25 shifted).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

__all__ = ["DriftConfig", "DriftReport", "FeatureDriftMonitor"]

_EPS = 1e-6


@dataclasses.dataclass
class DriftConfig:
    num_features: int = 64
    warmup_rows: int = 2_000       # rows before the baseline freezes
    window_rows: int = 2_000       # rolling comparison window
    threshold: float = 0.25        # PSI alarm level (config.py:110-116 analog)
    min_report_rows: int = 200     # window rows required before alarming
                                   # (a near-empty window is ~one-hot per
                                   # feature and would always false-alarm)


@dataclasses.dataclass
class DriftReport:
    drifted: bool
    max_psi: float
    psi: np.ndarray                      # f32[F]
    top_features: List[int]              # worst-first indices above threshold
    rows_seen: int
    baseline_frozen: bool


class FeatureDriftMonitor:
    """Streaming PSI drift monitor; feed every scored feature batch."""

    def __init__(self, config: Optional[DriftConfig] = None):
        self.config = config or DriftConfig()
        f = self.config.num_features
        # Welford accumulators for the baseline
        self._n = 0
        self._mean = np.zeros((f,), np.float64)
        self._m2 = np.zeros((f,), np.float64)
        self._edges: Optional[np.ndarray] = None      # f64[F, 7] bin edges
        self._base_mass: Optional[np.ndarray] = None  # f64[F, 8]
        self._base_counts = np.zeros((f, 8), np.float64)
        self._warmup_buf: List[np.ndarray] = []       # rows kept to self-seed
        # ring buffer of windowed per-bin counts
        self._win_counts = np.zeros((f, 8), np.float64)
        self._win_rows = 0
        self.rows_seen = 0

    @property
    def baseline_frozen(self) -> bool:
        return self._edges is not None

    # ---------------------------------------------------------------- update
    def update(self, features: np.ndarray) -> None:
        """Ingest one (B, F) batch of extracted features."""
        x = np.asarray(features, np.float64)
        if x.ndim != 2 or x.shape[1] != self.config.num_features:
            raise ValueError(f"expected (B, {self.config.num_features}), "
                             f"got {x.shape}")
        self.rows_seen += x.shape[0]
        if not self.baseline_frozen:
            self._update_baseline(x)
            self._warmup_buf.append(x)
            if self._n >= self.config.warmup_rows:
                self._freeze()
                # the warmup sample IS the baseline distribution — binning it
                # (rather than assuming Gaussian masses) keeps near-constant
                # and skewed features from false-alarming
                self._base_counts += self._bin_counts(
                    np.concatenate(self._warmup_buf, axis=0))
                self._warmup_buf.clear()
            return
        counts = self._bin_counts(x)
        self._win_counts += counts
        self._win_rows += x.shape[0]
        # decay instead of a true ring buffer: halve when 2x over the window
        # (cheap, keeps recency without storing per-row history)
        if self._win_rows >= 2 * self.config.window_rows:
            self._win_counts *= 0.5
            self._win_rows //= 2

    def _update_baseline(self, x: np.ndarray) -> None:
        # Chan's parallel Welford merge: fold the whole batch in O(1) numpy
        # calls instead of a per-row Python loop (this runs on the scoring
        # hot path during warmup)
        m = x.shape[0]
        batch_mean = x.mean(axis=0)
        batch_m2 = ((x - batch_mean) ** 2).sum(axis=0)
        n = self._n
        delta = batch_mean - self._mean
        total = n + m
        self._mean += delta * (m / total)
        self._m2 += batch_m2 + delta ** 2 * (n * m / total)
        self._n = total

    def _freeze(self) -> None:
        std = np.sqrt(self._m2 / max(self._n - 1, 1))
        std = np.where(std < _EPS, 1.0, std)
        offsets = np.array([-2.0, -1.0, -0.5, 0.0, 0.5, 1.0, 2.0])
        self._edges = self._mean[:, None] + std[:, None] * offsets[None, :]

    def seed_baseline_counts(self, features: np.ndarray) -> None:
        """Re-bin warmup data as the baseline mass (call after freeze, or
        let steady-state updates lazily approximate it)."""
        if not self.baseline_frozen:
            raise RuntimeError("baseline not frozen yet")
        self._base_counts += self._bin_counts(np.asarray(features, np.float64))
        self._base_mass = None

    def _bin_counts(self, x: np.ndarray) -> np.ndarray:
        assert self._edges is not None
        f = x.shape[1]
        # searchsorted per feature: bin index in [0, 7]
        idx = np.empty(x.shape, np.intp)
        for j in range(f):
            idx[:, j] = np.searchsorted(self._edges[j], x[:, j])
        counts = np.zeros((f, 8), np.float64)
        for j in range(f):
            counts[j] = np.bincount(idx[:, j], minlength=8)
        return counts

    # ---------------------------------------------------------------- report
    def report(self) -> DriftReport:
        f = self.config.num_features
        if not self.baseline_frozen or self._win_rows < max(
                self.config.min_report_rows, 1):
            return DriftReport(False, 0.0, np.zeros((f,), np.float32), [],
                               self.rows_seen, self.baseline_frozen)
        if self._base_mass is None:
            base = self._base_counts
            self._base_mass = (base + _EPS) / (base + _EPS).sum(
                axis=1, keepdims=True)
        cur = (self._win_counts + _EPS) / (self._win_counts + _EPS).sum(
            axis=1, keepdims=True)
        psi = np.sum((cur - self._base_mass)
                     * np.log(cur / self._base_mass), axis=1)
        psi32 = psi.astype(np.float32)
        above = np.where(psi > self.config.threshold)[0]
        top = sorted(above.tolist(), key=lambda j: -psi[j])
        return DriftReport(
            drifted=bool(len(top) > 0),
            max_psi=float(psi.max()),
            psi=psi32,
            top_features=top,
            rows_seen=self.rows_seen,
            baseline_frozen=True,
        )

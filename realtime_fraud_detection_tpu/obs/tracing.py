"""Per-transaction tracing plane: flight recorder, tail attribution, SLO burn.

The north-star SLO (50k txn/s at p99 < 20 ms) was unverifiable from inside
the system: latency existed only as disconnected per-stage aggregates
(``FraudScorer.spans``, batcher stats, ``device_pool_*`` counters), so
"where did the p99 go" had no answer for any individual transaction. This
module gives every admitted transaction a trace context that rides the
existing flow objects through the whole pipeline —

    ingest (gateway/broker lag) → QoS admission → microbatch queue wait →
    columnar assembly → pack → device dispatch (replica id + in-flight
    depth) → device wait → finalize/fan-out (emit)

— and lands completed traces in a fixed-size ring buffer (the "flight
recorder") plus a slowest-N exemplar store kept verbatim, so the current
tail outliers are always capturable. This is the per-stage latency
accounting that arXiv:2109.09541 credits for its serving wins, and the
pipeline-stage attribution that makes overlap tuning actionable
(tf.data, arXiv:2101.12127).

Cost discipline (the plane must be admissible on the hot path):

- default-off: with no tracer attached the scoring paths pay one
  ``is None`` check per batch — the drill measures the no-op path;
- stage marks are BATCH-granular (one clock read per stage per microbatch,
  not per transaction): per-transaction state is only (trace_id, txn_id,
  admission timestamp, ingest lag);
- completion takes ONE lock per batch; the ring buffer is a bounded deque
  (O(1) append, oldest evicted) and the slowest-N store a small heap.

Clock discipline: every duration is computed within a single clock base.
Stage marks, admission timestamps, and SLO windows all read the tracer's
clock (``time.monotonic`` in production, the virtual clock in drills); the
one wall-clock quantity — broker-ingest-to-admission lag — is computed as
a wall-minus-wall delta upstream and carried as a duration, never mixed
with monotonic readings.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TRACE_STAGES",
    "TRACE_STAGE_BUCKETS_MS",
    "CARRIER_KEY",
    "make_carrier",
    "parse_carrier",
    "TraceContext",
    "TraceBatch",
    "CompletedTrace",
    "SloTracker",
    "Tracer",
    "set_log_context",
    "clear_log_context",
    "current_log_context",
]

# Canonical stage order: ``ingest`` is the gateway→produce lag,
# ``broker_transit`` the produce→consume transit (producer wall stamp in
# the carrier vs consume wall stamp — the cross-process segment),
# ``redirect_hops`` time burnt on 421 wrong-shard bounces before the
# record reached its owner, ``queue`` the microbatch assembly wait; the
# rest are the batch-granular pipeline stages. ``device_wait`` spans
# launch-returned → result-in-hand, so under pipelining it absorbs the
# in-flight dwell (that time IS the batch's device+queue residency from
# the transaction's point of view). ``remote_fetch`` is carved OUT of
# its enclosing stage by the child-span bookkeeping (graph-fetch RPCs
# issued mid-dispatch), so the stages stay additive over e2e.
TRACE_STAGES = ("ingest", "broker_transit", "redirect_hops", "queue",
                "assemble", "pack", "dispatch", "device_wait",
                "remote_fetch", "finalize")

# trace_stage_ms histogram bounds (milliseconds). Shared with
# obs.metrics.MetricsCollector.sync_tracing: the tracer aggregates into
# exactly these buckets so the Prometheus mirror is a pure counter-delta.
TRACE_STAGE_BUCKETS_MS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                          20.0, 50.0, 100.0, 500.0)

# ---------------------------------------------------------------------------
# cross-process trace carrier
# ---------------------------------------------------------------------------

# Producers stamp the carrier INTO the record value (next to ``ingest_ts``),
# so it rides ``produce_batch_stamped`` framing across the in-memory broker
# and the TCP netbroker verbatim; consumers read it from the RAW record
# value before sanitize strips unknown fields.
CARRIER_KEY = "trace_carrier"


def make_carrier(trace_id: str, origin: str = "",
                 produced_ts: Optional[float] = None, priority: str = "",
                 fault: str = "", parent: str = "", hops: int = 0,
                 redirect_s: float = 0.0) -> Dict[str, Any]:
    """Compact wire form of a trace context (the keys are the format):

    ``v`` version, ``tid`` trace id, ``sp`` parent span id, ``org``
    producing process (gateway / serving / worker id), ``ts`` producer
    WALL stamp (consume-wall minus it = ``broker_transit``), ``pr`` QoS
    priority, ``flt`` producer-side fault context, ``rh``/``rs``
    421-redirect hop count and accumulated redirect seconds. Empty
    fields are omitted — the carrier stays a handful of bytes.
    """
    c: Dict[str, Any] = {"v": 1, "tid": str(trace_id)}
    if parent:
        c["sp"] = str(parent)
    if origin:
        c["org"] = str(origin)
    if produced_ts is not None:
        c["ts"] = round(float(produced_ts), 6)
    if priority:
        c["pr"] = str(priority)
    if fault:
        c["flt"] = str(fault)
    if hops:
        c["rh"] = int(hops)
    if redirect_s:
        c["rs"] = round(float(redirect_s), 6)
    return c


def parse_carrier(obj: Any) -> Optional[Dict[str, Any]]:
    """Validate a wire carrier; None = unusable (counted as carrier loss
    by ``Tracer.begin`` when one was expected — a fresh root, never a
    wedge)."""
    if not isinstance(obj, dict):
        return None
    tid = obj.get("tid")
    if not isinstance(tid, str) or not tid:
        return None
    out: Dict[str, Any] = {"tid": tid,
                           "sp": str(obj.get("sp", "") or ""),
                           "org": str(obj.get("org", "") or ""),
                           "pr": str(obj.get("pr", "") or ""),
                           "flt": str(obj.get("flt", "") or "")}
    for key, cast in (("ts", float), ("rh", int), ("rs", float)):
        try:
            out[key] = cast(obj[key])
        except (KeyError, TypeError, ValueError):
            pass
    return out


# Log/trace correlation seam: ``Tracer.batch`` publishes the active batch's
# lead trace id (+ worker origin) thread-locally; ``obs.logs.JsonFormatter``
# consults it so flight-recorder exemplars are greppable in the JSON logs.
_log_ctx = threading.local()


def set_log_context(trace_id: str, worker: str = "") -> None:
    _log_ctx.trace_id = str(trace_id)
    _log_ctx.worker = str(worker)


def clear_log_context() -> None:
    _log_ctx.trace_id = ""
    _log_ctx.worker = ""


def current_log_context() -> Optional[Dict[str, str]]:
    tid = getattr(_log_ctx, "trace_id", "")
    if not tid:
        return None
    return {"trace_id": tid, "worker": getattr(_log_ctx, "worker", "")}


class TraceContext:
    """Per-transaction trace state between admission and completion.
    ``priority`` is the QoS class the admission path assigned (empty when
    no QoS plane classified the transaction) — it rides to the completed
    trace so queue-wait attribution can split by class."""

    __slots__ = ("trace_id", "txn_id", "t_admit", "ingest_lag_s",
                 "priority", "broker_transit_s", "redirect_s", "hops",
                 "origin", "parent", "fault")

    def __init__(self, trace_id: str, txn_id: str, t_admit: float,
                 ingest_lag_s: float = 0.0, priority: str = "",
                 broker_transit_s: float = 0.0, redirect_s: float = 0.0,
                 hops: int = 0, origin: str = "", parent: str = "",
                 fault: str = ""):
        self.trace_id = trace_id
        self.txn_id = txn_id
        self.t_admit = t_admit
        self.ingest_lag_s = ingest_lag_s
        self.priority = priority
        # carrier-adopted cross-process segments (wall-minus-wall deltas
        # carried as durations, the ingest-lag clock discipline)
        self.broker_transit_s = broker_transit_s
        self.redirect_s = redirect_s
        self.hops = hops
        self.origin = origin            # producing process ("" = local root)
        self.parent = parent            # producer-side parent span id
        self.fault = fault              # producer-side fault context


class TraceBatch:
    """One microbatch's trace carrier: per-txn contexts + batch marks.

    ``mark`` records (stage, now) once per batch — the near-zero-overhead
    contract. The scorer marks assemble/pack/dispatch/device_wait/finalize;
    the owner (stream job / serving app) finishes the batch after fan-out,
    which stamps the emit time and fans the shared marks out to per-txn
    completed traces.
    """

    __slots__ = ("tracer", "contexts", "marks", "meta", "spans")

    def __init__(self, tracer: "Tracer", contexts: List[TraceContext],
                 meta: Optional[Dict[str, Any]] = None):
        self.tracer = tracer
        self.contexts = contexts
        self.marks: List[Tuple[str, float]] = []
        self.meta: Dict[str, Any] = dict(meta or {})
        # child spans carved OUT of their enclosing stage at finish time:
        # (enclosing mark index, span name, duration ms, span meta)
        self.spans: List[Tuple[int, str, float, Dict[str, Any]]] = []

    def mark(self, stage: str) -> None:
        self.marks.append((stage, self.tracer._clock()))

    def child_span(self, name: str, dur_ms: float, **meta: Any) -> None:
        """Record a sub-operation (a remote graph-fetch RPC, say) inside
        the CURRENT stage. ``finish_batch`` subtracts the span from its
        enclosing stage and books it under its own name, so the stage
        table stays additive over e2e while the remote time is visible
        as a first-class stage."""
        self.spans.append((len(self.marks) - 1, str(name),
                           max(0.0, float(dur_ms)), meta))

    def annotate(self, **kv: Any) -> None:
        self.meta.update(kv)


class CompletedTrace:
    """An immutable completed trace row in the flight recorder."""

    __slots__ = ("trace_id", "txn_id", "t_start", "e2e_ms", "stages",
                 "meta", "terminal", "priority", "origin", "parent")

    def __init__(self, trace_id, txn_id, t_start, e2e_ms, stages, meta,
                 terminal, priority="", origin="", parent=""):
        self.trace_id = trace_id
        self.txn_id = txn_id
        self.t_start = t_start          # tracer-clock start (admit - queue)
        self.e2e_ms = e2e_ms
        self.stages = stages            # {stage: ms}, additive over e2e
        self.meta = meta
        self.terminal = terminal        # scored | shed | error | cached
        self.priority = priority        # QoS class ("" = unclassified)
        self.origin = origin            # carrier origin ("" = local root)
        self.parent = parent            # carrier parent span id

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "trace_id": self.trace_id,
            "txn_id": self.txn_id,
            "t_start": round(self.t_start, 6),
            "e2e_ms": round(self.e2e_ms, 4),
            "stages": {k: round(v, 4) for k, v in self.stages.items()},
            "meta": self.meta,
            "terminal": self.terminal,
            "priority": self.priority,
        }
        if self.origin:
            out["origin"] = self.origin
        if self.parent:
            out["parent"] = self.parent
        return out


class SloTracker:
    """Windowed SLO accounting: objective_frac of txns under objective_ms.

    Time-bucketed counters (one [bucket, total, violations] row per
    ``bucket_s``) bound memory to the slow window regardless of
    throughput, and make the burn rate exact on a virtual clock. Burn
    rate = violation fraction / error budget (1 - objective_frac): 1.0
    means the budget is being consumed exactly at the sustainable rate,
    2.0 means twice as fast — the standard multi-window burn alerting
    quantity.
    """

    def __init__(self, objective_ms: float = 20.0,
                 objective_frac: float = 0.99,
                 fast_window_s: float = 3600.0,
                 slow_window_s: float = 21600.0,
                 bucket_s: float = 60.0,
                 clock=time.monotonic):
        self.objective_ms = float(objective_ms)
        self.objective_frac = float(objective_frac)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.bucket_s = float(bucket_s)
        self._clock = clock
        self._lock = threading.Lock()
        maxlen = int(self.slow_window_s / self.bucket_s) + 2
        self._buckets: deque = deque(maxlen=maxlen)  # [idx, total, bad]
        self.violations_total = 0
        self.observations_total = 0

    def record(self, e2e_ms: float, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        idx = int(now // self.bucket_s)
        bad = 1 if e2e_ms > self.objective_ms else 0
        with self._lock:
            if self._buckets and self._buckets[-1][0] == idx:
                row = self._buckets[-1]
                row[1] += 1
                row[2] += bad
            else:
                self._buckets.append([idx, 1, bad])
            self.observations_total += 1
            self.violations_total += bad

    def _counts(self, window_s: float, now: float) -> Tuple[int, int]:
        lo = int((now - window_s) // self.bucket_s)
        total = bad = 0
        with self._lock:
            for idx, t, b in self._buckets:
                if idx > lo:
                    total += t
                    bad += b
        return total, bad

    def burn_rate(self, window_s: float, now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        total, bad = self._counts(window_s, now)
        if not total:
            return 0.0
        budget = max(1e-9, 1.0 - self.objective_frac)
        return (bad / total) / budget

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``GET /slo`` payload."""
        now = self._clock() if now is None else now
        windows = {}
        for name, win in (("fast", self.fast_window_s),
                          ("slow", self.slow_window_s)):
            total, bad = self._counts(win, now)
            budget = max(1e-9, 1.0 - self.objective_frac)
            frac = bad / total if total else 0.0
            windows[name] = {
                "window_s": win,
                "observed": total,
                "violations": bad,
                "violation_frac": round(frac, 6),
                "burn_rate": round(frac / budget, 4),
                "budget_remaining_frac": round(1.0 - frac / budget, 4),
            }
        return {
            "objective": {"latency_ms": self.objective_ms,
                          "frac": self.objective_frac},
            "windows": windows,
            "observations_total": self.observations_total,
            "violations_total": self.violations_total,
        }


def _bucket_index(ms: float) -> int:
    for i, ub in enumerate(TRACE_STAGE_BUCKETS_MS):
        if ms <= ub:
            return i
    return len(TRACE_STAGE_BUCKETS_MS)        # the +Inf bucket


class _StageAgg:
    """Cumulative per-stage histogram (TRACE_STAGE_BUCKETS_MS + Inf),
    mirrored into Prometheus by counter deltas (sync_tracing)."""

    __slots__ = ("bucket_counts", "sum_ms", "count", "max_ms", "exemplar")

    def __init__(self) -> None:
        self.bucket_counts = [0] * (len(TRACE_STAGE_BUCKETS_MS) + 1)
        self.sum_ms = 0.0
        self.count = 0
        self.max_ms = 0.0
        self.exemplar: Optional[Dict[str, Any]] = None   # slowest sample

    def add(self, ms: float, trace_id: str) -> None:
        self.bucket_counts[_bucket_index(ms)] += 1
        self.sum_ms += ms
        self.count += 1
        if ms >= self.max_ms:
            self.max_ms = ms
            self.exemplar = {"trace_id": trace_id, "ms": round(ms, 4)}


class Tracer:
    """The tracing plane: begin/mark/finish + flight recorder + SLO.

    One instance per process-plane (stream job or serving app). All
    completion work is batched: ``finish_batch`` takes the plane lock once
    per microbatch. ``settings`` is a ``utils.config.TracingSettings``
    (or anything with its fields); ``clock`` must match the time base of
    every ``t_admit`` handed to :meth:`begin` — the drills pass a virtual
    clock.
    """

    def __init__(self, settings: Optional[Any] = None, clock=time.monotonic,
                 origin: str = ""):
        from realtime_fraud_detection_tpu.utils.config import TracingSettings

        self.settings = settings if settings is not None else TracingSettings(
            enabled=True)
        self.enabled = bool(getattr(self.settings, "enabled", True))
        self._clock = clock
        self._lock = threading.Lock()
        # process identity stamped into minted trace ids and carriers —
        # what keeps two workers' fresh roots globally distinct when the
        # coordinator stitches their rings ("" keeps the single-process
        # id format unchanged)
        self.origin = str(origin
                          or getattr(self.settings, "origin", "") or "")
        s = self.settings
        self._ring: deque = deque(maxlen=max(16, int(s.ring_size)))
        self._slowest: List[Tuple[float, int, CompletedTrace]] = []
        self._slowest_n = max(1, int(s.slowest_n))
        self._seq = itertools.count()
        self._stage_agg: Dict[str, _StageAgg] = {}
        self.counters: Dict[str, int] = {
            "started": 0, "completed": 0, "shed": 0, "errors": 0,
            "cached": 0, "carrier_adopted": 0, "carrier_lost": 0,
        }
        # active fault-window attribution (chaos plane): while set, every
        # trace closed — scored, shed, errored, terminal — carries
        # ``meta["fault"]``, so a flight-recorder window spanning an
        # injected outage separates in-fault tails from steady state
        self.fault_context: str = ""
        self.slo = SloTracker(
            objective_ms=s.slo_objective_ms,
            objective_frac=s.slo_objective_frac,
            fast_window_s=s.slo_fast_window_s,
            slow_window_s=s.slo_slow_window_s,
            bucket_s=s.slo_bucket_s,
            clock=clock,
        )

    # ------------------------------------------------------------- lifecycle
    def _next_id(self) -> str:
        n = next(self._seq)
        return f"t{self.origin}-{n:08x}" if self.origin else f"t{n:08x}"

    def begin(self, txn_id: str, ingest_lag_s: float = 0.0,
              t_admit: Optional[float] = None, priority: str = "",
              carrier: Any = None, now_wall: Optional[float] = None,
              expect_carrier: bool = False) -> Optional[TraceContext]:
        """Open a trace at admission. Returns None when disabled — every
        downstream call site guards on the context, so the disabled plane
        costs one branch. ``priority`` is the QoS class the admission path
        assigned (queue-wait attribution splits on it).

        ``carrier`` re-hydrates a producer-stamped wire carrier: the
        trace ADOPTS the producer's trace id (stitching key), priority,
        fault context and redirect ledger, and ``broker_transit`` becomes
        ``now_wall`` (consume wall stamp) minus the carrier's produce
        stamp — the ingest lag is reduced by the same amount so the
        pre-admission segments never double-count one interval. A
        missing or unparseable carrier where one was expected
        (``expect_carrier``, or a present-but-garbled frame) degrades to
        a fresh LOCAL root, counted in ``carrier_lost`` — never a gap,
        never a wedge."""
        if not self.enabled:
            return None
        self.counters["started"] += 1
        tid = ""
        parent = origin = fault = ""
        transit = redirect = 0.0
        hops = 0
        pr = str(priority)
        if carrier is not None or expect_carrier:
            c = parse_carrier(carrier)
            if c is None:
                self.counters["carrier_lost"] += 1
            else:
                self.counters["carrier_adopted"] += 1
                tid = c["tid"]
                parent, origin, fault = c["sp"], c["org"], c["flt"]
                if not pr:
                    pr = c["pr"]
                ts = c.get("ts")
                if ts is not None and now_wall is not None:
                    transit = max(0.0, float(now_wall) - ts)
                hops = int(c.get("rh", 0))
                redirect = max(0.0, float(c.get("rs", 0.0)))
        ingest = max(0.0, float(ingest_lag_s))
        if transit > 0.0:
            # ingest_ts and the carrier's produce stamp bracket the same
            # wall interval's two ends: keep ingest = submit→produce,
            # transit = produce→consume, additive by construction
            ingest = max(0.0, ingest - transit)
        return TraceContext(
            tid or self._next_id(), str(txn_id),
            self._clock() if t_admit is None else t_admit,
            ingest, pr, broker_transit_s=transit, redirect_s=redirect,
            hops=hops, origin=origin, parent=parent, fault=fault)

    def root_carrier(self, produced_ts: Optional[float] = None,
                     priority: str = "") -> Optional[Dict[str, Any]]:
        """Mint a wire carrier for a record THIS process produces but
        will never score (gateway/serving → broker): a fresh distributed
        trace id plus the producer wall stamp the consumer turns into
        ``broker_transit``. Returns None when disabled."""
        if not self.enabled:
            return None
        return make_carrier(self._next_id(), origin=self.origin,
                            produced_ts=produced_ts, priority=priority,
                            fault=self.fault_context)

    def batch(self, contexts: Sequence[Optional[TraceContext]],
              **meta: Any) -> Optional[TraceBatch]:
        """Bind admitted contexts into one microbatch carrier. Publishes
        the lead trace id thread-locally (``current_log_context``) so JSON
        log lines emitted while the batch is in flight carry it."""
        ctxs = [c for c in contexts if c is not None]
        if not self.enabled or not ctxs:
            return None
        set_log_context(ctxs[0].trace_id, self.origin)
        return TraceBatch(self, ctxs, meta)

    def set_fault_context(self, name: str) -> None:
        """Chaos-plane attribution: set (or clear, with "") the active
        fault-window name(s); subsequent trace completions — terminal
        sheds/errors included — carry it as ``meta["fault"]``, so the
        flight recorder separates fault-window tails from steady state."""
        self.fault_context = str(name or "")

    # ------------------------------------------------------------ completion
    def finish_batch(self, trace: Optional[TraceBatch],
                     terminal: str = "scored") -> None:
        """Stamp emit time, fan batch marks out to per-txn traces, record.

        Stage durations are consecutive-mark deltas, so they partition
        ``emit - admit`` exactly (additive by construction); ``queue`` is
        per-transaction (first mark - that txn's admission), ``ingest``
        the carried upstream lag.
        """
        if trace is None:
            return
        now = self._clock()
        clear_log_context()
        if self.fault_context:
            trace.meta = dict(trace.meta)
            trace.meta["fault"] = self.fault_context
        if trace.spans:
            trace.meta = dict(trace.meta)
            trace.meta["spans"] = [
                {"name": name, "ms": round(ms, 4), **smeta}
                for _, name, ms, smeta in trace.spans]
        marks = trace.marks
        completed: List[CompletedTrace] = []
        for ctx in trace.contexts:
            stages: Dict[str, float] = {}
            if ctx.ingest_lag_s > 0.0:
                stages["ingest"] = ctx.ingest_lag_s * 1e3
            if ctx.broker_transit_s > 0.0:
                stages["broker_transit"] = ctx.broker_transit_s * 1e3
            if ctx.hops or ctx.redirect_s > 0.0:
                stages["redirect_hops"] = ctx.redirect_s * 1e3
            if marks:
                stages["queue"] = max(0.0, marks[0][1] - ctx.t_admit) * 1e3
                for i, (name, t0) in enumerate(marks):
                    t1 = marks[i + 1][1] if i + 1 < len(marks) else now
                    stages[name] = max(0.0, t1 - t0) * 1e3
            else:
                stages["queue"] = max(0.0, now - ctx.t_admit) * 1e3
            for idx, name, ms, _smeta in trace.spans:
                # carve the child span out of its enclosing stage so the
                # table stays additive (a span before the first mark came
                # out of the queue wait)
                encl = marks[idx][0] if 0 <= idx < len(marks) else "queue"
                if encl in stages:
                    stages[encl] = max(0.0, stages[encl] - ms)
                stages[name] = stages.get(name, 0.0) + ms
            pre = (ctx.ingest_lag_s + ctx.broker_transit_s
                   + ctx.redirect_s)
            e2e_ms = (pre + max(0.0, now - ctx.t_admit)) * 1e3
            meta = trace.meta
            if ctx.fault and "fault" not in meta:
                meta = dict(meta)
                meta["fault"] = ctx.fault
            completed.append(CompletedTrace(
                ctx.trace_id, ctx.txn_id, ctx.t_admit - pre, e2e_ms,
                stages, meta, terminal, ctx.priority,
                origin=ctx.origin, parent=ctx.parent))
        with self._lock:
            for ct in completed:
                self._record_locked(ct, now)

    def finish_terminal(self, ctx: Optional[TraceContext], terminal: str,
                        **meta: Any) -> None:
        """Close a trace that never reached the device — shed at
        admission, served from the prediction cache, or errored before
        dispatch. The terminal stage is recorded so sheds are auditable
        in the flight recorder, never silent gaps."""
        if ctx is None:
            return
        now = self._clock()
        pre = ctx.ingest_lag_s + ctx.broker_transit_s + ctx.redirect_s
        e2e_ms = (pre + max(0.0, now - ctx.t_admit)) * 1e3
        stages = {"queue": max(0.0, now - ctx.t_admit) * 1e3}
        if ctx.ingest_lag_s > 0.0:
            stages["ingest"] = ctx.ingest_lag_s * 1e3
        if ctx.broker_transit_s > 0.0:
            stages["broker_transit"] = ctx.broker_transit_s * 1e3
        if ctx.hops or ctx.redirect_s > 0.0:
            stages["redirect_hops"] = ctx.redirect_s * 1e3
        meta = dict(meta)
        if self.fault_context:
            meta.setdefault("fault", self.fault_context)
        if ctx.fault:
            meta.setdefault("fault", ctx.fault)
        ct = CompletedTrace(ctx.trace_id, ctx.txn_id,
                            ctx.t_admit - pre, e2e_ms, stages,
                            meta, terminal, ctx.priority,
                            origin=ctx.origin, parent=ctx.parent)
        with self._lock:
            self._record_locked(ct, now)

    def _record_locked(self, ct: CompletedTrace, now: float) -> None:
        self._ring.append(ct)
        key = self.counters
        if ct.terminal == "scored":
            key["completed"] += 1
        elif ct.terminal == "shed":
            key["shed"] += 1
        elif ct.terminal == "cached":
            key["cached"] += 1
        else:
            key["errors"] += 1
        if ct.terminal == "scored":
            for stage, ms in ct.stages.items():
                agg = self._stage_agg.get(stage)
                if agg is None:
                    agg = self._stage_agg[stage] = _StageAgg()
                agg.add(ms, ct.trace_id)
            self.slo.record(ct.e2e_ms, now)
            # slowest-N exemplars kept verbatim (min-heap on e2e)
            item = (ct.e2e_ms, next(self._seq), ct)
            if len(self._slowest) < self._slowest_n:
                heapq.heappush(self._slowest, item)
            elif ct.e2e_ms > self._slowest[0][0]:
                heapq.heapreplace(self._slowest, item)

    # -------------------------------------------------------------- analysis
    def traces(self, terminal: Optional[str] = None) -> List[CompletedTrace]:
        with self._lock:
            out = list(self._ring)
        if terminal is not None:
            out = [t for t in out if t.terminal == terminal]
        return out

    def slowest(self) -> List[CompletedTrace]:
        with self._lock:
            return [ct for _, _, ct in sorted(self._slowest, reverse=True)]

    def breakdown(self) -> Dict[str, Any]:
        """Critical-path decomposition: additive per-stage contributions
        to the p50/p95/p99 end-to-end latency, with the dominant stage
        flagged per quantile (the ``GET /latency/breakdown`` payload).

        For each quantile q the contribution of stage s is the mean of s
        over the traces at-or-above the q-th e2e percentile — the stage
        means sum to the tail's mean e2e, so "where did the p99 go" has
        an additive answer.
        """
        from realtime_fraud_detection_tpu.obs.profiling import (
            interpolated_percentile,
        )

        traces = self.traces(terminal="scored")
        if not traces:
            return {"enabled": self.enabled, "n": 0, "quantiles": {},
                    "exemplars": []}
        e2e = sorted(t.e2e_ms for t in traces)

        quantiles: Dict[str, Any] = {}
        for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            thresh = interpolated_percentile(e2e, q)
            tail = [t for t in traces if t.e2e_ms >= thresh] or traces[-1:]
            contrib: Dict[str, float] = {}
            queue_by_prio: Dict[str, Dict[str, float]] = {}
            for t in tail:
                for stage, ms in t.stages.items():
                    contrib[stage] = contrib.get(stage, 0.0) + ms
                    if stage == "queue":
                        # queue-wait attribution split by QoS class: each
                        # class's share of the tail's SUMMED queue time,
                        # so the per-class contributions (normalized by
                        # the same tail_n) sum exactly to the aggregate
                        # queue figure — "is high-value traffic the one
                        # waiting?" has an additive answer
                        row = queue_by_prio.setdefault(
                            t.priority or "unclassified",
                            {"ms": 0.0, "n": 0})
                        row["ms"] += ms
                        row["n"] += 1
            n = len(tail)
            contrib = {s: round(v / n, 4) for s, v in contrib.items()}
            dominant = max(contrib, key=contrib.get)
            quantiles[name] = {
                "e2e_ms": round(thresh, 4),
                "tail_n": n,
                "stage_ms": contrib,
                "dominant_stage": dominant,
                "dominant_frac": round(
                    contrib[dominant] / max(sum(contrib.values()), 1e-9), 4),
                "queue_ms_by_priority": {
                    p: {"contrib_ms": round(row["ms"] / n, 4),
                        "tail_n": row["n"],
                        "mean_ms": round(row["ms"] / max(row["n"], 1), 4)}
                    for p, row in sorted(queue_by_prio.items())
                },
            }
        return {
            "enabled": self.enabled,
            "n": len(traces),
            "quantiles": quantiles,
            "exemplars": [
                {"trace_id": t.trace_id, "txn_id": t.txn_id,
                 "e2e_ms": round(t.e2e_ms, 4),
                 "dominant_stage": max(t.stages, key=t.stages.get)
                 if t.stages else None}
                for t in self.slowest()[:8]
            ],
        }

    # --------------------------------------------------------------- export
    def export_chrome_trace(self) -> Dict[str, Any]:
        """Chrome-trace / Perfetto JSON of the captured window: one track
        per trace (the ring, slowest-N merged in), complete ("X") events
        per stage. Load in ui.perfetto.dev or chrome://tracing."""
        with self._lock:
            ring = list(self._ring)
            slowest = [ct for _, _, ct in self._slowest]
        seen = {id(t) for t in ring}
        traces = ring + [t for t in slowest if id(t) not in seen]
        traces.sort(key=lambda t: t.t_start)
        events: List[Dict[str, Any]] = []
        for tid, tr in enumerate(traces):
            t = tr.t_start
            for stage in TRACE_STAGES:
                ms = tr.stages.get(stage)
                if ms is None:
                    continue
                events.append({
                    "name": stage, "ph": "X", "pid": 1, "tid": tid,
                    "ts": round(t * 1e6, 3), "dur": round(ms * 1e3, 3),
                    "args": {"trace_id": tr.trace_id, "txn_id": tr.txn_id,
                             "terminal": tr.terminal, **tr.meta},
                })
                t += ms / 1e3
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"tool": "rtfd trace-export",
                         "n_traces": len(traces),
                         "slo": self.slo.snapshot()},
        }

    # ------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, Any]:
        """Cumulative plane state for the Prometheus mirror
        (obs.metrics.MetricsCollector.sync_tracing) and JSON endpoints.
        Bucket counts use TRACE_STAGE_BUCKETS_MS exactly, so the mirror
        is a pure counter-delta (honest counters, rate()/increase()
        valid)."""
        with self._lock:
            stages = {
                name: {
                    "bucket_counts": list(agg.bucket_counts),
                    "sum_ms": agg.sum_ms,
                    "count": agg.count,
                    "max_ms": agg.max_ms,
                    "exemplar": dict(agg.exemplar) if agg.exemplar else None,
                }
                for name, agg in self._stage_agg.items()
            }
            counters = dict(self.counters)
        return {
            "enabled": self.enabled,
            "buckets_ms": list(TRACE_STAGE_BUCKETS_MS),
            "stages": stages,
            "counters": counters,
            "slo": self.slo.snapshot(),
        }

    def reset(self) -> None:
        """Drop the captured window (testing/drills); cumulative counters
        and SLO history survive — only the recorder clears."""
        with self._lock:
            self._ring.clear()
            self._slowest.clear()

"""Fleet-wide observability: metric aggregation + cross-process trace stitching.

A multi-process fleet (cluster/procfleet.py) has N workers each holding a
private ``MetricsCollector`` and a private ``Tracer`` flight recorder. The
coordinator previously saw only liveness (hello/hb/bye); "what is the fleet
doing" required ssh-ing per worker. This module is the coordinator side of
the fleet observability plane:

- ``FleetMetrics`` folds per-worker counter snapshots — published as
  DELTA events on ``cluster-events`` — into one fleet-level Prometheus
  exposition (``GET /metrics/fleet``): every series appears once per
  worker with a ``{worker=...}`` label plus an honest unlabeled fleet
  sum, exactly one ``# HELP``/``# TYPE`` pair per family.
- ``FleetTraceStore`` stitches workers' flight-recorder rings (shipped in
  their bye frames / ring dumps) into fleet-level critical-path analysis
  (additive per-stage tail quantiles with the dominant stage AND the
  dominant worker flagged) and one merged Chrome/Perfetto trace with a
  named track per OS process and broker-transit flow arrows from the
  producer track to the consuming worker's track.

Wire discipline (what makes the fleet sums exact, not approximate):
workers publish counter DELTAS with a per-worker monotonic ``seq``. A
worker advances its ``last_sent`` baseline only after the produce call
returns — a netfault-dropped publish is retried as a larger delta next
interval, never lost. The coordinator drops any event whose seq is not
strictly newer than the last applied (redelivery-safe), so every count is
applied exactly once and the fleet total equals the sum of the workers'
cumulative counters at all times the workers are drained.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from realtime_fraud_detection_tpu.obs.tracing import TRACE_STAGES

__all__ = [
    "FleetMetrics",
    "FleetTraceStore",
    "merge_chrome_traces",
]


def _num(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def _fmt(v: float) -> str:
    """Prometheus sample value: integers render bare (honest counters)."""
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


class FleetMetrics:
    """Coordinator-side fold of per-worker counter snapshots.

    Two ingestion paths share one accumulator:

    - :meth:`ingest_delta` — the streaming path: a ``metrics`` event off
      ``cluster-events`` carrying ``{worker, seq, counters:{k: delta}}``.
      Events are deduped by per-worker ``seq`` (strictly increasing) so
      broker redelivery can never double-count.
    - :meth:`ingest_cumulative` — the snapshot path: an absolute counter
      dict (a worker's bye frame, or the serving process's own local
      counters folded in at render time). Replaces that worker's totals
      wholesale — last snapshot wins.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # worker -> {counter_key: cumulative total}
        self._workers: Dict[str, Dict[str, float]] = {}
        # worker -> last applied delta seq (streaming dedup watermark)
        self._seq: Dict[str, int] = {}
        # worker -> {label: value} identity stamps (pid, version, ...)
        self._info: Dict[str, Dict[str, str]] = {}
        self.events_applied = 0
        self.events_stale = 0

    # -------------------------------------------------------------- ingest
    def ingest_delta(self, event: Mapping[str, Any]) -> bool:
        """Apply one ``metrics`` fleet event; False = stale seq, dropped."""
        worker = str(event.get("worker", "") or "")
        if not worker:
            return False
        seq = int(event.get("seq", 0) or 0)
        counters = event.get("counters") or {}
        with self._lock:
            last = self._seq.get(worker, -1)
            if seq <= last:
                self.events_stale += 1
                return False
            self._seq[worker] = seq
            totals = self._workers.setdefault(worker, {})
            for k, v in counters.items():
                totals[str(k)] = totals.get(str(k), 0.0) + _num(v)
            self.events_applied += 1
        return True

    def ingest_cumulative(self, worker: str,
                          counters: Mapping[str, Any]) -> None:
        """Replace ``worker``'s totals with an absolute snapshot (bye
        frames; the coordinator's own in-process counters)."""
        worker = str(worker)
        with self._lock:
            self._workers[worker] = {
                str(k): _num(v) for k, v in counters.items()}

    def set_worker_info(self, worker: str, **labels: Any) -> None:
        """Identity stamps rendered on ``fleet_worker_info`` (pid,
        version, config digest, ...)."""
        with self._lock:
            row = self._info.setdefault(str(worker), {})
            for k, v in labels.items():
                row[str(k)] = str(v)

    def forget_worker(self, worker: str) -> None:
        with self._lock:
            self._workers.pop(str(worker), None)
            self._seq.pop(str(worker), None)
            self._info.pop(str(worker), None)

    # ------------------------------------------------------------- queries
    def worker_counters(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {w: dict(c) for w, c in self._workers.items()}

    def fleet_counters(self) -> Dict[str, float]:
        """Honest fleet sums: key -> sum over workers."""
        out: Dict[str, float] = {}
        with self._lock:
            for counters in self._workers.values():
                for k, v in counters.items():
                    out[k] = out.get(k, 0.0) + v
        return out

    def take_delta(self, key: str, _state: Dict[str, float] = None) -> float:
        """Fleet-sum delta for ``key`` since the previous call with the
        same ``_state`` dict (callers keep their own) — the autoscaler
        feeds these into ``observe()`` as arrivals."""
        state = _state if _state is not None else self._default_state
        total = self.fleet_counters().get(key, 0.0)
        prev = state.get(key, 0.0)
        state[key] = total
        return max(0.0, total - prev)

    @property
    def _default_state(self) -> Dict[str, float]:
        st = getattr(self, "_take_state", None)
        if st is None:
            st = self._take_state = {}
        return st

    # -------------------------------------------------------------- render
    def render(self, version: str = "", extra_info: Optional[
            Mapping[str, str]] = None) -> str:
        """One fleet Prometheus exposition. Families are rendered from a
        family-keyed dict, so exactly one ``# HELP``/``# TYPE`` pair per
        series name is structural, not incidental:

        - ``rtfd_worker_<key>{worker="w0"}`` — per-worker totals;
        - ``rtfd_fleet_<key>`` — the unlabeled fleet sum;
        - ``rtfd_build_info`` / ``fleet_worker_info`` — constant ``1``
          gauges carrying version + per-worker identity stamps.

        Counter keys that already end in ``_total`` keep the suffix once
        (never ``_total_total``); keys without it get ``_total`` appended
        so the counter naming convention holds fleet-wide.
        """
        with self._lock:
            workers = {w: dict(c) for w, c in sorted(self._workers.items())}
            info = {w: dict(r) for w, r in sorted(self._info.items())}

        def series_name(prefix: str, key: str) -> str:
            base = f"{prefix}_{key}"
            return base if key.endswith("_total") else f"{base}_total"

        # family name -> (help, type, [(labels_str, value)])
        fams: Dict[str, Tuple[str, str, List[Tuple[str, float]]]] = {}

        def add(name: str, help_text: str, mtype: str,
                labels: str, value: float) -> None:
            fam = fams.get(name)
            if fam is None:
                fam = fams[name] = (help_text, mtype, [])
            fam[2].append((labels, value))

        fleet: Dict[str, float] = {}
        for w, counters in workers.items():
            for k in sorted(counters):
                v = counters[k]
                fleet[k] = fleet.get(k, 0.0) + v
                add(series_name("rtfd_worker", k),
                    f"Per-worker cumulative {k}", "counter",
                    '{worker="%s"}' % _escape_label(w), v)
        for k in sorted(fleet):
            add(series_name("rtfd_fleet", k),
                f"Fleet-wide sum of {k} over all workers", "counter",
                "", fleet[k])

        build_labels = {"version": version or "unknown"}
        if extra_info:
            build_labels.update({str(k): str(v)
                                 for k, v in extra_info.items()})
        lbl = ",".join('%s="%s"' % (k, _escape_label(v))
                       for k, v in sorted(build_labels.items()))
        add("rtfd_build_info",
            "Build/version identity of the aggregating process", "gauge",
            "{%s}" % lbl, 1.0)
        for w, row in info.items():
            labels = {"worker": w}
            labels.update(row)
            lbl = ",".join('%s="%s"' % (k, _escape_label(v))
                           for k, v in sorted(labels.items()))
            add("fleet_worker_info",
                "Per-worker identity stamps (pid, version, config)",
                "gauge", "{%s}" % lbl, 1.0)

        lines: List[str] = []
        for name in sorted(fams):
            help_text, mtype, samples = fams[name]
            lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {mtype}")
            for labels, value in samples:
                lines.append(f"{name}{labels} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            workers = {w: dict(c) for w, c in self._workers.items()}
            seq = dict(self._seq)
            applied, stale = self.events_applied, self.events_stale
        fleet: Dict[str, float] = {}
        for counters in workers.values():
            for k, v in counters.items():
                fleet[k] = fleet.get(k, 0.0) + v
        return {
            "workers": workers,
            "fleet": fleet,
            "seq": seq,
            "events_applied": applied,
            "events_stale": stale,
        }


# ---------------------------------------------------------------------------
# cross-process trace stitching
# ---------------------------------------------------------------------------

class FleetTraceStore:
    """Coordinator-side flight recorder over STITCHED traces.

    Ingests workers' ring dumps (``CompletedTrace.to_dict`` rows, wall-
    clock ``t_start`` base) tagged with the consuming worker id. A trace
    whose ``origin`` differs from its consuming worker crossed a process
    boundary — the stitching signal the obs-drill pins.
    """

    def __init__(self, ring_size: int = 16384, slowest_n: int = 32):
        self._lock = threading.Lock()
        self._ring_size = max(16, int(ring_size))
        self._rows: List[Dict[str, Any]] = []
        self._pids: Dict[str, int] = {}
        self._slowest_n = max(1, int(slowest_n))

    # -------------------------------------------------------------- ingest
    def ingest(self, worker: str, traces: Sequence[Mapping[str, Any]],
               pid: int = 0) -> int:
        """Fold one worker's ring dump in; rows are kept verbatim plus a
        ``worker`` tag. Returns rows accepted."""
        worker = str(worker)
        rows = []
        for t in traces:
            if not isinstance(t, Mapping) or "trace_id" not in t:
                continue
            row = dict(t)
            row["worker"] = worker
            rows.append(row)
        with self._lock:
            if pid:
                self._pids[worker] = int(pid)
            self._rows.extend(rows)
            if len(self._rows) > self._ring_size:
                self._rows = self._rows[-self._ring_size:]
        return len(rows)

    def rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._rows)

    # ------------------------------------------------------------ analysis
    def stitch_stats(self) -> Dict[str, Any]:
        """How well did the carrier plane stitch: of all ingested traces,
        how many crossed a process boundary (carrier adopted from another
        origin), how many carry a remote graph-fetch child span, and the
        broker-transit distribution. ``fresh_roots`` are traces minted
        locally (no origin) — carrier loss and un-stamped producers land
        here."""
        from realtime_fraud_detection_tpu.obs.profiling import (
            interpolated_percentile,
        )

        rows = self.rows()
        crossed = with_remote = fresh = 0
        transit: List[float] = []
        for r in rows:
            origin = str(r.get("origin", "") or "")
            worker = str(r.get("worker", "") or "")
            if origin and origin != worker:
                crossed += 1
            elif not origin:
                fresh += 1
            bt = _num((r.get("stages") or {}).get("broker_transit", 0.0))
            if bt > 0.0:
                transit.append(bt)
            spans = (r.get("meta") or {}).get("spans") or []
            if any(s.get("name") == "remote_fetch" for s in spans
                   if isinstance(s, Mapping)):
                with_remote += 1
        out: Dict[str, Any] = {
            "total": len(rows),
            "crossed_process": crossed,
            "with_remote_span": with_remote,
            "fresh_roots": fresh,
            "stitch_rate": round(crossed / len(rows), 4) if rows else 0.0,
        }
        if transit:
            st = sorted(transit)
            out["broker_transit_ms"] = {
                "p50": round(interpolated_percentile(st, 0.50), 4),
                "p99": round(interpolated_percentile(st, 0.99), 4),
                "max": round(st[-1], 4),
                "n": len(st),
            }
        return out

    def breakdown(self) -> Dict[str, Any]:
        """Fleet critical path: the Tracer.breakdown contract (additive
        per-stage contributions over the tail at each quantile, dominant
        stage flagged) computed over ALL workers' scored traces, plus
        per-worker dominant stages and the dominant WORKER of each tail
        (the worker contributing the most summed e2e among tail traces —
        the slow-worker attribution the obs-drill pins)."""
        from realtime_fraud_detection_tpu.obs.profiling import (
            interpolated_percentile,
        )

        rows = [r for r in self.rows() if r.get("terminal") == "scored"]
        if not rows:
            return {"n": 0, "quantiles": {}, "per_worker": {},
                    "exemplars": []}
        e2e = sorted(_num(r.get("e2e_ms")) for r in rows)
        quantiles: Dict[str, Any] = {}
        for name, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            thresh = interpolated_percentile(e2e, q)
            tail = [r for r in rows if _num(r.get("e2e_ms")) >= thresh] \
                or rows[-1:]
            contrib: Dict[str, float] = {}
            by_worker: Dict[str, float] = {}
            for r in tail:
                for stage, ms in (r.get("stages") or {}).items():
                    contrib[stage] = contrib.get(stage, 0.0) + _num(ms)
                w = str(r.get("worker", "") or "?")
                by_worker[w] = by_worker.get(w, 0.0) + _num(r.get("e2e_ms"))
            n = len(tail)
            contrib = {s: round(v / n, 4) for s, v in contrib.items()}
            dominant = max(contrib, key=contrib.get)
            dom_worker = max(by_worker, key=by_worker.get)
            quantiles[name] = {
                "e2e_ms": round(thresh, 4),
                "tail_n": n,
                "stage_ms": contrib,
                "dominant_stage": dominant,
                "dominant_frac": round(
                    contrib[dominant] / max(sum(contrib.values()), 1e-9), 4),
                "dominant_worker": dom_worker,
                "worker_e2e_share": {
                    w: round(v / max(sum(by_worker.values()), 1e-9), 4)
                    for w, v in sorted(by_worker.items())},
            }
        per_worker: Dict[str, Any] = {}
        for w in sorted({str(r.get("worker", "") or "?") for r in rows}):
            wrows = [r for r in rows if str(r.get("worker", "") or "?") == w]
            sums: Dict[str, float] = {}
            for r in wrows:
                for stage, ms in (r.get("stages") or {}).items():
                    sums[stage] = sums.get(stage, 0.0) + _num(ms)
            dom = max(sums, key=sums.get) if sums else None
            per_worker[w] = {
                "n": len(wrows),
                "dominant_stage": dom,
                "mean_e2e_ms": round(
                    sum(_num(r.get("e2e_ms")) for r in wrows) / len(wrows),
                    4),
            }
        slowest = sorted(rows, key=lambda r: _num(r.get("e2e_ms")),
                         reverse=True)[: self._slowest_n]
        return {
            "n": len(rows),
            "quantiles": quantiles,
            "per_worker": per_worker,
            "stitch": self.stitch_stats(),
            # slowest-N exemplars verbatim — the whole row, not a summary
            "exemplars": slowest,
        }

    # -------------------------------------------------------------- export
    def export_chrome_trace(self) -> Dict[str, Any]:
        """One merged Chrome/Perfetto trace for the whole fleet: a named
        process track per worker (``worker <id> (pid N)``) plus one
        ``ingress`` track per producing origin; a stitched trace's
        ``ingest`` + ``broker_transit`` slices draw on its ORIGIN track
        and the remaining stages on the consuming worker's track, joined
        by a flow arrow (``ph:"s"``/``ph:"f"``) across the broker hop —
        the cross-process handoff is a visible edge, not an inference.
        Requires the workers' tracers to share one wall-clock base."""
        rows = sorted(self.rows(), key=lambda r: _num(r.get("t_start")))
        with self._lock:
            pids = dict(self._pids)
        # stable integer pid per track: workers first, then origins
        track_pid: Dict[str, int] = {}
        events: List[Dict[str, Any]] = []

        def pid_for(track: str, kind: str) -> int:
            p = track_pid.get(track)
            if p is not None:
                return p
            p = len(track_pid) + 1
            track_pid[track] = p
            real = pids.get(track)
            name = f"worker {track}" + (f" (pid {real})" if real else "") \
                if kind == "worker" else f"ingress {track}"
            events.append({"name": "process_name", "ph": "M", "pid": p,
                           "args": {"name": name}})
            return p

        flow_id = 0
        for tid, r in enumerate(rows):
            worker = str(r.get("worker", "") or "?")
            origin = str(r.get("origin", "") or "")
            stages = r.get("stages") or {}
            wpid = pid_for(worker, "worker")
            opid = pid_for(origin, "origin") if origin and origin != worker \
                else wpid
            args = {"trace_id": r.get("trace_id"),
                    "txn_id": r.get("txn_id"),
                    "terminal": r.get("terminal"),
                    "worker": worker}
            t = _num(r.get("t_start"))
            crossed = opid != wpid
            for stage in TRACE_STAGES:
                ms = stages.get(stage)
                if ms is None:
                    continue
                ms = _num(ms)
                on_origin = crossed and stage in ("ingest", "broker_transit")
                pid = opid if on_origin else wpid
                ts = round(t * 1e6, 3)
                events.append({"name": stage, "ph": "X", "pid": pid,
                               "tid": tid, "ts": ts,
                               "dur": round(ms * 1e3, 3), "args": args})
                if crossed and stage == "broker_transit":
                    # flow arrow: start on the producer's transit slice,
                    # finish at the head of the consumer's first slice
                    flow_id += 1
                    events.append({"name": "broker_hop", "ph": "s",
                                   "id": flow_id, "pid": opid, "tid": tid,
                                   "ts": ts, "cat": "broker"})
                    events.append({"name": "broker_hop", "ph": "f",
                                   "bp": "e", "id": flow_id, "pid": wpid,
                                   "tid": tid,
                                   "ts": round((t + ms / 1e3) * 1e6, 3),
                                   "cat": "broker"})
                t += ms / 1e3
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {"tool": "rtfd trace-export --merge",
                         "n_traces": len(rows),
                         "tracks": {t: p for t, p in track_pid.items()}},
        }


def merge_chrome_traces(dumps: Sequence[Mapping[str, Any]],
                        ring_size: int = 65536) -> Dict[str, Any]:
    """``rtfd trace-export --merge`` entry point: fold N per-worker ring
    dumps — ``{"worker": id, "pid": N, "traces": [CompletedTrace.to_dict,
    ...]}`` (the obs-drill/bye wire shape) — into one fleet Chrome trace."""
    store = FleetTraceStore(ring_size=ring_size)
    for d in dumps:
        store.ingest(str(d.get("worker", "") or "?"),
                     d.get("traces") or [], pid=int(d.get("pid", 0) or 0))
    return store.export_chrome_trace()

"""Self-contained metrics plane: counters/gauges/histograms + Prometheus text.

Capability parity with the reference's MetricsCollector (metrics.py:36-432):
per-model/per-decision prediction counters, latency histogram (1 ms–5 s
buckets), fraud-score histogram, uptime/throughput gauges, a bounded in-memory
window of recent predictions powering the JSON ``/metrics`` summaries, and a
``reset`` hook "(for testing purposes)" (metrics.py:403-417).

Implemented as our own tiny registry rather than ``prometheus_client`` so
instances are isolated (no process-global REGISTRY leaking between tests or
between a serving app and a stream job in one process) and the render path is
deterministic. Text output follows the Prometheus exposition format, so the
reference's scrape topology (prometheus.yml:14-90) points at
``GET /metrics/prometheus`` unchanged.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsCollector",
    "Registry",
    "DEFAULT_LATENCY_BUCKETS",
]

# Reference latency buckets: 1 ms .. 5 s (metrics.py:74-78).
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)
SCORE_BUCKETS: Tuple[float, ...] = tuple(i / 10 for i in range(1, 10))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _labels_key(labels: Mapping[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    body = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + body + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def render(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_text, labelnames=()):
        super().__init__(name, help_text, labelnames)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def by_label(self) -> List[Tuple[Dict[str, str], float]]:
        """Sorted snapshot of (labels, value) pairs — the public accessor
        for folding a labeled counter (e.g. the drills' shed-by-
        priority:reason tables) without reaching into ``_values``."""
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        if not items:
            items = [((), 0.0)]
        for key, v in items:
            lines.append(f"{self.name}{_render_labels(key)} {_fmt(v)}")
        return lines


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_text, labelnames=()):
        super().__init__(name, help_text, labelnames)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_labels_key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items()) or [((), 0.0)]
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key, v in items:
            lines.append(f"{self.name}{_render_labels(key)} {_fmt(v)}")
        return lines


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_text, labelnames=(),
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS):
        super().__init__(name, help_text, labelnames)
        self.buckets = tuple(sorted(buckets)) + (math.inf,)
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._maxes: Dict[Tuple[Tuple[str, str], ...], float] = {}
        # one exemplar per series: (bucket_index, labels dict, value) —
        # rendered OpenMetrics-style on the matching bucket line (the
        # trace_stage_ms series attaches trace_ids this way)
        self._exemplars: Dict[Tuple[Tuple[str, str], ...],
                              Tuple[int, Dict[str, str], float]] = {}

    def observe(self, value: float, **labels: str) -> None:
        if not math.isfinite(value):
            # NaN/inf would poison _sum forever; drop it so count stays
            # consistent with the bucket lines (callers should catch
            # non-finite scores upstream via record_error)
            return
        key = _labels_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
                    break
            self._sums[key] = self._sums.get(key, 0.0) + float(value)
            self._maxes[key] = max(self._maxes.get(key, value), value)

    def add_bucket_deltas(self, deltas: Sequence[float], sum_delta: float,
                          max_value: Optional[float] = None,
                          exemplar: Optional[Mapping[str, Any]] = None,
                          **labels: str) -> None:
        """Merge pre-bucketed observation deltas into this histogram.

        The mirror path for externally aggregated histograms (the tracing
        plane buckets stage durations itself so its hot path never touches
        this lock): ``deltas`` must align with ``self.buckets`` (+Inf
        last) and be non-negative — the honest-counter discipline of the
        sync_* mirrors. ``exemplar`` is ``{"value": v, **labels}``; it
        replaces the series' stored exemplar and renders as a comment
        line next to the bucket the value falls in (the classic text
        format the endpoint serves has no exemplar syntax).
        """
        if len(deltas) != len(self.buckets):
            raise ValueError(
                f"{self.name}: expected {len(self.buckets)} bucket deltas "
                f"(incl. +Inf), got {len(deltas)}")
        if any(d < 0 for d in deltas) or sum_delta < 0:
            raise ValueError(f"{self.name}: bucket deltas must be >= 0")
        key = _labels_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, d in enumerate(deltas):
                counts[i] += int(d)
            self._sums[key] = self._sums.get(key, 0.0) + float(sum_delta)
            if max_value is not None:
                self._maxes[key] = max(self._maxes.get(key, max_value),
                                       float(max_value))
            if exemplar:
                ex = dict(exemplar)
                v = float(ex.pop("value"))
                idx = next((i for i, ub in enumerate(self.buckets)
                            if v <= ub), len(self.buckets) - 1)
                self._exemplars[key] = (
                    idx, {str(k): str(val) for k, val in ex.items()}, v)

    def count(self, **labels: str) -> int:
        return sum(self._counts.get(_labels_key(labels), ()))

    def sum(self, **labels: str) -> float:
        return self._sums.get(_labels_key(labels), 0.0)

    def quantile(self, q: float, **labels: str) -> float:
        """Upper bound of the hit bucket; when the mass lands in the +Inf
        bucket, the tracked max observation (never understates the tail)."""
        key = _labels_key(labels)
        counts = self._counts.get(key)
        if not counts:
            return 0.0
        total = sum(counts)
        target = q * total
        acc = 0
        for i, c in enumerate(counts):
            acc += c
            if acc >= target and c:
                return self.buckets[i] if self.buckets[i] != math.inf \
                    else self._maxes.get(key, self.buckets[-2])
        return self._maxes.get(key, self.buckets[-2])

    def render(self) -> List[str]:
        with self._lock:
            keys = sorted(self._counts) or [()]
            lines = [f"# HELP {self.name} {self.help}",
                     f"# TYPE {self.name} {self.kind}"]
            for key in keys:
                counts = self._counts.get(key, [0] * len(self.buckets))
                ex = self._exemplars.get(key)
                cum = 0
                for i, (ub, c) in enumerate(zip(self.buckets, counts)):
                    cum += c
                    lk = key + (("le", _fmt(ub)),)
                    lines.append(
                        f"{self.name}_bucket{_render_labels(lk)} {cum}")
                    if ex is not None and ex[0] == i:
                        # exemplar as a standalone comment line: the
                        # classic text format (version=0.0.4 — what the
                        # endpoint serves) has no exemplar syntax, and
                        # trailing content after a sample value fails the
                        # WHOLE scrape; a leading-# line is ignored by
                        # every Prometheus parser while staying visible
                        # to humans and log-grep tooling
                        ex_labels = ",".join(
                            f'{k}="{_escape(v)}"' for k, v in ex[1].items())
                        lines.append(
                            f"# exemplar {self.name}_bucket"
                            f"{_render_labels(lk)} {{{ex_labels}}} "
                            f"{_fmt(ex[2])}")
                lines.append(
                    f"{self.name}_sum{_render_labels(key)} "
                    f"{_fmt(self._sums.get(key, 0.0))}"
                )
                lines.append(f"{self.name}_count{_render_labels(key)} {cum}")
        return lines


class Registry:
    """Named metric collection with Prometheus text rendering."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name!r}")
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name, help_text, labelnames=()) -> Counter:
        return self.register(Counter(name, help_text, labelnames))  # type: ignore[return-value]

    def gauge(self, name, help_text, labelnames=()) -> Gauge:
        return self.register(Gauge(name, help_text, labelnames))  # type: ignore[return-value]

    def histogram(self, name, help_text, labelnames=(),
                  buckets=DEFAULT_LATENCY_BUCKETS) -> Histogram:
        return self.register(Histogram(name, help_text, labelnames, buckets))  # type: ignore[return-value]

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


class MetricsCollector:
    """Domain metrics for the scoring plane (reference metrics.py:36-432).

    Also keeps a bounded window of recent predictions so ``summary()`` can
    compute the JSON ``/metrics`` payload (throughput over the last minute,
    latency percentiles, decision mix) the way the reference's in-memory
    deques do (metrics.py:238-297) — but guarded by one lock, not three.
    """

    def __init__(self, window: int = 10_000, clock=time.monotonic) -> None:
        self.registry = Registry()
        self._clock = clock
        self._start = clock()
        self._lock = threading.Lock()
        self._recent: deque = deque(maxlen=window)  # (t, duration_s, score, decision)
        self._total = 0
        # per-second event counts for throughput: immune to the _recent cap,
        # so 50k tps reads as 50k tps even with a 10k-entry latency window
        self._sec_counts: deque = deque(maxlen=120)  # (int_second, count)

        r = self.registry
        self.predictions_total = r.counter(
            "ml_predictions_total", "Total predictions served",
            ("model", "decision"))
        self.prediction_errors = r.counter(
            "ml_prediction_errors_total", "Prediction failures", ("stage",))
        self.prediction_duration = r.histogram(
            "ml_prediction_duration_seconds", "End-to-end scoring latency")
        self.fraud_score = r.histogram(
            "ml_fraud_score", "Fraud score distribution", buckets=SCORE_BUCKETS)
        self.batch_size = r.histogram(
            "scoring_microbatch_size", "Scored microbatch sizes",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        self.batch_duration = r.histogram(
            "scoring_microbatch_duration_seconds", "Per-microbatch latency")
        self.active_models = r.gauge(
            "ml_active_models", "Number of live ensemble branches")
        self.uptime = r.gauge("ml_uptime_seconds", "Process uptime")
        self.throughput = r.gauge(
            "ml_throughput_tps", "Scored txns/sec over the last 60 s")
        self.queue_depth = r.gauge(
            "serving_queue_depth", "Requests waiting in the microbatcher")
        # QoS plane (qos/): admission, shedding, degradation ladder, and
        # per-transaction budget headroom — all on the same registry, so
        # the existing /metrics/prometheus exposition carries them
        self.qos_admitted = r.counter(
            "qos_admitted_total", "Transactions admitted by the QoS plane",
            ("priority",))
        self.qos_shed = r.counter(
            "qos_shed_total",
            "Transactions shed by admission control (explicit decisions, "
            "never silent drops)", ("priority", "reason"))
        self.qos_ladder_level = r.gauge(
            "qos_ladder_level",
            "Current degradation-ladder level (0=full ensemble, "
            "3=rules only)")
        self.qos_ladder_transitions = r.counter(
            "qos_ladder_transitions_total",
            "Degradation-ladder steps", ("direction",))
        self.qos_degraded_scored = r.counter(
            "qos_degraded_scored_total",
            "Transactions scored at a degraded ladder level", ("level",))
        self.qos_budget_remaining = r.histogram(
            "qos_budget_remaining_seconds",
            "Per-transaction latency budget remaining at completion "
            "(negative = deadline blown)",
            buckets=(-0.1, -0.02, -0.005, 0.0, 0.001, 0.0025, 0.005,
                     0.01, 0.015, 0.02, 0.05, 0.1))
        # host-assembly plane (columnar assemble + token/entity caches +
        # overlapped assembler stage): cumulative cache hit/miss counts and
        # per-stage wall-clock stats, mirrored from FraudScorer.host_stats()
        # by sync_host_stats — same registry, same Prometheus exposition
        self.host_cache_hits = r.counter(
            "host_assembly_cache_hits_total",
            "Cumulative host-assembly cache hits (token LRU, entity join "
            "rows)", ("cache",))
        self.host_cache_misses = r.counter(
            "host_assembly_cache_misses_total",
            "Cumulative host-assembly cache misses", ("cache",))
        self.host_stage_ms = r.gauge(
            "host_assembly_stage_ms",
            "Host-side per-stage timing (assemble/pack/dispatch/"
            "device_wait)", ("stage", "stat"))
        # last-mirrored cache totals, so sync_host_stats can inc the
        # counters by deltas (keeps the _total series honest counters —
        # rate()/increase() and promtool lint stay valid)
        self._host_cache_seen: Dict[Tuple[str, str], float] = {}
        # device-pool scoring plane (scoring/device_pool.py): per-device
        # dispatch/completion/retry counters, live in-flight depth and
        # cumulative queue-wait — mirrored from DevicePool.stats() by
        # sync_device_pool at exposition time, same registry/exposition
        self.pool_dispatched = r.counter(
            "device_pool_dispatched_total",
            "Microbatches dispatched to each pool replica", ("device",))
        self.pool_completed = r.counter(
            "device_pool_completed_total",
            "Microbatches completed by each pool replica", ("device",))
        self.pool_retries = r.counter(
            "device_pool_retries_total",
            "Batches rescued ONTO this replica after another replica "
            "failed mid-flight", ("device",))
        self.pool_inflight = r.gauge(
            "device_pool_inflight",
            "Batches currently in flight on each pool replica", ("device",))
        self.pool_healthy = r.gauge(
            "device_pool_healthy_replicas",
            "Replicas currently in the dispatch rotation")
        self.pool_queue_wait = r.counter(
            "device_pool_queue_wait_ms_total",
            "Cumulative milliseconds dispatch spent blocked on a replica "
            "at full in-flight depth", ("device",))
        self._pool_seen: Dict[Tuple[str, str], float] = {}
        # continuous-learning plane (feedback/): prequential quality under
        # live labels, label-join health, and the retrain/gate/promotion
        # audit counters — mirrored from FeedbackPlane.snapshot() by
        # sync_feedback at exposition time, same registry, same exposition
        self.preq_auc = r.gauge(
            "prequential_auc",
            "Streaming test-then-train AUC over matched labels",
            ("window",))
        self.preq_precision = r.gauge(
            "prequential_precision",
            "Prequential precision at the pinned operating threshold",
            ("window",))
        self.preq_recall = r.gauge(
            "prequential_recall",
            "Prequential recall at the pinned operating threshold",
            ("window",))
        self.preq_calibration = r.gauge(
            "prequential_calibration_error",
            "Expected calibration error over the sliding label window")
        self.feedback_labels = r.counter(
            "feedback_labels_total",
            "Label-join outcomes (matched / expired_unlabeled / "
            "orphan_labels / duplicate_labels)", ("outcome",))
        self.feedback_label_lag = r.gauge(
            "feedback_label_lag_seconds",
            "Mean prediction-to-label delay over matched labels")
        self.feedback_buffer = r.gauge(
            "feedback_buffer_examples",
            "Labeled-example buffer occupancy", ("klass",))
        self.feedback_triggers = r.counter(
            "feedback_retrain_triggers_total",
            "Retrain triggers fired by the policy", ("reason",))
        self.feedback_gate = r.counter(
            "feedback_gate_verdicts_total",
            "Promotion-gate verdicts on retrained candidates", ("verdict",))
        self.feedback_promotions = r.counter(
            "feedback_promotions_total",
            "Candidates promoted into the serving blend")
        # last-seen totals for the feedback counter mirrors (same honest-
        # counter delta scheme as the host-assembly caches above)
        self._feedback_seen: Dict[Tuple[str, str], float] = {}
        # tracing plane (obs/tracing.py): per-stage latency histograms
        # with exemplar trace_ids, trace terminal counters, and the SLO
        # burn-rate gauges — mirrored from Tracer.snapshot() by
        # sync_tracing at exposition time so the stream job and the
        # serving app expose IDENTICAL trace_* series
        from realtime_fraud_detection_tpu.obs.tracing import (
            TRACE_STAGE_BUCKETS_MS,
        )

        self.trace_stage_ms = r.histogram(
            "trace_stage_ms",
            "Per-transaction stage latency from the tracing plane "
            "(exemplars carry trace_ids)", ("stage",),
            buckets=TRACE_STAGE_BUCKETS_MS)
        self.trace_completed = r.counter(
            "trace_completed_total",
            "Traces closed by the flight recorder", ("terminal",))
        self.trace_slo_violations = r.counter(
            "trace_slo_violations_total",
            "Transactions that blew the SLO latency objective")
        # cross-process carrier plane: adopted = producer-stamped trace
        # contexts re-hydrated at consume time (stitched traces); lost =
        # expected-but-missing/garbled carriers degraded to fresh local
        # roots (netfault-window drops land here — counted, never a gap)
        self.trace_carrier_adopted = r.counter(
            "trace_carrier_adopted_total",
            "Producer-stamped trace carriers adopted at consume time")
        self.trace_carrier_lost = r.counter(
            "trace_carrier_lost_total",
            "Expected trace carriers missing/unparseable — degraded to "
            "fresh local roots")
        self.trace_slo_burn = r.gauge(
            "trace_slo_burn_rate",
            "SLO error-budget burn rate (1.0 = budget consumed exactly at "
            "the sustainable rate)", ("window",))
        self._trace_seen: Dict[Tuple[str, ...], Any] = {}
        # microbatcher close reasons (stream MicrobatchAssembler +
        # serving RequestMicrobatcher): why each batch handed off —
        # size/deadline/budget/timeout/flush, plus jit under autotune.
        # Mirrored from the batcher's close_reasons histogram by
        # sync_microbatch at exposition time (honest counter deltas, so
        # stream-job and serving expose identical series)
        self.microbatch_close_reason = r.counter(
            "microbatch_close_reason_total",
            "Microbatch close decisions by trigger "
            "(size/deadline/budget/timeout/flush/jit)", ("reason",))
        self._close_reason_seen: Dict[str, float] = {}
        # self-tuning plane (tuning/): arrival forecast, JIT close
        # decision mix, live knob values, tuner trial/freeze audit —
        # mirrored from TuningPlane.snapshot() by sync_autotune
        self.autotune_decisions = r.counter(
            "autotune_close_decisions_total",
            "JIT controller decisions (jit/deadline close, wait)",
            ("decision",))
        self.autotune_tuner_events = r.counter(
            "autotune_tuner_events_total",
            "Online-tuner epoch outcomes "
            "(trials/accepted/reverted/frozen_epochs)", ("event",))
        self.autotune_forecast_tps = r.gauge(
            "autotune_forecast_tps",
            "Short-horizon forecast arrival rate (txn/s)")
        self.autotune_max_wait_ms = r.gauge(
            "autotune_max_wait_ms",
            "Current tuned batch max-wait bound (ms)")
        self.autotune_bucket_set = r.gauge(
            "autotune_bucket_set",
            "Index of the bucket set the tuner currently serves")
        self.autotune_inflight_depth = r.gauge(
            "autotune_inflight_depth",
            "Overlap/in-flight depth the tuner currently recommends")
        self.autotune_frozen = r.gauge(
            "autotune_frozen",
            "1 while the tuner is frozen by the QoS ladder / SLO burn")
        self._autotune_seen: Dict[Tuple[str, str], float] = {}
        # chaos plane (chaos/): scheduled fault windows and recovery
        # accounting — mirrored from ChaosPlan.snapshot() by sync_chaos at
        # exposition time (honest counter deltas, same discipline as every
        # sync_* mirror above)
        self.chaos_fault_windows = r.counter(
            "chaos_fault_windows_total",
            "Fault windows opened by the chaos plane", ("fault",))
        self.chaos_fault_active = r.gauge(
            "chaos_fault_active",
            "1 while the named fault window is open", ("fault",))
        self.chaos_recovery_seconds = r.gauge(
            "chaos_recovery_seconds",
            "Virtual seconds from a fault window's end to observed plane "
            "recovery", ("fault",))
        self._chaos_seen: Dict[str, float] = {}
        # quantized scoring plane (models/quant.py + QuantSettings):
        # SERVED per-branch weight/kernel modes (live-params truth from
        # FraudScorer.quant_snapshot, not config — the two differ after an
        # allow_arch_mismatch restore), replicated param bytes, and the
        # score-delta oracle's verdicts — mirrored by sync_quant at
        # exposition time (honest counter deltas, same discipline as every
        # sync_* mirror above)
        self.quant_branch_mode = r.gauge(
            "quant_branch_mode",
            "1 for the weight/kernel mode each branch currently serves "
            "(f32/int8 for bert_text, gather/gemm for the tree branches)",
            ("branch", "mode"))
        self.quant_param_bytes = r.gauge(
            "quant_param_bytes",
            "Serialized parameter bytes of the quantizable branch as "
            "served (the per-replica replication / hot-swap payload)",
            ("branch",))
        self.quant_gate_verdicts = r.counter(
            "quant_gate_verdicts_total",
            "Divergence-oracle verdicts recorded against this scorer "
            "(rtfd quant-drill and any caller running the quantized-vs-"
            "f32 comparison)", ("verdict",))
        self._quant_seen: Dict[str, float] = {}
        # Pallas kernel plane (ops/ + KernelSettings): per-site effective
        # modes as exhaustive 0/1 gauges (the quant_branch_mode
        # discipline — a swap reads as a transition, not a new series),
        # whether the interpreter is serving (non-TPU hosts), and honest
        # per-site dispatch/fallback counters mirrored from
        # FraudScorer.kernel_snapshot by sync_kernels at exposition time
        self.kernel_site_mode = r.gauge(
            "kernel_site_mode",
            "1 for the kernel mode each fusion site currently serves "
            "(off/pallas for dequant_matmul and epilogue, "
            "reference/flash for attention)",
            ("site", "mode"))
        self.kernel_interpret = r.gauge(
            "kernel_interpret_active",
            "1 when the kernel plane is serving through the Pallas "
            "interpreter (non-TPU host) rather than compiled kernels")
        self.kernel_dispatches = r.counter(
            "kernel_dispatch_total",
            "Batches dispatched with this site's Pallas kernel engaged",
            ("site",))
        self.kernel_fallbacks = r.counter(
            "kernel_fallback_total",
            "Batches where this site's kernel was requested but the "
            "shape/param-form guard fell back to the XLA lowering",
            ("site",))
        self._kernel_seen: Dict[str, Dict[str, float]] = {
            "dispatch": {}, "fallback": {}}
        # megakernel plane (ops/megakernel.py): the persistent whole-batch
        # program gets dedicated counters beside its generic site series
        # (kernel_dispatch_total{site="megakernel"} carries the same
        # number — these exist so dashboards can alert on the ONE site
        # that collapses the launch chain without a label join), plus the
        # launch-count gauge the fusion claim is measured by
        self.kernel_mega_dispatch = r.counter(
            "kernel_mega_dispatch_total",
            "Batches dispatched with the persistent megakernel engaged "
            "(one program serving every branch plus the epilogue)")
        self.kernel_mega_fallback = r.counter(
            "kernel_mega_fallback_total",
            "Batches where the megakernel was requested but its shape/"
            "VMEM plan declined and the per-site kernel chain served "
            "instead")
        self.kernel_launches_per_batch = r.gauge(
            "kernel_launches_per_batch",
            "Device programs launched for the most recent scoring "
            "microbatch (1 when the megakernel served it; the per-site "
            "chain length otherwise)")
        self._mega_seen: Dict[str, float] = {}
        # partition-parallel worker plane (cluster/): fleet membership,
        # partition ownership, checkpointed-handoff accounting, and the
        # serving router's key-movement ledger — mirrored from
        # WorkerFleet.snapshot() (stream side) or the serving app's
        # router snapshot by sync_cluster at exposition time (honest
        # counter deltas, same discipline as every sync_* mirror above)
        self.cluster_workers_alive = r.gauge(
            "cluster_workers_alive",
            "Fleet workers currently alive (in the hash ring)")
        self.cluster_partitions_owned = r.gauge(
            "cluster_partitions_owned",
            "Transaction-topic partitions each worker currently owns "
            "(state ownership == consumption ownership)", ("worker",))
        self.cluster_handoff = r.counter(
            "cluster_handoff_total",
            "Partitions handed off to a surviving worker after a worker "
            "loss (restore + committed-gap state replay)")
        self.cluster_handoff_replay_depth = r.gauge(
            "cluster_handoff_replay_depth",
            "Records state-replayed during the most recent handoff "
            "(committed offset minus snapshot offset, summed over the "
            "moved partitions)")
        self.cluster_router_moved_keys = r.counter(
            "cluster_router_moved_keys_total",
            "Keys (partition moves x key density) the consistent-hash "
            "serving router re-routed across membership changes")
        self._cluster_seen: Dict[str, float] = {}
        # elastic process fleet (cluster/autoscale.py + handoff.py):
        # forecast-driven target worker count, scale events, and the
        # network handoff server's checkpoint/restore/torn-blob ledger —
        # mirrored from AutoscaleController.snapshot() (+ the fleet's
        # HandoffClient.stats()) by sync_autoscale at exposition time
        # (honest counter deltas, same discipline as every sync_* mirror)
        self.autoscale_target_workers = r.gauge(
            "autoscale_target_workers",
            "Worker-count target the autoscale controller currently "
            "wants (forecast lead x headroom / per-worker capacity)")
        self.autoscale_forecast_rate = r.gauge(
            "autoscale_forecast_rate",
            "Arrival-rate estimate (txn/s) behind the current target")
        self.autoscale_events = r.counter(
            "autoscale_events_total",
            "Autoscale target changes by direction (up = spawn + restore "
            "+ replay, down = graceful drain)", ("direction",))
        self.handoff_server_checkpoints = r.counter(
            "handoff_server_checkpoints_total",
            "Partition snapshots committed to the network handoff store "
            "(temp->fsync->rename, sha256-stamped)")
        self.handoff_server_restores = r.counter(
            "handoff_server_restores_total",
            "Verified snapshot restores served to partition inheritors")
        self.handoff_server_torn_blobs = r.counter(
            "handoff_server_torn_blobs_total",
            "Checkpoint blobs that failed sha256 verification on restore "
            "(the previous checkpoint was served instead)")
        self._autoscale_seen: Dict[str, float] = {}
        # mesh-sharded scoring plane (scoring/mesh_executor.py): mesh
        # geometry, per-branch placement as exhaustive 0/1 gauges (a
        # placement flip reads as a transition, not a new series — the
        # quant_branch_mode discipline), per-chip vs replicated param
        # bytes read from the COMMITTED shardings, and per-mesh-replica
        # dispatch counters — mirrored from MeshExecutor.mesh_snapshot()
        # by sync_mesh at exposition time (honest counter deltas, same
        # discipline as every sync_* mirror above)
        self.mesh_data_axis = r.gauge(
            "mesh_data_axis_size",
            "Data-parallel axis size of each serving mesh replica")
        self.mesh_model_axis = r.gauge(
            "mesh_model_axis_size",
            "Model-parallel axis size of each serving mesh replica")
        self.mesh_replica_count = r.gauge(
            "mesh_replica_count",
            "Mesh replicas in the executor's round-robin rotation "
            "(pool x mesh: replicate the mesh, not the chip)")
        self.mesh_branch_sharded = r.gauge(
            "mesh_branch_sharded",
            "1 when the branch's params store sharded over the model "
            "axis, 0 when replicated (exhaustive over the registry)",
            ("branch",))
        self.mesh_param_bytes = r.gauge(
            "mesh_param_bytes_per_chip",
            "Max per-chip resident param bytes for each branch as "
            "committed on mesh replica 0 (the HBM the placement actually "
            "buys)", ("branch",))
        self.mesh_param_bytes_replicated = r.gauge(
            "mesh_param_bytes_replicated",
            "Replicated-equivalent param bytes per branch (what a pure "
            "DevicePool replica would hold) — the denominator of the "
            "sharding win", ("branch",))
        self.mesh_dispatched = r.counter(
            "mesh_dispatched_total",
            "Microbatches dispatched to each mesh replica", ("replica",))
        self.mesh_completed = r.counter(
            "mesh_completed_total",
            "Microbatches completed by each mesh replica", ("replica",))
        self._mesh_seen: Dict[Tuple[str, str], float] = {}
        # network fault plane (chaos/netfaults.py) + broker producer-
        # generation fencing (stream/netbroker.py): per-link injected
        # fault effects and the broker's refused-write counters —
        # mirrored from LinkFaultPlane.snapshot() (optionally carrying a
        # broker fencing block) by sync_netfaults at exposition time
        # (honest counter deltas, same discipline as every sync_* mirror
        # above)
        self.netfault_link_active = r.gauge(
            "netfault_link_active",
            "1 while any fault (partition/degrade) is armed on the named "
            "link", ("link",))
        self.netfault_windows = r.counter(
            "netfault_windows_total",
            "Fault windows begun on the named link", ("link",))
        self.netfault_delayed_sends = r.counter(
            "netfault_delayed_sends_total",
            "Frames delayed by injected latency on the named link",
            ("link",))
        self.netfault_dropped_sends = r.counter(
            "netfault_dropped_sends_total",
            "Frames dropped (bounded drop-then-reconnect) on the named "
            "link", ("link",))
        self.netfault_partitioned_sends = r.counter(
            "netfault_partitioned_sends_total",
            "Frames refused at send by a full partition on the named "
            "link", ("link",))
        self.netfault_lost_responses = r.counter(
            "netfault_lost_responses_total",
            "Responses lost to a one-way partition on the named link "
            "(the op was APPLIED peer-side; retries may duplicate)",
            ("link",))
        self.netfault_throttled_bytes = r.counter(
            "netfault_throttled_bytes_total",
            "Bytes paced by slow-link throttling on the named link",
            ("link",))
        self.fenced_produce = r.counter(
            "fenced_produce_total",
            "Stamped produces the broker refused because the target "
            "partition was fenced at a newer assignment generation "
            "(StaleGenerationError — the zombie-writer fence)")
        self.fenced_commit = r.counter(
            "fenced_commit_total",
            "Stamped offset commits the broker refused at the "
            "generation fence (a zombie's commit must not advance the "
            "group past refused predictions)")
        self._netfault_seen: Dict[Tuple[str, str], float] = {}
        # entity-graph plane (graph/): typed-store occupancy, sampler
        # cache effectiveness, and the cross-partition fetch client's
        # resolution/degrade ledger — mirrored from
        # FraudScorer.graph_snapshot() by sync_graph at exposition time
        # (honest counter deltas, same discipline as every sync_* mirror
        # above)
        self.graph_typed_mode = r.gauge(
            "graph_typed_mode",
            "1 while the scorer assembles typed entity-graph "
            "neighborhoods (graph/ plane), 0 on the bipartite "
            "user<->merchant store")
        self.graph_nodes = r.gauge(
            "graph_nodes",
            "Typed-graph nodes resident by node type (partitioned "
            "stores report the sum of owned-partition shards)",
            ("type",))
        self.graph_edges = r.gauge(
            "graph_edges",
            "Typed-graph ring entries resident by directed edge type",
            ("edge",))
        self.graph_edges_added = r.counter(
            "graph_edges_added_total",
            "Entity links ingested into the typed graph at finalize "
            "time (both directions of one link count once)")
        self.graph_sampler_cache_hits = r.counter(
            "graph_sampler_cache_hits_total",
            "Neighborhood-sampler cache hits (center sample reused)")
        self.graph_sampler_cache_misses = r.counter(
            "graph_sampler_cache_misses_total",
            "Neighborhood-sampler cache misses (center sample rebuilt)")
        self.graph_sampler_cache_evictions = r.counter(
            "graph_sampler_cache_evictions_total",
            "Sampler cache entries evicted (adjacency-dependency dirt, "
            "age-out, ownership-epoch clear, or the capacity cap)")
        self.graph_sampler_entries = r.gauge(
            "graph_sampler_entries",
            "Center samples currently resident in the sampler cache")
        self.graph_remote_fetch = r.counter(
            "graph_remote_fetch_total",
            "Cross-partition neighbor-fetch requests sent to peer "
            "workers")
        self.graph_remote_nodes = r.counter(
            "graph_remote_nodes_total",
            "Node adjacency entries received from peer workers")
        self.graph_fetch_deadline = r.counter(
            "graph_fetch_deadline_total",
            "Microbatches whose remote resolution hit the per-batch "
            "deadline (degraded to the local subgraph)")
        self.graph_fetch_errors = r.counter(
            "graph_fetch_errors_total",
            "Failed/refused peer fetch calls (connection errors, "
            "netfault windows, backoff-gated skips)")
        self.graph_fetch_budget_exhausted = r.counter(
            "graph_fetch_budget_exhausted_total",
            "Microbatches whose remote resolution hit the per-batch "
            "node budget (partial remote view, counted as degraded)")
        self.graph_fetch_stale_generation = r.counter(
            "graph_fetch_stale_generation_total",
            "Peer fetches refused at the server's assignment-generation "
            "fence (stale requester — degraded, refreshed on rebalance "
            "adoption)")
        self.graph_degraded_batches = r.counter(
            "graph_degraded_batches_total",
            "Microbatches scored with a degraded (partial or local-only) "
            "neighbor view for ANY reason — deadline, budget, netfault, "
            "fenced generation")
        self._graph_seen: Dict[str, float] = {}

    def sync_host_stats(self, host_stats: Mapping[str, Any]) -> None:
        """Mirror ``FraudScorer.host_stats()`` into the Prometheus series.

        Called at exposition time so the scorer's hot path never touches
        the metrics lock per record. Cache totals mirror as counter
        DELTAS against the last-seen values (a scorer swap that resets its
        counters contributes 0 until it catches up — the standard
        counter-mirror compromise, never a negative increment)."""
        for name, st in (host_stats.get("caches") or {}).items():
            for kind, counter in (("hits", self.host_cache_hits),
                                  ("misses", self.host_cache_misses)):
                total = float(st.get(kind, 0))
                key = (name, kind)
                delta = total - self._host_cache_seen.get(key, 0.0)
                if delta > 0:
                    counter.inc(delta, cache=name)
                self._host_cache_seen[key] = total
        for stage, st in (host_stats.get("stages") or {}).items():
            for stat in ("mean_ms", "p50_ms", "p99_ms"):
                self.host_stage_ms.set(float(st.get(stat, 0.0)),
                                       stage=stage,
                                       stat=stat.replace("_ms", ""))

    def sync_device_pool(self, stats: Mapping[str, Any]) -> None:
        """Mirror ``DevicePool.stats()`` into the Prometheus series.

        Called at exposition time (the pool's hot path never touches the
        metrics lock); cumulative counters mirror as deltas against
        last-seen values — the same honest-counter scheme as
        sync_host_stats."""
        for dev in stats.get("devices") or ():
            name = str(dev.get("device", dev.get("index", "?")))
            for kind, counter in (("dispatched", self.pool_dispatched),
                                  ("completed", self.pool_completed),
                                  ("retries", self.pool_retries),
                                  ("queue_wait_ms", self.pool_queue_wait)):
                total = float(dev.get(kind, 0))
                key = (name, kind)
                delta = total - self._pool_seen.get(key, 0.0)
                if delta > 0:
                    counter.inc(delta, device=name)
                self._pool_seen[key] = total
            self.pool_inflight.set(float(dev.get("inflight", 0)),
                                   device=name)
        self.pool_healthy.set(float(stats.get("healthy", 0)))

    def sync_feedback(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``FeedbackPlane.snapshot()`` into the Prometheus
        series. Called at exposition time (cheap gauge sets); cumulative
        plane counters mirror as counter deltas against last-seen values
        (never a negative increment), matching sync_host_stats."""
        preq = snapshot.get("prequential") or {}
        for window in ("sliding", "fading"):
            w = preq.get(window) or {}
            for key, gauge in (("auc", self.preq_auc),
                               ("precision", self.preq_precision),
                               ("recall", self.preq_recall)):
                v = w.get(key)
                if v is not None and math.isfinite(float(v)):
                    gauge.set(float(v), window=window)
        ce = (preq.get("sliding") or {}).get("calibration_error")
        if ce is not None and math.isfinite(float(ce)):
            self.preq_calibration.set(float(ce))
        self.feedback_label_lag.set(float(preq.get("mean_label_lag_s", 0.0)))
        buf = snapshot.get("buffer") or {}
        self.feedback_buffer.set(float(buf.get("positives", 0)),
                                 klass="positive")
        self.feedback_buffer.set(float(buf.get("negatives", 0)),
                                 klass="negative")

        def _mirror(counter, group: str, key: str, total: float,
                    **labels: str) -> None:
            seen_key = (group, key)
            delta = float(total) - self._feedback_seen.get(seen_key, 0.0)
            if delta > 0:
                counter.inc(delta, **labels)
            self._feedback_seen[seen_key] = float(total)

        join = snapshot.get("label_join") or {}
        for outcome in ("matched", "expired_unlabeled", "orphan_labels",
                        "duplicate_labels"):
            _mirror(self.feedback_labels, "join", outcome,
                    join.get(outcome, 0), outcome=outcome)
        policy = snapshot.get("policy") or {}
        _mirror(self.feedback_gate, "gate", "pass",
                policy.get("gate_pass", 0), verdict="pass")
        _mirror(self.feedback_gate, "gate", "fail",
                policy.get("gate_fail", 0), verdict="fail")
        _mirror(self.feedback_promotions, "promotions", "total",
                policy.get("promotions", 0))
        _mirror(self.feedback_triggers, "triggers", "total",
                policy.get("triggers", 0), reason="any")

    def sync_tracing(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``Tracer.snapshot()`` into the Prometheus series.

        Called at exposition time (the tracing hot path never touches the
        metrics lock); every cumulative quantity mirrors as a DELTA
        against last-seen values — the same honest-counter discipline as
        sync_feedback/sync_device_pool, so the stream job and the serving
        app expose identical, rate()-valid trace_* series. The tracer
        buckets stage durations with TRACE_STAGE_BUCKETS_MS, matching
        ``trace_stage_ms`` exactly, so the histogram mirror is a pure
        bucket-count delta (plus the latest slowest-sample exemplar)."""
        for stage, st in (snapshot.get("stages") or {}).items():
            counts = list(st.get("bucket_counts") or ())
            if len(counts) != len(self.trace_stage_ms.buckets):
                continue
            seen_key = ("stage", stage)
            prev = self._trace_seen.get(seen_key)
            prev_counts = (prev or {}).get(
                "bucket_counts", [0] * len(counts))
            deltas = [max(0, c - p) for c, p in zip(counts, prev_counts)]
            sum_delta = max(0.0, float(st.get("sum_ms", 0.0))
                            - float((prev or {}).get("sum_ms", 0.0)))
            if any(deltas) or sum_delta > 0:
                ex = st.get("exemplar") or None
                self.trace_stage_ms.add_bucket_deltas(
                    deltas, sum_delta, max_value=st.get("max_ms"),
                    exemplar=({"value": ex["ms"],
                               "trace_id": ex["trace_id"]} if ex else None),
                    stage=stage)
            self._trace_seen[seen_key] = {
                "bucket_counts": counts,
                "sum_ms": float(st.get("sum_ms", 0.0))}
        counters = snapshot.get("counters") or {}
        for key, terminal in (("completed", "scored"), ("shed", "shed"),
                              ("errors", "error"), ("cached", "cached")):
            total = counters.get(key, 0)
            seen_key = ("terminal", terminal)
            delta = float(total) - float(self._trace_seen.get(seen_key, 0.0))
            if delta > 0:
                self.trace_completed.inc(delta, terminal=terminal)
            self._trace_seen[seen_key] = float(total)
        for key, counter in (("carrier_adopted", self.trace_carrier_adopted),
                             ("carrier_lost", self.trace_carrier_lost)):
            total = counters.get(key, 0)
            seen_key = ("carrier", key)
            delta = float(total) - float(self._trace_seen.get(seen_key, 0.0))
            if delta > 0:
                counter.inc(delta)
            self._trace_seen[seen_key] = float(total)
        slo = snapshot.get("slo") or {}
        seen_key = ("slo", "violations")
        total = float(slo.get("violations_total", 0))
        delta = total - float(self._trace_seen.get(seen_key, 0.0))
        if delta > 0:
            self.trace_slo_violations.inc(delta)
        self._trace_seen[seen_key] = total
        for window, w in (slo.get("windows") or {}).items():
            burn = w.get("burn_rate")
            if burn is not None and math.isfinite(float(burn)):
                self.trace_slo_burn.set(float(burn), window=window)

    def sync_microbatch(self, close_reasons: Mapping[str, int]) -> None:
        """Mirror a batcher's cumulative close-reason histogram
        (``MicrobatchAssembler.close_reasons`` /
        ``RequestMicrobatcher.close_reasons``) into
        ``microbatch_close_reason_total``. Called at exposition time —
        the batch-close hot path only ever bumps a plain dict — and
        mirrored as counter DELTAS against last-seen values (the
        honest-counter scheme every sync_* mirror here uses), so the
        stream job and the serving app expose identical series."""
        for reason, total in (close_reasons or {}).items():
            delta = float(total) - self._close_reason_seen.get(reason, 0.0)
            if delta > 0:
                self.microbatch_close_reason.inc(delta, reason=str(reason))
            self._close_reason_seen[reason] = float(total)

    def sync_autotune(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``TuningPlane.snapshot()`` into the autotune_*
        series. Called at exposition time; cumulative counters mirror as
        deltas against last-seen values — never a negative increment."""
        ctrl = snapshot.get("controller") or {}
        for decision, total in (ctrl.get("decisions") or {}).items():
            key = ("decision", str(decision))
            delta = float(total) - self._autotune_seen.get(key, 0.0)
            if delta > 0:
                self.autotune_decisions.inc(delta, decision=str(decision))
            self._autotune_seen[key] = float(total)
        tuner = snapshot.get("tuner") or {}
        for event in ("trials", "accepted", "reverted", "frozen_epochs"):
            total = (tuner.get("counters") or {}).get(event, 0)
            key = ("tuner", event)
            delta = float(total) - self._autotune_seen.get(key, 0.0)
            if delta > 0:
                self.autotune_tuner_events.inc(delta, event=event)
            self._autotune_seen[key] = float(total)
        self.autotune_forecast_tps.set(
            float(snapshot.get("forecast_tps", 0.0)))
        self.autotune_max_wait_ms.set(float(ctrl.get("max_wait_ms", 0.0)))
        self.autotune_bucket_set.set(float(tuner.get("bucket_set_idx", 0)))
        self.autotune_inflight_depth.set(
            float(tuner.get("inflight_depth", 0)))
        self.autotune_frozen.set(1.0 if tuner.get("frozen") else 0.0)

    def sync_chaos(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``chaos.ChaosPlan.snapshot()`` into the chaos_*
        series. Called at exposition time (the plan's poll path never
        touches the metrics lock); window-open counts mirror as deltas
        against last-seen values — the same honest-counter scheme as
        every other sync_* mirror."""
        for w in snapshot.get("windows") or ():
            fault = str(w.get("fault", "?"))
            opened = 1.0 if w.get("begun") else 0.0
            delta = opened - self._chaos_seen.get(fault, 0.0)
            if delta > 0:
                self.chaos_fault_windows.inc(delta, fault=fault)
            self._chaos_seen[fault] = opened
            self.chaos_fault_active.set(
                1.0 if w.get("active") else 0.0, fault=fault)
        for fault, rec_s in (snapshot.get("recovery_s") or {}).items():
            self.chaos_recovery_seconds.set(float(rec_s), fault=str(fault))

    def sync_quant(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``FraudScorer.quant_snapshot()`` into the quant_*
        series. Called at exposition time; the scorer's cumulative gate
        ledger mirrors as counter DELTAS against last-seen values (the
        honest-counter scheme every sync_* mirror here uses), so a stream
        job and a serving app syncing the same snapshot expose IDENTICAL
        series. Branch-mode gauges are exhaustive over the valid modes
        (the inactive mode reads 0, so a flip is visible as a transition,
        not a new series appearing)."""
        from realtime_fraud_detection_tpu.utils.config import (
            VALID_BERT_WEIGHTS,
            VALID_TREE_KERNELS,
        )

        modes = snapshot.get("modes") or {}
        valid_by_branch = {"bert_text": VALID_BERT_WEIGHTS,
                           "xgboost_primary": VALID_TREE_KERNELS,
                           "isolation_forest": VALID_TREE_KERNELS}
        for branch, served in modes.items():
            for mode in valid_by_branch.get(branch, (served,)):
                self.quant_branch_mode.set(
                    1.0 if mode == served else 0.0,
                    branch=str(branch), mode=str(mode))
        for branch, nbytes in (snapshot.get("param_bytes") or {}).items():
            self.quant_param_bytes.set(float(nbytes), branch=str(branch))
        for verdict, total in (snapshot.get("gate") or {}).items():
            delta = float(total) - self._quant_seen.get(verdict, 0.0)
            if delta > 0:
                self.quant_gate_verdicts.inc(delta, verdict=str(verdict))
            self._quant_seen[verdict] = float(total)

    def sync_kernels(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``FraudScorer.kernel_snapshot()`` into the kernel_*
        series. Called at exposition time; per-site mode gauges are
        exhaustive over the valid modes (an off site reads mode="off"=1,
        so a kernel swap is a visible transition, never a new series),
        and the scorer's cumulative dispatch/fallback counts mirror as
        counter DELTAS against last-seen values — the honest-counter
        scheme every sync_* mirror here uses — so a stream job and a
        serving app syncing the same snapshot render IDENTICAL series."""
        from realtime_fraud_detection_tpu.utils.config import (
            VALID_ATTENTION_KERNELS,
            VALID_KERNEL_MODES,
            VALID_KERNEL_SITES,
        )

        modes = snapshot.get("modes") or {}
        for site in VALID_KERNEL_SITES:
            served = modes.get(site)
            valid = (VALID_ATTENTION_KERNELS if site == "attention"
                     else VALID_KERNEL_MODES)
            for mode in valid:
                self.kernel_site_mode.set(
                    1.0 if mode == served else 0.0,
                    site=str(site), mode=str(mode))
        self.kernel_interpret.set(
            1.0 if snapshot.get("interpret") else 0.0)
        for kind, counter in (("dispatch", self.kernel_dispatches),
                              ("fallback", self.kernel_fallbacks)):
            seen = self._kernel_seen[kind]
            for site, total in (snapshot.get(kind) or {}).items():
                delta = float(total) - seen.get(site, 0.0)
                if delta > 0:
                    counter.inc(delta, site=str(site))
                seen[site] = float(total)
        # dedicated megakernel series: same snapshot numbers, own deltas
        # (so a dashboard alerting on the launch-collapsing site never
        # needs a label join), plus the launch-count gauge
        for kind, counter in (("dispatch", self.kernel_mega_dispatch),
                              ("fallback", self.kernel_mega_fallback)):
            total = float((snapshot.get(kind) or {}).get("megakernel", 0.0))
            delta = total - self._mega_seen.get(kind, 0.0)
            if delta > 0:
                counter.inc(delta)
            self._mega_seen[kind] = total
        self.kernel_launches_per_batch.set(
            float(snapshot.get("launches_per_batch", 0)))

    def sync_mesh(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``MeshExecutor.mesh_snapshot()`` into the mesh_*
        series. Called at exposition time (the executor's dispatch path
        never touches the metrics lock); the cumulative per-replica
        dispatch/completion counts mirror as counter DELTAS against
        last-seen values — the honest-counter scheme every sync_* mirror
        here uses — so a stream job and a serving app syncing the same
        snapshot render IDENTICAL series."""
        self.mesh_data_axis.set(float(snapshot.get("data_axis", 0)))
        self.mesh_model_axis.set(float(snapshot.get("model_axis", 0)))
        self.mesh_replica_count.set(float(snapshot.get("replicas", 0)))
        for branch, placement in (snapshot.get("placement") or {}).items():
            self.mesh_branch_sharded.set(
                1.0 if placement == "sharded" else 0.0, branch=str(branch))
        for branch, pb in (snapshot.get("param_bytes") or {}).items():
            self.mesh_param_bytes.set(float(pb.get("per_chip", 0)),
                                      branch=str(branch))
            self.mesh_param_bytes_replicated.set(
                float(pb.get("replicated", 0)), branch=str(branch))
        for kind, counter in (("dispatched", self.mesh_dispatched),
                              ("completed", self.mesh_completed)):
            for replica, total in (snapshot.get(kind) or {}).items():
                key = (kind, str(replica))
                delta = float(total) - self._mesh_seen.get(key, 0.0)
                if delta > 0:
                    counter.inc(delta, replica=str(replica))
                self._mesh_seen[key] = float(total)

    def sync_netfaults(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``chaos.netfaults.LinkFaultPlane.snapshot()`` —
        optionally carrying a broker ``fencing`` block (the
        ``fenced_*_total`` counters from ``NetBrokerClient.status()`` /
        ``InMemoryBroker.producer_fence_stats()``) — into the
        netfault_* / fenced_* series. Called at exposition time; the
        links' cumulative effect counts mirror as counter DELTAS against
        last-seen values (never a negative increment), so a stream-job
        and a serving app syncing the same snapshot render IDENTICAL
        series."""
        for link, entry in (snapshot.get("links") or {}).items():
            link = str(link)
            self.netfault_link_active.set(
                1.0 if entry.get("active") else 0.0, link=link)
            for field, counter in (
                    ("windows_begun", self.netfault_windows),
                    ("delayed_sends_total", self.netfault_delayed_sends),
                    ("dropped_sends_total", self.netfault_dropped_sends),
                    ("partitioned_sends_total",
                     self.netfault_partitioned_sends),
                    ("lost_responses_total",
                     self.netfault_lost_responses),
                    ("throttled_bytes_total",
                     self.netfault_throttled_bytes)):
                total = float(entry.get(field, 0))
                key = (link, field)
                delta = total - self._netfault_seen.get(key, 0.0)
                if delta > 0:
                    counter.inc(delta, link=link)
                self._netfault_seen[key] = total
        fencing = snapshot.get("fencing") or {}
        for field, counter in (
                ("fenced_produces_total", self.fenced_produce),
                ("fenced_commits_total", self.fenced_commit)):
            if field not in fencing:
                continue
            total = float(fencing.get(field, 0))
            key = ("fencing", field)
            delta = total - self._netfault_seen.get(key, 0.0)
            if delta > 0:
                counter.inc(delta)
            self._netfault_seen[key] = total

    def sync_graph(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``FraudScorer.graph_snapshot()`` into the graph_*
        series. Called at exposition time (the sampler's score path
        never touches the metrics lock); cumulative store/sampler/fetch
        counts mirror as counter DELTAS against last-seen values — the
        honest-counter scheme every sync_* mirror here uses — so a
        stream job and a serving app syncing the same snapshot render
        IDENTICAL series. Bipartite-mode snapshots carry only ``mode``;
        the typed series keep their last mirrored values."""
        self.graph_typed_mode.set(
            1.0 if snapshot.get("mode") == "typed" else 0.0)
        store = snapshot.get("store") or {}
        for ntype, count in (store.get("nodes") or {}).items():
            self.graph_nodes.set(float(count), type=str(ntype))
        for edge, count in (store.get("edges") or {}).items():
            self.graph_edges.set(float(count), edge=str(edge))

        def delta(key: str, total: Any, counter: Counter) -> None:
            total = float(total)
            d = total - self._graph_seen.get(key, 0.0)
            if d > 0:
                counter.inc(d)
            self._graph_seen[key] = total

        if "edges_added" in store:
            delta("edges_added", store["edges_added"],
                  self.graph_edges_added)
        sampler = snapshot.get("sampler") or {}
        if sampler:
            delta("hits", sampler.get("hits", 0),
                  self.graph_sampler_cache_hits)
            delta("misses", sampler.get("misses", 0),
                  self.graph_sampler_cache_misses)
            delta("evictions", sampler.get("evictions", 0),
                  self.graph_sampler_cache_evictions)
            self.graph_sampler_entries.set(
                float(sampler.get("entries", 0)))
        fetch = snapshot.get("fetch") or {}
        if fetch:
            delta("remote_fetch", fetch.get("remote_fetch_total", 0),
                  self.graph_remote_fetch)
            delta("remote_nodes", fetch.get("fetched_nodes_total", 0),
                  self.graph_remote_nodes)
            delta("deadline", fetch.get("fetch_deadline_total", 0),
                  self.graph_fetch_deadline)
            delta("errors", fetch.get("fetch_error_total", 0),
                  self.graph_fetch_errors)
            delta("budget", fetch.get("budget_exhausted_total", 0),
                  self.graph_fetch_budget_exhausted)
            delta("stale", fetch.get("stale_generation_total", 0),
                  self.graph_fetch_stale_generation)
            delta("degraded", fetch.get("degraded_batches_total", 0),
                  self.graph_degraded_batches)

    def sync_cluster(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror a ``cluster.fleet.WorkerFleet.snapshot()`` (stream
        side) or the serving app's router snapshot into the cluster_*
        series. Called at exposition time; cumulative quantities mirror
        as counter DELTAS against last-seen values (never a negative
        increment), so a stream job and a serving app syncing the same
        snapshot render IDENTICAL series. Router-only snapshots simply
        lack the handoff ledger — those series stay at their last
        mirrored values."""
        self.cluster_workers_alive.set(
            float(snapshot.get("workers_alive", 0)))
        for wid, w in (snapshot.get("workers") or {}).items():
            self.cluster_partitions_owned.set(
                float(w.get("partitions_owned", 0)), worker=str(wid))
        if "handoffs_total" in snapshot:
            total = float(snapshot.get("handoffs_total", 0))
            delta = total - self._cluster_seen.get("handoffs", 0.0)
            if delta > 0:
                self.cluster_handoff.inc(delta)
            self._cluster_seen["handoffs"] = total
            self.cluster_handoff_replay_depth.set(
                float(snapshot.get("last_replay_depth", 0)))
        router = snapshot.get("router") or {}
        if "moved_keys_total" in router:
            total = float(router.get("moved_keys_total", 0))
            delta = total - self._cluster_seen.get("router_moved", 0.0)
            if delta > 0:
                self.cluster_router_moved_keys.inc(delta)
            self._cluster_seen["router_moved"] = total

    def sync_autoscale(self, snapshot: Mapping[str, Any]) -> None:
        """Mirror an ``cluster.autoscale.AutoscaleController.snapshot()``
        — optionally carrying a ``handoff_server`` stats block
        (``HandoffServer.stats()`` / ``HandoffClient.stats()``) — into
        the autoscale_* / handoff_server_* series. Called at exposition
        time; cumulative quantities mirror as counter DELTAS against
        last-seen values (never a negative increment), so a stream-side
        coordinator and a serving app syncing the same snapshot render
        IDENTICAL series."""
        self.autoscale_target_workers.set(
            float(snapshot.get("target_workers", 0)))
        self.autoscale_forecast_rate.set(
            float(snapshot.get("forecast_rate", 0.0)))
        for direction in ("up", "down"):
            total = float((snapshot.get("events") or {}).get(direction, 0))
            key = f"events:{direction}"
            delta = total - self._autoscale_seen.get(key, 0.0)
            if delta > 0:
                self.autoscale_events.inc(delta, direction=direction)
            self._autoscale_seen[key] = total
        hs = snapshot.get("handoff_server") or {}
        for field, counter in (
                ("checkpoints_total", self.handoff_server_checkpoints),
                ("restores_total", self.handoff_server_restores),
                ("torn_blobs_total", self.handoff_server_torn_blobs)):
            if field not in hs:
                continue
            total = float(hs.get(field, 0))
            delta = total - self._autoscale_seen.get(field, 0.0)
            if delta > 0:
                counter.inc(delta)
            self._autoscale_seen[field] = total

    # ------------------------------------------------------------- recording
    def record_prediction(self, decision: str, fraud_score: float,
                          duration_s: float,
                          model_predictions: Optional[Mapping[str, float]] = None,
                          ) -> None:
        self.predictions_total.inc(model="ensemble", decision=decision)
        for name in (model_predictions or {}):
            self.predictions_total.inc(model=name, decision=decision)
        self.prediction_duration.observe(duration_s)
        self.fraud_score.observe(fraud_score)
        now = self._clock()
        with self._lock:
            self._recent.append((now, duration_s, fraud_score, decision))
            self._total += 1
            sec = int(now)
            if self._sec_counts and self._sec_counts[-1][0] == sec:
                self._sec_counts[-1][1] += 1
            else:
                self._sec_counts.append([sec, 1])

    def record_batch(self, size: int, duration_s: float) -> None:
        self.batch_size.observe(size)
        self.batch_duration.observe(duration_s)

    def record_error(self, stage: str = "predict") -> None:
        self.prediction_errors.inc(stage=stage)

    # ------------------------------------------------------------- summaries
    def summary(self) -> Dict[str, Any]:
        """JSON metrics payload (reference ``GET /metrics``, main.py:268-288)."""
        now = self._clock()
        self.uptime.set(now - self._start)
        with self._lock:
            recent = list(self._recent)
            in_window = sum(c for s, c in self._sec_counts if now - s <= 60.0)
        tps = in_window / 60.0
        self.throughput.set(tps)
        durations = sorted(r[1] for r in recent)
        decisions: Dict[str, int] = {}
        for _, _, _, d in recent:
            decisions[d] = decisions.get(d, 0) + 1

        def pct(q: float) -> float:
            if not durations:
                return 0.0
            return durations[min(int(q * len(durations)), len(durations) - 1)]

        return {
            "uptime_seconds": now - self._start,
            "total_predictions": self._total,
            "recent_predictions": len(recent),
            "throughput_tps_60s": tps,
            "latency_ms": {
                "p50": pct(0.50) * 1e3,
                "p95": pct(0.95) * 1e3,
                "p99": pct(0.99) * 1e3,
            },
            "avg_fraud_score": (
                sum(r[2] for r in recent) / len(recent) if recent else 0.0),
            "decision_counts": decisions,
            "errors": int(self.prediction_errors.total()),
        }

    def render_prometheus(self) -> str:
        self.uptime.set(self._clock() - self._start)
        return self.registry.render()

    def reset(self) -> None:
        """Drop windowed state (reference reset_metrics, metrics.py:403-417)."""
        with self._lock:
            self._recent.clear()
            self._sec_counts.clear()

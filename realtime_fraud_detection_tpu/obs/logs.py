"""Structured logging: console + rotating JSON file, domain helpers.

Parity with the reference's logging stack (logging_config.py:11-219): a
``dictConfig``-driven setup with a human console handler and a rotating JSON
file handler, plus structured helper functions (``log_prediction_result`` et
al.). JSON encoding is a stdlib formatter here — no ``pythonjsonlogger``
dependency.
"""

from __future__ import annotations

import json
import logging
import logging.config
import logging.handlers
import time
from typing import Any, Dict, Mapping, Optional

__all__ = [
    "JsonFormatter",
    "setup_logging",
    "log_prediction_result",
    "log_batch_scored",
    "log_model_event",
]

_RESERVED = set(logging.LogRecord(
    "", 0, "", 0, "", (), None).__dict__) | {"message", "asctime"}


class JsonFormatter(logging.Formatter):
    """One JSON object per line; extra record attrs become fields."""

    def __init__(self, service_name: str = ""):
        super().__init__()
        # config.service_name (reference logging_config.py service field):
        # lets one log pipeline multiplex scorer/stream-job/state-server
        self.service_name = service_name

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        if self.service_name:
            out["service"] = self.service_name
        # log/trace correlation: while a traced microbatch is in flight
        # on this thread, every JSON line carries its lead trace id (and
        # the worker origin), so flight-recorder exemplars are greppable
        # straight from the logs. Lazy import — logging must configure
        # even if the tracing plane never loads.
        try:
            from realtime_fraud_detection_tpu.obs.tracing import (
                current_log_context,
            )

            ctx = current_log_context()
        except Exception:  # noqa: BLE001 - logging never raises
            ctx = None
        if ctx is not None and "trace_id" not in record.__dict__:
            out["trace_id"] = ctx["trace_id"]
            if ctx["worker"]:
                out["worker"] = ctx["worker"]
        for k, v in record.__dict__.items():
            if k not in _RESERVED and not k.startswith("_"):
                out[k] = v
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


def setup_logging(level: str = "INFO", json_file: Optional[str] = None,
                  max_bytes: int = 10 * 1024 * 1024, backups: int = 3,
                  service_name: str = "") -> None:
    """Configure root logging (reference logging_config.py:11-93).
    ``service_name`` stamps every JSON line (config.service_name)."""
    handlers: Dict[str, Any] = {
        "console": {
            "class": "logging.StreamHandler",
            "formatter": "console",
            "level": level,
        },
    }
    if json_file:
        handlers["json_file"] = {
            "class": "logging.handlers.RotatingFileHandler",
            "filename": json_file,
            "maxBytes": max_bytes,
            "backupCount": backups,
            "formatter": "json",
            "level": level,
        }
    logging.config.dictConfig({
        "version": 1,
        "disable_existing_loggers": False,
        "formatters": {
            "console": {
                "format": "%(asctime)s %(levelname)-7s %(name)s  %(message)s",
            },
            "json": {"()": f"{__name__}.JsonFormatter",
                     "service_name": service_name},
        },
        "handlers": handlers,
        "root": {"level": level, "handlers": list(handlers)},
    })


def log_prediction_result(logger: logging.Logger, transaction_id: str,
                          fraud_score: float, decision: str,
                          processing_time_ms: float,
                          extra: Optional[Mapping[str, Any]] = None) -> None:
    """Structured per-prediction log (logging_config.py:145-219 analog)."""
    logger.info(
        "prediction",
        extra={
            "event": "prediction",
            "transaction_id": transaction_id,
            "fraud_score": round(float(fraud_score), 6),
            "decision": decision,
            "processing_time_ms": round(float(processing_time_ms), 3),
            **(dict(extra) if extra else {}),
        },
    )


def log_batch_scored(logger: logging.Logger, size: int, elapsed_ms: float,
                     bucket: int) -> None:
    logger.info(
        "batch_scored",
        extra={"event": "batch_scored", "size": size, "bucket": bucket,
               "elapsed_ms": round(elapsed_ms, 3)},
    )


def log_model_event(logger: logging.Logger, model: str, event: str,
                    **fields: Any) -> None:
    """Model lifecycle events: loaded / reloaded / disabled / failed."""
    logger.info(
        "model_event",
        # rtfd-lint: allow[wall-clock] ts_wall is the log line's wall stamp by contract
        extra={"event": event, "model": model, "ts_wall": time.time(),
               **fields},
    )

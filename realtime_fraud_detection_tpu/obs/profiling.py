"""Profiling: jax.profiler traces + cheap wall-clock span accounting.

Replaces the reference's coarse timing-threaded-through-results approach
(SURVEY.md §5.1: per-request processing_time_ms at main.py:160-169, per-model
timing at ensemble_predictor.py:185-215) with two proper layers:

- ``device_trace``: a real ``jax.profiler`` trace you can open in
  TensorBoard/Perfetto — shows XLA fusion, HBM traffic, collective overlap.
- ``SpanTimer``: near-zero-overhead named wall-clock spans with aggregate
  stats (count/total/p50/p99) for the host-side hot path, where a full
  profiler would distort the 5–10 ms microbatch deadline.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import Dict, Iterator, Optional

__all__ = ["device_trace", "SpanTimer", "annotate",
           "interpolated_percentile"]


def interpolated_percentile(xs_sorted, q: float) -> float:
    """Linear-interpolated percentile over a SORTED sample (numpy's
    default convention), unit-agnostic. The one implementation shared by
    SpanTimer.stats and the tracing plane's breakdown — raw index
    selection made small-n tails dishonest (p99 on n<100 was simply the
    max)."""
    pos = q * (len(xs_sorted) - 1)
    lo = int(pos)
    frac = pos - lo
    if lo + 1 >= len(xs_sorted):
        return float(xs_sorted[-1])
    return float(xs_sorted[lo] + (xs_sorted[lo + 1] - xs_sorted[lo]) * frac)


@contextlib.contextmanager
def device_trace(log_dir: str) -> Iterator[None]:
    """Capture a jax.profiler trace for the enclosed block."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region visible in device traces (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


class SpanTimer:
    """Aggregating span timer for host-side stages of the scoring seam."""

    def __init__(self, clock=time.perf_counter, max_samples: int = 10_000):
        self._clock = clock
        self._lock = threading.Lock()
        self._max = max_samples      # per-span cap: hot-path safe, O(1) memory
        self._spans: Dict[str, deque] = {}

    @contextlib.contextmanager
    def span(self, name: str) -> Iterator[None]:
        t0 = self._clock()
        try:
            yield
        finally:
            dt = self._clock() - t0
            self.record(name, dt)

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            self._spans.setdefault(
                name, deque(maxlen=self._max)).append(seconds)

    def stats(self, name: Optional[str] = None) -> Dict[str, Dict[str, float]]:
        # snapshot the deques under the lock; the O(n log n) sort and the
        # percentile math run outside it — a stats() reader must never
        # stall the hot path's record() behind a 10k-sample sort
        with self._lock:
            names = [name] if name else list(self._spans)
            snap = {n: list(self._spans[n]) for n in names
                    if self._spans.get(n)}
        out: Dict[str, Dict[str, float]] = {}
        for n, xs in snap.items():
            xs.sort()
            out[n] = {
                "count": len(xs),
                "total_s": sum(xs),
                "mean_ms": 1e3 * sum(xs) / len(xs),
                "p50_ms": 1e3 * interpolated_percentile(xs, 0.50),
                "p99_ms": 1e3 * interpolated_percentile(xs, 0.99),
                "max_ms": 1e3 * xs[-1],
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()

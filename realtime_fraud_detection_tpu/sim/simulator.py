"""Vectorized transaction load generator.

Capability mirror of the reference data simulator (simulator.py:159-476):
10k users with beta(2,8) risk and lognormal(4,1) spend, 5k merchants from 10
category tuples with 2% blacklisted, transaction generation with
user x merchant amount factors, and a ~5.5% basic fraud mix.

Two output paths:

- ``generate_batch(n)``: list of transaction dicts in the reference JSON
  schema (simulator.py:78-101) with stateful fraud appliers — feeds the
  transport / serving / e2e tests.
- ``generate_encoded(n)``: columns straight into a ``TransactionBatch`` +
  labels, fully vectorized in NumPy — feeds training and the 50k-TPS bench
  (the reference's one-thread ``sleep(1/tps)`` pacing loop, simulator.py:437-449,
  tops out near 1k TPS; this path generates millions/min).
"""

from __future__ import annotations

import dataclasses
from datetime import datetime, timedelta, timezone
from typing import Any, Dict, List, Sequence

import numpy as np

from realtime_fraud_detection_tpu.features.schema import (
    CARD_TYPES,
    KYC_STATUSES,
    MERCHANT_CATEGORIES,
    PAYMENT_METHODS,
    TRANSACTION_TYPES,
    TransactionBatch,
    encode_transactions,
)
from realtime_fraud_detection_tpu.sim.fraud_patterns import (
    AdvancedFraudPatterns,
    BASIC_FRAUD_MIX,
)

# (category, mcc, risk_level, avg_amount, fraud_rate) — simulator.py:255-266
MERCHANT_CATEGORY_TUPLES = (
    ("retail", "5399", "low", 50.0, 0.01),
    ("grocery", "5411", "low", 25.0, 0.005),
    ("gas_station", "5542", "medium", 40.0, 0.02),
    ("restaurant", "5812", "low", 35.0, 0.008),
    ("online_retail", "5399", "medium", 75.0, 0.025),
    ("gambling", "7995", "high", 200.0, 0.15),
    ("adult_entertainment", "5967", "high", 100.0, 0.12),
    ("pharmacy", "5912", "medium", 30.0, 0.01),
    ("jewelry", "5944", "high", 500.0, 0.08),
    ("electronics", "5732", "medium", 300.0, 0.03),
)

_SUSPICIOUS_TOKENS = ("Crypto Exchange", "Gift Card Outlet", "Wire Transfer Co",
                      "Casino Royale", "Bitcoin Mart")
_PLAIN_TOKENS = ("Market", "Store", "Shop", "House", "Depot", "Corner", "Bros")
_USER_AGENTS = (
    "Mozilla/5.0 (X11; Linux x86_64) AppleWebKit/537.36 Chrome/120.0",
    "Mozilla/5.0 (iPhone; CPU iPhone OS 17_0 like Mac OS X) Safari/604.1",
    "Mozilla/5.0 (Windows NT 10.0; Win64; x64) Gecko/20100101 Firefox/121.0",
    "Mozilla/5.0 (Macintosh; Intel Mac OS X 14_2) Version/17.2 Safari/605.1",
)


class UserPool:
    """Vectorized user profile pool (simulator.py:206-249 distributions)."""

    def __init__(self, n: int, rng: np.random.Generator):
        self.n = n
        self.ids = np.array([f"user_{i:08x}" for i in range(n)])
        self.risk_score = rng.beta(2, 8, n).astype(np.float32)
        self.avg_amount = rng.lognormal(4, 1, n).astype(np.float32)
        self.txn_frequency = (rng.gamma(2, 2, n).astype(np.int32) + 1)
        self.kyc_code = rng.choice(3, n, p=[0.85, 0.12, 0.03]).astype(np.int32)
        self.account_age_days = rng.uniform(0, 730, n).astype(np.float32)
        self.pref_start = rng.integers(6, 11, n).astype(np.int32)
        self.pref_end = rng.integers(18, 24, n).astype(np.int32)
        self.weekend_activity = rng.uniform(0.3, 1.0, n).astype(np.float32)
        self.intl_ratio = rng.uniform(0.0, 0.1, n).astype(np.float32)
        self.online_preference = rng.uniform(0.5, 0.95, n).astype(np.float32)
        self.home_lat = rng.uniform(-60, 60, n).astype(np.float32)
        self.home_lon = rng.uniform(-180, 180, n).astype(np.float32)
        n_dev = rng.integers(1, 4, n)
        self.device_fingerprints = [
            [f"dev_{i:08x}_{d}" for d in range(n_dev[i])] for i in range(n)
        ]

    def profile_dict(self, i: int) -> Dict[str, Any]:
        return {
            "user_id": str(self.ids[i]),
            "risk_score": float(self.risk_score[i]),
            "account_age_days": float(self.account_age_days[i]),
            "kyc_status": KYC_STATUSES[self.kyc_code[i]],
            "avg_transaction_amount": float(self.avg_amount[i]),
            "transaction_frequency": int(self.txn_frequency[i]),
            "device_fingerprints": list(self.device_fingerprints[i]),
            "behavioral_patterns": {
                "preferred_time_start": int(self.pref_start[i]),
                "preferred_time_end": int(self.pref_end[i]),
                "weekend_activity": float(self.weekend_activity[i]),
                "international_transactions": float(self.intl_ratio[i]),
                "online_preference": float(self.online_preference[i]),
            },
        }

    def profiles(self) -> Dict[str, Dict[str, Any]]:
        return {str(self.ids[i]): self.profile_dict(i) for i in range(self.n)}


class MerchantPool:
    """Vectorized merchant pool (simulator.py:251-296 distributions)."""

    def __init__(self, n: int, rng: np.random.Generator):
        self.n = n
        self.ids = np.array([f"merchant_{i:08x}" for i in range(n)])
        cat_idx = rng.integers(0, len(MERCHANT_CATEGORY_TUPLES), n)
        cats = [MERCHANT_CATEGORY_TUPLES[c] for c in cat_idx]
        self.category = np.array([c[0] for c in cats])
        self.category_code = np.array(
            [MERCHANT_CATEGORIES.index(c[0]) for c in cats], np.int32
        )
        self.mcc = np.array([c[1] for c in cats])
        self.risk_level = np.array([c[2] for c in cats])
        self.risk_code = np.array(
            [{"low": 0, "medium": 1, "high": 2}[c[2]] for c in cats], np.int32
        )
        self.avg_amount = np.array(
            [c[3] for c in cats], np.float32
        ) * rng.uniform(0.5, 2.0, n).astype(np.float32)
        self.fraud_rate = np.array([c[4] for c in cats], np.float32)
        self.is_blacklisted = rng.random(n) < 0.02
        self.op_start = rng.integers(6, 11, n).astype(np.int32)
        self.op_end = rng.integers(20, 25, n).astype(np.int32)
        self.lat = rng.uniform(-60, 60, n).astype(np.float32)
        self.lon = rng.uniform(-180, 180, n).astype(np.float32)
        suspicious = rng.random(n) < 0.05
        self.names = np.array([
            f"{'Biz'} {i} {(_SUSPICIOUS_TOKENS if suspicious[i] else _PLAIN_TOKENS)[int(rng.integers(0, 5))]}"
            for i in range(n)
        ])
        self.suspicious_name = suspicious
        # suspicious-named merchants really do attract more fraud
        self.fraud_rate = np.where(
            suspicious, np.minimum(self.fraud_rate * 3.0, 0.3), self.fraud_rate
        ).astype(np.float32)
        # per-merchant fraud multiplier, normalized so E[mult] == 1 over a
        # uniform merchant draw: total stream fraud stays at the documented
        # ~5.5% BASIC_FRAUD_MIX prevalence even after clipping
        raw_mult = np.clip(self.fraud_rate / max(self.fraud_rate.mean(), 1e-6), 0.2, 4.0)
        self.fraud_mult = (raw_mult / raw_mult.mean()).astype(np.float32)

    def profile_dict(self, i: int) -> Dict[str, Any]:
        return {
            "merchant_id": str(self.ids[i]),
            "name": str(self.names[i]),
            "category": str(self.category[i]),
            "mcc": str(self.mcc[i]),
            "risk_level": str(self.risk_level[i]),
            "avg_transaction_amount": float(self.avg_amount[i]),
            "fraud_rate": float(self.fraud_rate[i]),
            "is_blacklisted": bool(self.is_blacklisted[i]),
            "operating_hours": {
                "start_hour": str(int(self.op_start[i])),
                "end_hour": str(int(self.op_end[i])),
            },
        }

    def profiles(self) -> Dict[str, Dict[str, Any]]:
        return {str(self.ids[i]): self.profile_dict(i) for i in range(self.n)}


FRAUD_TYPES = ("none",) + tuple(BASIC_FRAUD_MIX)


class TransactionGenerator:
    """Generates transactions against a user/merchant pool."""

    def __init__(
        self,
        num_users: int = 10_000,
        num_merchants: int = 5_000,
        seed: int = 42,
        start_time: datetime | None = None,
        tps: float = 1000.0,
    ):
        self.rng = np.random.default_rng(seed)
        self.users = UserPool(num_users, self.rng)
        self.merchants = MerchantPool(num_merchants, self.rng)
        self.patterns = AdvancedFraudPatterns(self.rng)
        self.clock = start_time or datetime(2026, 1, 5, 8, 0, tzinfo=timezone.utc)
        self.tps = tps
        self._txn_counter = 0
        # drifted fraud pattern (inject_drift): a novel modus operandi the
        # incumbent models never trained on — 0.0 = off (default)
        self._drift_rate = 0.0
        self._drift_merchants: np.ndarray | None = None
        # coordinated fraud ring (inject_fraud_ring): a user cohort
        # funneling traffic through shared merchants/devices/IPs — the
        # adversarial scenario the chaos drill retrains against. None = off
        self._ring = None

    # ------------------------------------------------------------------ dicts
    def generate_batch(self, n: int) -> List[Dict[str, Any]]:
        """n transaction dicts in the reference schema (simulator.py:298-374)."""
        out = []
        for _ in range(n):
            out.append(self._generate_one())
        return out

    def _generate_one(self) -> Dict[str, Any]:
        rng = self.rng
        u = int(rng.integers(0, self.users.n))
        m = int(rng.integers(0, self.merchants.n))
        self.clock += timedelta(seconds=1.0 / self.tps)
        self._txn_counter += 1
        amount = max(
            1.0,
            round(
                float(self.users.avg_amount[u])
                * float(rng.normal(1.0, 0.3))
                * float(rng.normal(1.0, 0.2)),
                2,
            ),
        )
        intl = rng.random() < self.users.intl_ratio[u]
        if intl:
            geo = {"lat": float(rng.uniform(-90, 90)), "lon": float(rng.uniform(-180, 180))}
        else:
            geo = {
                "lat": float(self.users.home_lat[u] + rng.normal(0, 0.5)),
                "lon": float(self.users.home_lon[u] + rng.normal(0, 0.5)),
            }
        devices = self.users.device_fingerprints[u]
        device = devices[int(rng.integers(0, len(devices)))]
        txn: Dict[str, Any] = {
            "transaction_id": f"txn_{self._txn_counter:012d}",
            "user_id": str(self.users.ids[u]),
            "merchant_id": str(self.merchants.ids[m]),
            "amount": amount,
            "currency": "USD",
            "transaction_type": TRANSACTION_TYPES[int(rng.integers(0, 3))],
            "payment_method": PAYMENT_METHODS[int(rng.integers(0, 4))],
            "card_type": CARD_TYPES[int(rng.integers(0, 4))],
            "card_last_four": str(int(rng.integers(1000, 10000))),
            "timestamp": self.clock.isoformat(),
            "ip_address": self._random_ip(),
            "device_id": device,
            "device_fingerprint": device,
            "user_agent": _USER_AGENTS[int(rng.integers(0, len(_USER_AGENTS)))],
            "geolocation": geo,
            "merchant_location": {
                "lat": float(self.merchants.lat[m]),
                "lon": float(self.merchants.lon[m]),
            },
            "is_weekend": self.clock.weekday() >= 5,
            "hour_of_day": self.clock.hour,
            "day_of_week": self.clock.isoweekday(),
            "day_of_month": self.clock.day,
            "is_fraud": False,
            "fraud_type": None,
            "fraud_score": 0.0,
        }
        # basic fraud mix (simulator.py:106-127,349-371), modulated by the
        # merchant's fraud rate (same rule as the fast path)
        total_mix = sum(BASIC_FRAUD_MIX.values())
        mult = float(self.merchants.fraud_mult[m])
        fraud_type = None
        if rng.random() < total_mix * mult:
            pattern_roll = rng.random() * total_mix
            cum = 0.0
            for name, p in BASIC_FRAUD_MIX.items():
                cum += p
                if pattern_roll < cum:
                    fraud_type = name
                    break
        if fraud_type is not None:
            txn["is_fraud"] = True
            txn["fraud_type"] = fraud_type
            txn = self.patterns.apply_fraud_pattern(fraud_type, txn)
        else:
            txn["fraud_score"] = float(rng.uniform(0.0, 0.3))
            self.patterns.record_location(txn["user_id"], geo)
        if self._drift_rate > 0.0 and rng.random() < self._drift_rate:
            txn = self._apply_drifted_pattern(txn)
        if self._ring is not None \
                and rng.random() < self._ring.config.rate:
            txn = self._ring.apply(txn)
        return txn

    # ------------------------------------------------------------ drift
    def inject_drift(self, rate: float = 0.05) -> None:
        """Turn on the drifted fraud pattern: a ``rate`` fraction of the
        stream becomes a novel modus operandi (``fraud_type
        'drifted_pattern'``) that an incumbent model has never seen —
        benign-looking prior score, mid-range amounts, but a learnable
        signature (night-hour + crypto rail + a small complicit merchant
        ring). Drives the continuous-learning drill (feedback/drill.py):
        a pre-drift model ranks these like legit traffic, so prequential
        AUC dips until a retrain on labeled drifted examples recovers it.
        """
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"drift rate must be in [0, 1], got {rate}")
        self._drift_rate = float(rate)
        if self._drift_merchants is None:
            # the complicit ring is one coherent merchant CATEGORY
            # (electronics): ring membership is a single categorical
            # feature a retrained tree can split on, while the incumbent —
            # which saw electronics as a benign category — has no reason to
            ring = self.merchants.ids[self.merchants.category
                                      == "electronics"]
            if len(ring) == 0:
                ring = self.merchants.ids[:max(1, self.merchants.n // 10)]
            self._drift_merchants = ring

    def clear_drift(self) -> None:
        self._drift_rate = 0.0

    # ------------------------------------------------------------ fraud ring
    def inject_fraud_ring(self, config=None) -> "Any":
        """Activate a coordinated fraud ring (sim/fraud_patterns.FraudRing):
        a deterministic user cohort starts funneling a ``config.rate``
        fraction of the stream through a small shared merchant/device/IP
        set. Each ring transaction is in-distribution per feature; the
        signal is the shared-entity conjunction — the adversarial scenario
        that exercises the graph-side capability and drives the chaos
        drill's retrain-to-baseline acceptance. Returns the live ring (for
        stats / membership assertions)."""
        from realtime_fraud_detection_tpu.sim.fraud_patterns import (
            FraudRing,
            FraudRingConfig,
        )

        cfg = config or FraudRingConfig()
        self._ring = FraudRing(cfg, self.users, self.merchants.ids,
                               self.merchants.category, self.rng)
        return self._ring

    def clear_fraud_ring(self) -> None:
        self._ring = None

    def _apply_drifted_pattern(self, txn: Dict[str, Any]) -> Dict[str, Any]:
        rng = self.rng
        txn["is_fraud"] = True
        txn["fraud_type"] = "drifted_pattern"
        # the signature is deliberately IN-DISTRIBUTION per feature — the
        # user's own ordinary amount, a mainstream payment rail, a benign
        # prior score, ordinary geo/hour — so neither the leaky prior
        # feature, amount-vs-user-average splits, nor an anomaly detector
        # gets a free win; the signal lives only in the CONJUNCTION
        # (electronics-ring merchant x digital-wallet rail), which a model
        # must be retrained on drifted labels to rank
        txn["merchant_id"] = str(
            self._drift_merchants[int(rng.integers(
                0, len(self._drift_merchants)))])
        txn["payment_method"] = "digital_wallet"
        txn["fraud_score"] = float(rng.uniform(0.0, 0.3))
        txn["fraud_reason"] = "drifted pattern (novel MO, unseen in training)"
        return txn

    # ------------------------------------------------------------ labels
    def label_events(self, txns: Sequence[Dict[str, Any]],
                     event_ts: Sequence[float] | None = None,
                     delay_scale: float = 1.0) -> List[Dict[str, Any]]:
        """Delayed ground-truth label events for already-generated
        transactions (the labels-topic producer role): chargeback-style
        delays drawn from this generator's rng (deterministic replay),
        sorted by ``label_ts``. See feedback/labels.make_label_events."""
        from realtime_fraud_detection_tpu.feedback.labels import (
            make_label_events,
        )

        return make_label_events(list(txns), self.rng,
                                 event_ts=(list(event_ts)
                                           if event_ts is not None else None),
                                 delay_scale=delay_scale)

    def _random_ip(self) -> str:
        rng = self.rng
        if rng.random() < 0.05:
            return f"192.168.{int(rng.integers(0, 256))}.{int(rng.integers(1, 255))}"
        return f"{int(rng.integers(11, 223))}.{int(rng.integers(0, 256))}.{int(rng.integers(0, 256))}.{int(rng.integers(1, 255))}"

    # ------------------------------------------------------------ fast arrays
    def generate_encoded(self, n: int) -> tuple[TransactionBatch, Dict[str, np.ndarray]]:
        """Vectorized batch straight into TransactionBatch columns + labels.

        Semantically equivalent to generate_batch + encode_transactions with
        joined pools, minus string materialization. Velocity fields are
        synthesized (Poisson background; elevated for velocity fraud) since
        no state store is in the loop here.
        """
        rng = self.rng
        up, mp = self.users, self.merchants
        u = rng.integers(0, up.n, n)
        m = rng.integers(0, mp.n, n)
        amount = np.maximum(
            1.0,
            np.round(up.avg_amount[u] * rng.normal(1, 0.3, n) * rng.normal(1, 0.2, n), 2),
        ).astype(np.float32)

        # virtual clock: advance n/tps seconds across the batch
        offsets = np.arange(n) / self.tps
        base = self.clock
        secs = (base - datetime(2026, 1, 5, tzinfo=timezone.utc)).total_seconds() + offsets
        hour = ((secs // 3600) % 24).astype(np.int32)
        day_index = (secs // 86400).astype(np.int64)
        day_of_week = ((day_index % 7) + 1).astype(np.int32)  # base is a Monday
        # base date is the 5th; wrap within a 28-day month (dict path uses
        # real calendar days — equal on day 0, may drift at month ends)
        day_of_month = ((day_index + 4) % 28 + 1).astype(np.int32)
        self.clock = base + timedelta(seconds=float(n / self.tps))

        intl = rng.random(n) < up.intl_ratio[u]
        lat = np.where(intl, rng.uniform(-90, 90, n), up.home_lat[u] + rng.normal(0, 0.5, n))
        lon = np.where(intl, rng.uniform(-180, 180, n), up.home_lon[u] + rng.normal(0, 0.5, n))

        # fraud mix, modulated by the merchant's own fraud rate so merchant
        # identity (category, suspicious name) carries real signal — the
        # reference stores per-merchant fraud_rate (simulator.py:255-266)
        # but never lets it influence label generation
        probs = np.array(list(BASIC_FRAUD_MIX.values()))
        total_mix = probs.sum()
        mult = mp.fraud_mult[m]
        roll = rng.random(n)
        is_fraud = roll < total_mix * mult
        # pattern choice within fraud rows keeps the mix proportions
        pattern_roll = rng.random(n) * total_mix
        cum = np.concatenate([[0.0], np.cumsum(probs)])
        fraud_code = np.zeros(n, np.int32)  # 0 = none
        for k in range(len(probs)):
            sel = is_fraud & (pattern_roll >= cum[k]) & (pattern_roll < cum[k + 1])
            fraud_code[sel] = k + 1

        ct = fraud_code == 1 + list(BASIC_FRAUD_MIX).index("card_testing")
        ato = fraud_code == 1 + list(BASIC_FRAUD_MIX).index("account_takeover")
        syn = fraud_code == 1 + list(BASIC_FRAUD_MIX).index("synthetic_fraud")
        vel = fraud_code == 1 + list(BASIC_FRAUD_MIX).index("velocity_fraud")
        other = is_fraud & ~(ct | ato | syn | vel)

        amount = np.where(ct, np.round(rng.uniform(1.0, 5.0, n), 2), amount)
        amount = np.where(syn, np.round(rng.uniform(1000.0, 5000.0, n), 2), amount)
        lat = np.where(ato, rng.uniform(-90, 90, n), lat)
        lon = np.where(ato, rng.uniform(-180, 180, n), lon)

        fraud_score = rng.uniform(0.0, 0.3, n)
        fraud_score = np.where(ct, rng.uniform(0.8, 0.95, n), fraud_score)
        fraud_score = np.where(ato, rng.uniform(0.7, 0.9, n), fraud_score)
        fraud_score = np.where(syn, rng.uniform(0.75, 0.95, n), fraud_score)
        fraud_score = np.where(vel, rng.uniform(0.6, 0.85, n), fraud_score)
        fraud_score = np.where(other, rng.uniform(0.5, 0.8, n), fraud_score)

        known_device = ~ato  # takeover uses a brand-new fingerprint
        private_ip = rng.random(n) < 0.05

        v5 = rng.poisson(0.2, n).astype(np.float32)
        v5 = np.where(vel, rng.integers(6, 13, n), v5).astype(np.float32)
        v1h = v5 + rng.poisson(1.0, n).astype(np.float32)
        v1h = np.where(vel, v1h + rng.integers(10, 20, n), v1h).astype(np.float32)
        v24 = v1h + rng.poisson(4.0, n).astype(np.float32)
        avg_amt = up.avg_amount[u]

        payment_code = rng.integers(0, 4, n).astype(np.int32)
        txn_type = rng.integers(0, 3, n).astype(np.int32)

        batch = TransactionBatch(
            amount=amount.astype(np.float32),
            hour_of_day=hour,
            day_of_week=day_of_week,
            day_of_month=day_of_month,
            is_weekend=day_of_week >= 6,
            lat=lat.astype(np.float32),
            lon=lon.astype(np.float32),
            has_geo=np.ones(n, bool),
            merchant_lat=mp.lat[m],
            merchant_lon=mp.lon[m],
            has_merchant_geo=np.ones(n, bool),
            payment_method_code=payment_code,
            transaction_type_code=txn_type,
            card_type_code=rng.integers(0, 4, n).astype(np.int32),
            high_risk_payment=np.zeros(n, bool),  # basic methods are low-risk
            suspicious_user_agent=rng.random(n) < 0.01,
            private_ip=private_ip,
            has_txn_fingerprint=np.ones(n, bool),
            ip_risk=np.where(private_ip, 0.1, 0.3).astype(np.float32),
            prior_fraud_score=fraud_score.astype(np.float32),
            has_user=np.ones(n, bool),
            user_risk_score=up.risk_score[u],
            account_age_days=up.account_age_days[u],
            user_verified=up.kyc_code[u] == 0,
            kyc_code=up.kyc_code[u],
            user_avg_amount=avg_amt,
            user_txn_frequency=up.txn_frequency[u].astype(np.float32),
            preferred_start=up.pref_start[u],
            preferred_end=up.pref_end[u],
            has_preferred_hours=np.ones(n, bool),
            weekend_activity=up.weekend_activity[u],
            intl_ratio=up.intl_ratio[u],
            has_intl_ratio=np.ones(n, bool),
            online_preference=up.online_preference[u],
            known_device=known_device,
            has_device_list=np.ones(n, bool),
            has_merchant=np.ones(n, bool),
            merchant_risk_code=mp.risk_code[m],
            merchant_fraud_rate=mp.fraud_rate[m],
            merchant_blacklisted=mp.is_blacklisted[m],
            merchant_category_code=mp.category_code[m],
            merchant_high_risk_category=mp.risk_code[m] == 2,
            merchant_op_start=mp.op_start[m],
            merchant_op_end=mp.op_end[m],
            has_op_hours=np.ones(n, bool),
            merchant_avg_amount=mp.avg_amount[m],
            suspicious_merchant_name=mp.suspicious_name[m],
            velocity_5min_count=v5,
            velocity_5min_amount=v5 * avg_amt,
            velocity_1hour_count=v1h,
            velocity_1hour_amount=v1h * avg_amt,
            velocity_24hour_count=v24,
            velocity_24hour_amount=v24 * avg_amt,
        )
        labels = {
            "is_fraud": is_fraud,
            "fraud_type": fraud_code,
            "fraud_score": fraud_score.astype(np.float32),
            "user_index": u,
            "merchant_index": m,
        }
        return batch, labels

    # ---------------------------------------------------------------- joins
    def encode_dicts(self, records: Sequence[Dict[str, Any]]) -> TransactionBatch:
        """Encode dict transactions with this generator's profile pools."""
        return encode_transactions(
            records, self.users.profiles(), self.merchants.profiles()
        )

"""Fraud pattern library: 10 parameterized scenarios + stateful appliers.

Capability mirror of the reference's ``AdvancedFraudPatterns``
(fraud_patterns.py:17-417): scenario registry with probability/severity/
difficulty/amount-range/frequency/geo-pattern, velocity tracking over 10-minute
windows, geographic history for account-takeover and impossible-travel, and
structuring amounts (9000-9900) for laundering. Plus the simulator's basic
7-pattern mix (reference simulator.py:106-127) as ``BASIC_FRAUD_MIX``.

No faker / global ``random``: everything draws from an injected
``numpy.random.Generator`` for deterministic replay.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass
from datetime import datetime, timedelta
from typing import Any, Dict, Optional, Tuple

import numpy as np

# Basic mix wired into the reference simulator (simulator.py:107-115), ~5.5%.
BASIC_FRAUD_MIX: Dict[str, float] = {
    "card_testing": 0.02,
    "account_takeover": 0.01,
    "synthetic_fraud": 0.005,
    "money_laundering": 0.003,
    "merchant_fraud": 0.002,
    "velocity_fraud": 0.01,
    "geographic_fraud": 0.005,
}


@dataclass(frozen=True)
class FraudScenario:
    """Scenario parameters (reference fraud_patterns.py:17-27)."""

    name: str
    description: str
    probability: float
    severity: str            # low | medium | high | critical
    detection_difficulty: str  # easy | medium | hard | very_hard
    typical_amount_range: Tuple[float, float]
    typical_frequency: str   # single | burst | sustained
    geographic_pattern: str  # local | remote | international | random


def _scenarios() -> Dict[str, FraudScenario]:
    """The 10 scenarios (reference fraud_patterns.py:38-141)."""
    S = FraudScenario
    return {
        "card_testing": S("Card Testing",
                          "Probing stolen card credentials via tiny purchases",
                          0.025, "medium", "easy", (0.99, 9.99), "burst", "random"),
        "account_takeover": S("Account Takeover",
                              "Genuine account hijacked by an attacker",
                              0.015, "high", "medium", (100.0, 2000.0), "sustained", "remote"),
        "synthetic_identity": S("Synthetic Identity Fraud",
                                "Fabricated identity blending genuine and invented data",
                                0.008, "high", "hard", (500.0, 5000.0), "sustained", "local"),
        "first_party_fraud": S("First Party Fraud",
                               "Account owner abusing their own account",
                               0.012, "medium", "very_hard", (200.0, 1500.0), "single", "local"),
        "money_laundering": S("Money Laundering",
                              "Deposits split just under reporting limits to obscure origin",
                              0.005, "critical", "hard", (9000.0, 9900.0), "sustained", "random"),
        "merchant_fraud": S("Merchant Fraud",
                            "Complicit merchant running fabricated charges",
                            0.003, "high", "medium", (50.0, 500.0), "sustained", "local"),
        "velocity_fraud": S("Velocity Fraud",
                            "Burst of charges far above the account's usual cadence",
                            0.018, "medium", "easy", (25.0, 300.0), "burst", "local"),
        "geographic_fraud": S("Geographic Impossibility",
                              "Charges from locations no traveler could reach in time",
                              0.010, "medium", "medium", (100.0, 800.0), "single", "international"),
        "bust_out_fraud": S("Bust-Out Fraud",
                            "Patiently grown credit line drained in one spree",
                            0.004, "high", "hard", (1000.0, 8000.0), "burst", "local"),
        "friendly_fraud": S("Friendly Fraud",
                            "Cardholder charging back purchases they actually made",
                            0.020, "low", "very_hard", (50.0, 1000.0), "single", "local"),
    }


class AdvancedFraudPatterns:
    """Stateful fraud-pattern applier over transaction dicts."""

    def __init__(self, rng: np.random.Generator | None = None):
        self.rng = rng or np.random.default_rng(0)
        self.scenarios = _scenarios()
        self.velocity_windows: Dict[str, list] = {}
        self.geographic_history: Dict[str, list] = {}

    # -- selection ----------------------------------------------------------
    def generate_fraud_scenario(self) -> Tuple[bool, Optional[str], Optional[FraudScenario]]:
        """Weighted scenario draw (reference fraud_patterns.py:143-159)."""
        total = sum(s.probability for s in self.scenarios.values())
        if self.rng.random() > total:
            return False, None, None
        draw = self.rng.random() * total
        cum = 0.0
        for name, scenario in self.scenarios.items():
            cum += scenario.probability
            if draw <= cum:
                return True, name, scenario
        return False, None, None

    # -- appliers -----------------------------------------------------------
    def apply_fraud_pattern(self, fraud_type: str, txn: Dict[str, Any]) -> Dict[str, Any]:
        applier = getattr(self, f"_apply_{fraud_type}", None)
        if applier is None:
            txn["fraud_score"] = float(self.rng.uniform(0.50, 0.80))
            txn["fraud_reason"] = f"Unrecognized scenario key: {fraud_type}"
            return txn
        return applier(txn)

    def _amount(self, name: str) -> float:
        lo, hi = self.scenarios[name].typical_amount_range
        return round(float(self.rng.uniform(lo, hi)), 2)

    def _apply_card_testing(self, txn):
        txn["amount"] = self._amount("card_testing")
        txn["card_last_four"] = str(self.rng.choice(["1234", "5678", "9999", "0000"]))
        txn["fraud_score"] = float(self.rng.uniform(0.75, 0.95))
        txn["fraud_reason"] = "Card-testing probe: repeated tiny charges"
        txn["ip_address"] = _random_public_ip(self.rng)
        return txn

    def _apply_account_takeover(self, txn):
        user_id = txn["user_id"]
        history = self.geographic_history.setdefault(user_id, [])
        if history:
            last = history[-1]
            txn["geolocation"] = {
                "lat": float(np.clip(last["lat"] + self.rng.uniform(-50, 50), -90, 90)),
                "lon": float(np.clip(last["lon"] + self.rng.uniform(-50, 50), -180, 180)),
            }
        history.append(dict(txn.get("geolocation") or {"lat": 0.0, "lon": 0.0}))
        txn["device_fingerprint"] = str(uuid.UUID(int=int(self.rng.integers(0, 2**63)), version=4))
        txn["device_id"] = txn["device_fingerprint"]
        txn["amount"] = self._amount("account_takeover")
        txn["fraud_score"] = float(self.rng.uniform(0.70, 0.90))
        txn["fraud_reason"] = "Login from unfamiliar device and distant location"
        return txn

    def _apply_velocity_fraud(self, txn):
        user_id = txn["user_id"]
        now = datetime.fromisoformat(txn["timestamp"])
        window = self.velocity_windows.setdefault(user_id, [])
        window.append(now)
        cutoff = now - timedelta(minutes=10)
        self.velocity_windows[user_id] = window = [t for t in window if t > cutoff]
        count = len(window)
        if count > 5:
            txn["fraud_score"] = min(0.95, 0.5 + count * 0.1)
            txn["fraud_reason"] = f"Burst rate: {count} charges inside a 10-minute window"
        else:
            txn["fraud_score"] = float(self.rng.uniform(0.60, 0.80))
            txn["fraud_reason"] = "Charge cadence far above account baseline"
        txn["amount"] = self._amount("velocity_fraud")
        return txn

    def _apply_synthetic_identity(self, txn):
        txn["amount"] = self._amount("synthetic_identity")
        txn["fraud_score"] = float(self.rng.uniform(0.65, 0.85))
        txn["fraud_reason"] = "Profile signals consistent with a fabricated identity"
        txn["transaction_type"] = "purchase"
        return txn

    # the simulator's basic mix calls this "synthetic_fraud" (simulator.py:110)
    _apply_synthetic_fraud = _apply_synthetic_identity

    def _apply_money_laundering(self, txn):
        txn["amount"] = self._amount("money_laundering")  # structuring 9000-9900
        txn["fraud_score"] = float(self.rng.uniform(0.70, 0.90))
        txn["fraud_reason"] = "Amounts structured under the reporting threshold"
        return txn

    def _apply_geographic_fraud(self, txn):
        user_id = txn["user_id"]
        if self.geographic_history.get(user_id):
            txn["geolocation"] = {
                "lat": float(self.rng.uniform(-90, 90)),
                "lon": float(self.rng.uniform(-180, 180)),
            }
        txn["amount"] = self._amount("geographic_fraud")
        txn["fraud_score"] = float(self.rng.uniform(0.75, 0.90))
        txn["fraud_reason"] = "Location sequence physically impossible to travel"
        return txn

    def _apply_merchant_fraud(self, txn):
        txn["amount"] = float(self.rng.choice([49.99, 99.99, 199.99, 299.99]))
        txn["fraud_score"] = float(self.rng.uniform(0.60, 0.85))
        txn["fraud_reason"] = "Merchant-side fabricated charge signature"
        return txn

    def _apply_bust_out_fraud(self, txn):
        txn["amount"] = self._amount("bust_out_fraud")
        txn["fraud_score"] = float(self.rng.uniform(0.70, 0.90))
        txn["fraud_reason"] = "Credit line drained in a bust-out spree"
        return txn

    def _apply_friendly_fraud(self, txn):
        txn["amount"] = self._amount("friendly_fraud")
        txn["fraud_score"] = float(self.rng.uniform(0.05, 0.25))
        txn["fraud_reason"] = "Chargeback risk on a likely-genuine purchase"
        return txn

    def _apply_first_party_fraud(self, txn):
        txn["amount"] = self._amount("first_party_fraud")
        txn["fraud_score"] = float(self.rng.uniform(0.10, 0.40))
        txn["fraud_reason"] = "Owner-abuse signals on the account itself"
        return txn

    def record_location(self, user_id: str, geo: Dict[str, float]) -> None:
        """Track legit locations so takeover/impossible-travel have history."""
        self.geographic_history.setdefault(user_id, []).append(dict(geo))

    def get_fraud_statistics(self) -> Dict[str, Any]:
        return {
            "total_scenarios": len(self.scenarios),
            "total_fraud_probability": sum(s.probability for s in self.scenarios.values()),
            "velocity_tracking_users": len(self.velocity_windows),
            "geographic_tracking_users": len(self.geographic_history),
        }


def _random_public_ip(rng: np.random.Generator) -> str:
    octets = rng.integers(1, 255, size=4)
    if octets[0] in (10, 192, 172, 127):
        octets[0] = 52
    return ".".join(str(int(o)) for o in octets)


# ---------------------------------------------------------------------------
# coordinated fraud ring (the chaos plane's adversarial scenario)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FraudRingConfig:
    """Shape of a coordinated fraud ring: one attacker operating many
    compromised accounts through a SHARED, small entity set."""

    n_members: int = 24       # compromised user cohort
    n_merchants: int = 6      # complicit merchant set (one benign category)
    n_devices: int = 4        # shared device fingerprints (the attacker's)
    n_ips: int = 3            # shared egress IPs
    rate: float = 0.08        # fraction of the stream that is ring traffic
    merchant_category: str = "grocery"   # camouflage category

    def validate(self) -> None:
        if not 0.0 <= self.rate <= 1.0:
            raise ValueError(f"ring rate must be in [0, 1], got {self.rate}")
        if min(self.n_members, self.n_merchants, self.n_devices,
               self.n_ips) < 1:
            raise ValueError("ring needs >= 1 member/merchant/device/ip")


class FraudRing:
    """Stateful coordinated-ring applier over transaction dicts.

    Every per-user drift/fraud pattern above is INDEPENDENT across users —
    nothing in the simulator ever exercised the shared-entity structure
    the paper's GraphSAGE branch exists for. A ring is the opposite shape:
    a user COHORT funnels transactions through a handful of shared
    merchants, device fingerprints and egress IPs. Each transaction is
    deliberately in-distribution per feature — the member's own ordinary
    amount, a mainstream rail, a benign prior score, near-home geo — so
    neither the leaky prior feature nor an anomaly detector gets a free
    win. The learnable signature is the CONJUNCTION (camouflage-category
    merchant x a device fingerprint outside the member's enrolled list),
    plus the shared-entity links (same devices/merchants/IPs across many
    users) that the graph branch can consume. A model must be retrained
    on labeled ring examples to rank it — which is exactly what
    ``rtfd chaos-drill`` proves the feedback plane does.

    Membership is drawn deterministically from the injected rng, so a
    seeded drill replays the identical ring bit-for-bit.
    """

    def __init__(self, config: FraudRingConfig, users,
                 merchant_ids: np.ndarray,
                 merchant_categories: np.ndarray,
                 rng: np.random.Generator):
        config.validate()
        self.config = config
        self.users = users              # sim.simulator.UserPool
        member_idx = rng.choice(users.n,
                                size=min(config.n_members, users.n),
                                replace=False)
        self.member_idx = np.sort(member_idx)
        self.member_ids = users.ids[self.member_idx]
        in_cat = merchant_ids[merchant_categories
                              == config.merchant_category]
        if len(in_cat) == 0:
            in_cat = merchant_ids
        self.merchant_ids = in_cat[:config.n_merchants]
        self.device_ids = [f"ringdev_{int(rng.integers(0, 2**32)):08x}"
                           for _ in range(config.n_devices)]
        self.ips = [_random_public_ip(rng) for _ in range(config.n_ips)]
        self.rng = rng
        self.applied = 0

    def apply(self, txn: Dict[str, Any]) -> Dict[str, Any]:
        """Rewrite one transaction as ring traffic. The member keeps their
        OWN spend profile — amount drawn from the member's average (the
        generator's own noise model) and geo near the member's home, so
        per-user velocity/z-score/home-distance features stay in
        distribution; only the entity linkage changes."""
        rng = self.rng
        u = int(self.member_idx[int(rng.integers(0,
                                                 len(self.member_idx)))])
        txn["user_id"] = str(self.users.ids[u])
        txn["amount"] = max(1.0, round(
            float(self.users.avg_amount[u])
            * float(rng.normal(1.0, 0.3)) * float(rng.normal(1.0, 0.2)), 2))
        txn["geolocation"] = {
            "lat": float(self.users.home_lat[u] + rng.normal(0, 0.5)),
            "lon": float(self.users.home_lon[u] + rng.normal(0, 0.5)),
        }
        txn["merchant_id"] = str(
            self.merchant_ids[int(rng.integers(0, len(self.merchant_ids)))])
        device = self.device_ids[int(rng.integers(0, len(self.device_ids)))]
        txn["device_id"] = device
        txn["device_fingerprint"] = device
        txn["ip_address"] = self.ips[int(rng.integers(0, len(self.ips)))]
        txn["is_fraud"] = True
        txn["fraud_type"] = "fraud_ring"
        # benign-looking prior: the incumbent has no reason to flag it
        txn["fraud_score"] = float(rng.uniform(0.0, 0.3))
        txn["fraud_reason"] = (
            "coordinated ring (shared devices/merchants/IPs across cohort)")
        self.applied += 1
        return txn

    def stats(self) -> Dict[str, Any]:
        return {
            "members": len(self.member_ids),
            "merchants": len(self.merchant_ids),
            "devices": len(self.device_ids),
            "ips": len(self.ips),
            "category": self.config.merchant_category,
            "rate": self.config.rate,
            "applied": self.applied,
        }

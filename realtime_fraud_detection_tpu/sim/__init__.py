from realtime_fraud_detection_tpu.sim.simulator import (  # noqa: F401
    UserPool,
    MerchantPool,
    TransactionGenerator,
)
from realtime_fraud_detection_tpu.sim.fraud_patterns import (  # noqa: F401
    FraudScenario,
    AdvancedFraudPatterns,
    BASIC_FRAUD_MIX,
)
from realtime_fraud_detection_tpu.sim.arrivals import (  # noqa: F401
    DiurnalBurstConfig,
    DiurnalBurstProcess,
)

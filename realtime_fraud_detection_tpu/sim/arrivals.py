"""Nonstationary offered-load generator: diurnal ramp + Poisson bursts.

The reference simulator paces a single flat TPS with ``sleep(1/tps)``
(simulator.py:437-449); real payment traffic is nothing like that — it
ramps through a diurnal cycle and spikes in bursts (flash sales, batch
retries, regional wakeups). This module generates explicit arrival
TIMESTAMPS for such a process, as a first-class simulator feature:

- the base rate follows a raised-cosine diurnal ramp between
  ``trough_tps`` and ``peak_tps`` over ``period_s`` (a drill compresses a
  day into virtual seconds by shrinking the period);
- bursts arrive on a deterministic schedule (``burst_every_s`` apart,
  starting at ``burst_offset_s``), each multiplying the instantaneous
  rate by ``burst_mult`` for ``burst_duration_s``;
- arrivals are drawn from the resulting nonhomogeneous Poisson process by
  Lewis thinning — fully seedable, so the same seed reproduces the same
  timeline bit-for-bit;
- timestamps are plain floats from ``t0`` on whatever clock base the
  caller uses (the drills' virtual clock, or wall time), so the process
  is virtual-clock compatible by construction.

Consumed by ``rtfd autotune-drill`` (tuning/drill.py) and available to
any future scenario drill (flash crowds, regional failure) that needs
nonstationary offered load.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

__all__ = ["DiurnalBurstProcess", "DiurnalBurstConfig"]


@dataclasses.dataclass
class DiurnalBurstConfig:
    """Shape of the offered load. Rates are instantaneous txn/s."""

    trough_tps: float = 200.0
    peak_tps: float = 2_000.0
    period_s: float = 10.0          # one full diurnal cycle
    # burst schedule: deterministic spacing so drills can pin which
    # phases contain bursts; each burst multiplies the diurnal rate
    burst_every_s: float = 2.5
    burst_offset_s: float = 1.25
    burst_duration_s: float = 0.25
    burst_mult: float = 4.0
    t0: float = 0.0

    def validate(self) -> None:
        if not (0.0 < self.trough_tps <= self.peak_tps):
            raise ValueError(
                f"arrivals require 0 < trough_tps <= peak_tps, got "
                f"trough={self.trough_tps} peak={self.peak_tps}")
        if self.period_s <= 0 or self.burst_duration_s < 0 \
                or self.burst_mult < 1.0:
            raise ValueError(
                "arrivals require period_s > 0, burst_duration_s >= 0 "
                "and burst_mult >= 1")
        if self.burst_every_s <= 0:
            raise ValueError("arrivals require burst_every_s > 0")


class DiurnalBurstProcess:
    """Seedable nonhomogeneous Poisson arrival-time generator."""

    def __init__(self, config: DiurnalBurstConfig | None = None,
                 seed: int = 7):
        self.config = config or DiurnalBurstConfig()
        self.config.validate()
        self.rng = np.random.default_rng(seed)

    # ------------------------------------------------------------- intensity
    def _rates(self, t: np.ndarray) -> np.ndarray:
        """Vectorized deterministic intensity at each time in ``t`` —
        independent of the rng, so tests can pin the envelope exactly."""
        c = self.config
        rel = np.asarray(t, float) - c.t0
        # raised cosine: trough at phase 0, peak at phase 0.5
        phase = np.mod(rel, c.period_s) / c.period_s
        rates = (c.trough_tps
                 + (c.peak_tps - c.trough_tps)
                 * 0.5 * (1.0 - np.cos(2.0 * math.pi * phase)))
        if c.burst_duration_s > 0:
            in_cycle = np.mod(rel - c.burst_offset_s, c.burst_every_s)
            rates = np.where((rel >= c.burst_offset_s)
                             & (in_cycle < c.burst_duration_s),
                             rates * c.burst_mult, rates)
        return np.where(rel < 0, 0.0, rates)

    def rate_at(self, t: float) -> float:
        """Scalar convenience over :meth:`_rates`."""
        return float(self._rates(np.asarray([t]))[0])

    def peak_rate(self) -> float:
        return self.config.peak_tps * max(1.0, self.config.burst_mult)

    # ------------------------------------------------------------- sampling
    def generate(self, duration_s: float) -> np.ndarray:
        """Arrival timestamps in ``[t0, t0 + duration_s)`` by Lewis
        thinning: homogeneous candidates at the peak rate, kept with
        probability rate(t)/peak. Sorted, float64, deterministic per
        seed."""
        c = self.config
        lam_max = self.peak_rate()
        n_cand = self.rng.poisson(lam_max * duration_s)
        cand = np.sort(self.rng.uniform(0.0, duration_s, n_cand)) + c.t0
        if n_cand == 0:
            return cand
        keep = self.rng.uniform(0.0, lam_max, n_cand) < self._rates(cand)
        return cand[keep]

    def paired_with(self, generator: Any,
                    duration_s: float) -> List[Tuple[float, Dict]]:
        """(arrival_ts, transaction) pairs: the offered-load timeline
        joined to a ``TransactionGenerator``'s record stream — what a
        drill's drive loop feeds the broker."""
        times = self.generate(duration_s)
        txns = generator.generate_batch(len(times))
        return list(zip(times.tolist(), txns))

    def summary(self, times: Sequence[float]) -> Dict[str, Any]:
        """Compact stats over a generated timeline (drill reporting)."""
        times = np.asarray(times, float)
        if times.size == 0:
            return {"n": 0}
        gaps = np.diff(times) if times.size > 1 else np.array([0.0])
        return {
            "n": int(times.size),
            "span_s": round(float(times[-1] - times[0]), 4),
            "mean_tps": round(
                float(times.size / max(times[-1] - times[0], 1e-9)), 1),
            "min_gap_us": round(float(gaps.min()) * 1e6, 2),
            "p99_gap_ms": round(
                float(np.percentile(gaps, 99)) * 1e3, 4),
        }

"""Tensorized isolation forest.

The reference serves a sklearn IsolationForest (contamination 0.1, 100
estimators — config.py:186-198) and maps its ``decision_function`` through a
sigmoid to get fraud probability: ``1/(1+exp(score))``
(model_manager.py:338-346). Here each isolation tree becomes the same
complete-binary-tree tensor layout as the GBDT (models/trees.py), with leaves
holding the *path length* estimate h = depth + c(n_leaf); scoring is the
standard anomaly score s = 2^(-E[h]/c(psi)) and the sklearn-compatible
decision function 0.5 - s, so the reference's probability mapping carries
over unchanged.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


def _c(n: float) -> float:
    """Average unsuccessful BST search length c(n) (Liu et al. 2008)."""
    if n <= 1:
        return 0.0
    h = math.log(n - 1) + 0.5772156649015329
    return 2.0 * h - 2.0 * (n - 1) / n


@struct.dataclass
class IsolationForest:
    """Complete-binary-tree isolation forest parameters (pytree)."""

    feature: jax.Array     # i32[T, I]
    threshold: jax.Array   # f32[T, I]
    path_length: jax.Array  # f32[T, L] — h estimate per leaf
    c_psi: jax.Array       # f32[] normalizer c(psi)

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]


def iforest_scores(forest: IsolationForest, x: jax.Array,
                   kernel: str = "gather", paths=None) -> jax.Array:
    """Anomaly score s in (0, 1]; higher = more anomalous. f32[B].

    ``kernel`` selects the traversal (models/trees.py): ``"gather"`` (the
    oracle) or ``"gemm"`` (Hummingbird-style one-hot contractions over the
    same complete-tree layout — identical leaves, path lengths summed in
    a different order, so scores agree to float tolerance).
    """
    from realtime_fraud_detection_tpu.models.trees import (
        descend_complete_trees,
        gather_leaf_values,
        gemm_leaf_contract,
    )

    if kernel == "gemm":
        h = gemm_leaf_contract(forest.feature, forest.threshold,
                               forest.path_length, x, paths=paths)  # [B, T]
    elif kernel == "gather":
        leaf_idx = descend_complete_trees(forest.feature, forest.threshold, x)
        h = gather_leaf_values(forest.path_length, leaf_idx)  # [B, T]
    else:
        raise ValueError(
            f"iforest kernel must be 'gather' or 'gemm', got {kernel!r}")
    mean_h = h.mean(axis=1)
    return jnp.exp2(-mean_h / forest.c_psi)


@partial(jax.jit, static_argnames=("kernel",))
def iforest_predict(forest: IsolationForest, x: jax.Array,
                    kernel: str = "gather", paths=None) -> jax.Array:
    """Fraud probability via the reference mapping (model_manager.py:338-346).

    decision_function = 0.5 - s (sklearn offset convention), then
    p = 1/(1+exp(decision)).
    """
    decision = 0.5 - iforest_scores(forest, x, kernel=kernel, paths=paths)
    return 1.0 / (1.0 + jnp.exp(decision))


@dataclasses.dataclass
class IsolationForestTrainer:
    """Fits isolation trees on subsamples with random splits."""

    n_estimators: int = 100
    max_samples: int = 256
    seed: int = 42

    def fit(self, x: np.ndarray) -> IsolationForest:
        rng = np.random.default_rng(self.seed)
        x = np.asarray(x, np.float32)
        n, f = x.shape
        psi = min(self.max_samples, n)
        depth = max(1, int(np.ceil(np.log2(psi))))
        n_internal = 2**depth - 1
        n_leaf = 2**depth

        feat = np.zeros((self.n_estimators, n_internal), np.int32)
        thr = np.full((self.n_estimators, n_internal), np.inf, np.float32)
        plen = np.zeros((self.n_estimators, n_leaf), np.float32)

        for t in range(self.n_estimators):
            idx = rng.choice(n, size=psi, replace=False)
            # node -> sample index list; grow breadth-first over the complete tree
            members: dict[int, np.ndarray] = {0: idx}
            for node in range(n_internal):
                rows = members.pop(node, None)
                if rows is None:
                    continue
                level = int(np.log2(node + 1))
                if len(rows) <= 1:
                    self._seal(node, level, depth, len(rows), thr[t], plen[t])
                    continue
                sub = x[rows]
                lo, hi = sub.min(axis=0), sub.max(axis=0)
                splittable = np.where(hi > lo)[0]
                if splittable.size == 0:
                    self._seal(node, level, depth, len(rows), thr[t], plen[t])
                    continue
                j = int(rng.choice(splittable))
                s = float(rng.uniform(lo[j], hi[j]))
                feat[t, node] = j
                thr[t, node] = s
                right = sub[:, j] >= s
                members[2 * node + 1] = rows[~right]
                members[2 * node + 2] = rows[right]
            # max-depth leaves
            for node, rows in members.items():
                leaf = node - n_internal
                plen[t, leaf] = depth + _c(len(rows))

        return IsolationForest(
            feature=jnp.asarray(feat),
            threshold=jnp.asarray(thr),
            path_length=jnp.asarray(plen),
            c_psi=jnp.asarray(_c(psi), jnp.float32),
        )

    @staticmethod
    def _seal(node: int, level: int, depth: int, n_rows: int,
              thr: np.ndarray, plen: np.ndarray) -> None:
        """Terminate a node early: inf thresholds route left to one leaf."""
        h = level + _c(n_rows)
        n_internal = thr.shape[0]
        # walk leftmost chain to the leaf, marking inf thresholds
        cur = node
        for _ in range(depth - level):
            thr[cur] = np.inf
            cur = 2 * cur + 1
        first_leaf = cur - n_internal
        span = 2 ** (depth - level)
        plen[first_leaf : first_leaf + span] = h

"""Tensorized gradient-boosted tree inference.

The reference scores XGBoost per request on CPU
(model_manager.py:309-311, called one transaction at a time from
ensemble_predictor.py:185-215). Tree traversal is branchy and
data-dependent — the worst possible shape for XLA — so we re-represent every
tree as a *complete* binary tree of fixed depth D:

- ``feature``   i32[T, 2^D - 1]  split feature per internal node
- ``threshold`` f32[T, 2^D - 1]  split threshold (x < t goes left)
- ``leaf``      f32[T, 2^D]      leaf values (log-odds contributions)

Traversal is then D data-independent gather steps: ``node = 2*node + 1 +
(x[feature] >= threshold)``. All shapes static, no control flow — the whole
ensemble jits into a handful of fused gathers on TPU and batches trivially.
Nodes that the trainer left unsplit get ``threshold=+inf`` so every row routes
left toward the real leaf (right subtree duplicates it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class TreeEnsemble:
    """Complete-binary-tree GBDT parameters (pytree)."""

    feature: jax.Array    # i32[T, I] with I = 2^depth - 1
    threshold: jax.Array  # f32[T, I]
    leaf: jax.Array       # f32[T, L] with L = 2^depth
    base_score: jax.Array  # f32[] prior logit

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def depth(self) -> int:
        return int(np.log2(self.leaf.shape[1]))

    @classmethod
    def zeros(cls, n_trees: int, depth: int, prior: float = 0.0) -> "TreeEnsemble":
        n_internal = 2**depth - 1
        return cls(
            feature=jnp.zeros((n_trees, n_internal), jnp.int32),
            threshold=jnp.full((n_trees, n_internal), jnp.inf, jnp.float32),
            leaf=jnp.zeros((n_trees, 2**depth), jnp.float32),
            base_score=jnp.asarray(prior, jnp.float32),
        )


def descend_complete_trees(
    feature: jax.Array, threshold: jax.Array, x: jax.Array
) -> jax.Array:
    """Shared complete-tree traversal: leaf index per (row, tree).

    feature/threshold: [T, 2^D - 1]; x: f32[B, F]. Returns i32[B, T] leaf
    indices in [0, 2^D). D unrolled data-independent gather steps; the single
    split convention for the whole framework is **x >= threshold goes
    right** (GBDT forward, GBDT trainer, isolation forest all share it).
    """
    b = x.shape[0]
    t, n_internal = feature.shape
    depth = int(np.log2(n_internal + 1))

    feat_flat = feature.reshape(-1)      # [T * I]
    thr_flat = threshold.reshape(-1)     # [T * I]
    tree_offset = jnp.arange(t, dtype=jnp.int32) * n_internal  # [T]

    node = jnp.zeros((b, t), jnp.int32)
    for _ in range(depth):
        flat = node + tree_offset[None, :]               # [B, T]
        feat = feat_flat[flat]                           # [B, T]
        thr = thr_flat[flat]                             # [B, T]
        xv = jnp.take_along_axis(x, feat, axis=1)        # [B, T]
        node = 2 * node + 1 + (xv >= thr).astype(jnp.int32)
    return node - n_internal                              # [B, T] in [0, L)


def gather_leaf_values(leaf: jax.Array, leaf_idx: jax.Array) -> jax.Array:
    """leaf: [T, L], leaf_idx: i32[B, T] -> f32[B, T] values."""
    t, l = leaf.shape
    leaf_flat = leaf.reshape(-1)
    offset = jnp.arange(t, dtype=jnp.int32) * l
    return leaf_flat[leaf_idx + offset[None, :]]


def tree_ensemble_logits(ensemble: TreeEnsemble, x: jax.Array) -> jax.Array:
    """Raw log-odds for a feature batch. x: f32[B, F] -> f32[B]."""
    leaf_idx = descend_complete_trees(ensemble.feature, ensemble.threshold, x)
    values = gather_leaf_values(ensemble.leaf, leaf_idx)
    return ensemble.base_score + values.sum(axis=1)


@jax.jit
def tree_ensemble_predict(ensemble: TreeEnsemble, x: jax.Array) -> jax.Array:
    """Fraud probability, the predict_proba[:, 1] equivalent. f32[B]."""
    return jax.nn.sigmoid(tree_ensemble_logits(ensemble, x))

"""Tensorized gradient-boosted tree inference.

The reference scores XGBoost per request on CPU
(model_manager.py:309-311, called one transaction at a time from
ensemble_predictor.py:185-215). Tree traversal is branchy and
data-dependent — the worst possible shape for XLA — so we re-represent every
tree as a *complete* binary tree of fixed depth D:

- ``feature``   i32[T, 2^D - 1]  split feature per internal node
- ``threshold`` f32[T, 2^D - 1]  split threshold (x < t goes left)
- ``leaf``      f32[T, 2^D]      leaf values (log-odds contributions)

Traversal is then D data-independent gather steps: ``node = 2*node + 1 +
(x[feature] >= threshold)``. All shapes static, no control flow — the whole
ensemble jits into a handful of fused gathers on TPU and batches trivially.
Nodes that the trainer left unsplit get ``threshold=+inf`` so every row routes
left toward the real leaf (right subtree duplicates it).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class TreeEnsemble:
    """Complete-binary-tree GBDT parameters (pytree)."""

    feature: jax.Array    # i32[T, I] with I = 2^depth - 1
    threshold: jax.Array  # f32[T, I]
    leaf: jax.Array       # f32[T, L] with L = 2^depth
    base_score: jax.Array  # f32[] prior logit

    @property
    def n_trees(self) -> int:
        return self.feature.shape[0]

    @property
    def depth(self) -> int:
        return int(np.log2(self.leaf.shape[1]))

    @classmethod
    def zeros(cls, n_trees: int, depth: int, prior: float = 0.0) -> "TreeEnsemble":
        n_internal = 2**depth - 1
        return cls(
            feature=jnp.zeros((n_trees, n_internal), jnp.int32),
            threshold=jnp.full((n_trees, n_internal), jnp.inf, jnp.float32),
            leaf=jnp.zeros((n_trees, 2**depth), jnp.float32),
            base_score=jnp.asarray(prior, jnp.float32),
        )


def descend_complete_trees(
    feature: jax.Array, threshold: jax.Array, x: jax.Array
) -> jax.Array:
    """Shared complete-tree traversal: leaf index per (row, tree).

    feature/threshold: [T, 2^D - 1]; x: f32[B, F]. Returns i32[B, T] leaf
    indices in [0, 2^D). D unrolled data-independent gather steps; the single
    split convention for the whole framework is **x >= threshold goes
    right** (GBDT forward, GBDT trainer, isolation forest all share it).
    """
    b = x.shape[0]
    t, n_internal = feature.shape
    depth = int(np.log2(n_internal + 1))

    feat_flat = feature.reshape(-1)      # [T * I]
    thr_flat = threshold.reshape(-1)     # [T * I]
    tree_offset = jnp.arange(t, dtype=jnp.int32) * n_internal  # [T]

    node = jnp.zeros((b, t), jnp.int32)
    for _ in range(depth):
        flat = node + tree_offset[None, :]               # [B, T]
        feat = feat_flat[flat]                           # [B, T]
        thr = thr_flat[flat]                             # [B, T]
        xv = jnp.take_along_axis(x, feat, axis=1)        # [B, T]
        node = 2 * node + 1 + (xv >= thr).astype(jnp.int32)
    return node - n_internal                              # [B, T] in [0, L)


def gather_leaf_values(leaf: jax.Array, leaf_idx: jax.Array) -> jax.Array:
    """leaf: [T, L], leaf_idx: i32[B, T] -> f32[B, T] values."""
    t, l = leaf.shape
    leaf_flat = leaf.reshape(-1)
    offset = jnp.arange(t, dtype=jnp.int32) * l
    return leaf_flat[leaf_idx + offset[None, :]]


# --------------------------------------------------------------------------
# GEMM-form traversal (Hummingbird, arXiv:2010.04804): the same complete
# trees re-expressed as batched tensor contractions the MXU actually likes.
# Selectable per branch via utils.config.QuantSettings; the gather path
# above stays the numerics oracle (leaf-index equality pinned in tests and
# by `rtfd quant-drill`).
# --------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _complete_tree_paths(depth: int) -> tuple:
    """Structure constants of a complete binary tree of ``depth``:

    ``C`` i8[I, L] — +1 where leaf ``l`` sits in the LEFT subtree of
    internal node ``i``, -1 for the right subtree, 0 when ``i`` is not an
    ancestor; ``d`` i32[L] — the number of left edges on the path to
    ``l``. Depends only on the depth, so it folds into the compiled
    program as a constant.
    """
    n_internal = 2 ** depth - 1
    n_leaf = 2 ** depth
    c = np.zeros((n_internal, n_leaf), np.int8)
    d = np.zeros((n_leaf,), np.int32)
    for leaf in range(n_leaf):
        node = leaf + n_internal
        while node:
            parent = (node - 1) // 2
            is_left = node == 2 * parent + 1
            c[parent, leaf] = 1 if is_left else -1
            if is_left:
                d[leaf] += 1
            node = parent
    return c, d


def gemm_leaf_onehot(
    feature: jax.Array, threshold: jax.Array, x: jax.Array,
    paths=None,
) -> jax.Array:
    """One-hot leaf selection as batched matmuls. f32[B, T, L].

    Three contractions (the Hummingbird GEMM strategy): (1) a one-hot
    feature-selection tensor built from the runtime ``feature`` params
    routes ``x`` to every internal node at once, (2) the left-indicator
    matrix contracts with the ancestor-structure constants ``C``, and (3)
    the leaf whose count of satisfied ancestor conditions equals its
    left-edge count ``d`` lights up. The split convention matches
    ``descend_complete_trees`` EXACTLY — ``left = NOT (x >= t)`` — so
    unsplit nodes (threshold=+inf) route identically and the selected
    leaf indices are equal by construction on finite features (the §2.3
    feature contract; a non-finite feature would poison the selection
    contraction, where the gather path localizes it). All count
    arithmetic involves small integers (<= depth), exact in f32.
    """
    t, n_internal = feature.shape
    depth = int(np.log2(n_internal + 1))
    f_dim = x.shape[1]
    # ``paths`` lets a Pallas caller (ops/megakernel.py) ride the ancestor
    # constants in as kernel operands — a kernel body cannot close over
    # concrete arrays. Default: the lru_cached compile-time constants.
    c, d = _complete_tree_paths(depth) if paths is None else paths
    sel = (feature[:, :, None]
           == jnp.arange(f_dim, dtype=feature.dtype)[None, None, :])
    xv = jnp.einsum("bf,tif->bti", x, sel.astype(x.dtype))     # [B, T, I]
    left = 1.0 - (xv >= threshold[None, :, :]).astype(x.dtype)
    reach = jnp.einsum("bti,il->btl", left,
                       jnp.asarray(c, x.dtype))                # [B, T, L]
    return (reach == jnp.asarray(d, x.dtype)[None, None, :]).astype(x.dtype)


def gemm_leaf_index(
    feature: jax.Array, threshold: jax.Array, x: jax.Array,
    paths=None,
) -> jax.Array:
    """GEMM-path leaf indices i32[B, T] — the oracle-comparison hook:
    equal to ``descend_complete_trees`` on every input, by test."""
    onehot = gemm_leaf_onehot(feature, threshold, x, paths=paths)
    return jnp.argmax(onehot, axis=2).astype(jnp.int32)


def gemm_leaf_contract(
    feature: jax.Array, threshold: jax.Array, values: jax.Array,
    x: jax.Array, paths=None,
) -> jax.Array:
    """One-hot leaf selection contracted with per-leaf ``values`` [T, L]
    -> f32[B, T]: the GEMM-form replacement for descend+gather, shared by
    the GBDT (leaf log-odds) and the isolation forest (path lengths)."""
    onehot = gemm_leaf_onehot(feature, threshold, x, paths=paths)
    return jnp.einsum("btl,tl->bt", onehot, values)


def tree_ensemble_logits(ensemble: TreeEnsemble, x: jax.Array,
                         kernel: str = "gather", paths=None) -> jax.Array:
    """Raw log-odds for a feature batch. x: f32[B, F] -> f32[B].

    ``kernel`` selects the traversal: ``"gather"`` (the D-step gather
    oracle above) or ``"gemm"`` (batched contractions). Same signature,
    same split convention, identical leaves; leaf-value summation order
    differs, so logits agree to float tolerance, not bit-for-bit.
    """
    if kernel == "gemm":
        values = gemm_leaf_contract(ensemble.feature, ensemble.threshold,
                                    ensemble.leaf, x, paths=paths)
    elif kernel == "gather":
        leaf_idx = descend_complete_trees(ensemble.feature,
                                          ensemble.threshold, x)
        values = gather_leaf_values(ensemble.leaf, leaf_idx)
    else:
        raise ValueError(
            f"tree kernel must be 'gather' or 'gemm', got {kernel!r}")
    return ensemble.base_score + values.sum(axis=1)


@partial(jax.jit, static_argnames=("kernel",))
def tree_ensemble_predict(ensemble: TreeEnsemble, x: jax.Array,
                          kernel: str = "gather", paths=None) -> jax.Array:
    """Fraud probability, the predict_proba[:, 1] equivalent. f32[B]."""
    return jax.nn.sigmoid(
        tree_ensemble_logits(ensemble, x, kernel=kernel, paths=paths))

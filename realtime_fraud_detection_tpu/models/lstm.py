"""LSTM sequential fraud model.

Capability mirror of ``lstm_sequential`` (reference config.py:151-157:
sequence_length 10, 128 hidden units, dropout 0.2; served via Keras
``model.predict`` one request at a time, model_manager.py:313-319). Rebuilt
TPU-first:

- single fused gate matmul per step: x@Wx + h@Wh is one (B, F+H) x (F+H, 4H)
  MXU call after concatenation;
- ``lax.scan`` over the (static) sequence axis — no Python loops in jit;
- front-padded sequences with a step mask so short histories keep their
  state instead of ingesting pad zeros;
- bf16 compute / f32 state per the global precision policy.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np


def init_lstm_params(
    key: jax.Array,
    feature_dim: int = 64,
    hidden: int = 128,
    head_hidden: int = 64,
) -> Dict[str, jax.Array]:
    """Glorot-initialized LSTM + MLP-head parameters (pytree)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale_in = float(np.sqrt(2.0 / (feature_dim + hidden + 4 * hidden)))
    params = {
        "w_gates": jax.random.normal(k1, (feature_dim + hidden, 4 * hidden), jnp.float32) * scale_in,
        "b_gates": jnp.zeros((4 * hidden,), jnp.float32),
        "w_head1": jax.random.normal(k2, (hidden, head_hidden), jnp.float32)
        * float(np.sqrt(2.0 / hidden)),
        "b_head1": jnp.zeros((head_hidden,), jnp.float32),
        "w_head2": jax.random.normal(k3, (head_hidden, 1), jnp.float32)
        * float(np.sqrt(2.0 / head_hidden)),
        "b_head2": jnp.zeros((1,), jnp.float32),
    }
    # forget-gate bias init to 1 (standard stabilizer)
    hidden_slice = jnp.zeros((4 * hidden,)).at[hidden : 2 * hidden].set(1.0)
    params["b_gates"] = params["b_gates"] + hidden_slice
    del k4
    return params


def lstm_logits(
    params: Dict[str, jax.Array],
    sequences: jax.Array,       # f32[B, T, F] front-padded
    lengths: jax.Array | None = None,  # i32[B] valid suffix lengths
    compute_dtype=jnp.bfloat16,
) -> jax.Array:
    """Fraud logit per sequence. f32[B]."""
    b, t, f = sequences.shape
    hidden = params["w_head1"].shape[0]
    w = params["w_gates"].astype(compute_dtype)
    bg = params["b_gates"].astype(jnp.float32)

    if lengths is None:
        step_valid = jnp.ones((t, b), bool)
    else:
        # front-padded: step i is valid iff i >= T - length
        idx = jnp.arange(t)[:, None]
        step_valid = idx >= (t - lengths)[None, :]

    xs = jnp.swapaxes(sequences, 0, 1).astype(compute_dtype)  # [T, B, F]

    def step(carry, inp):
        h, c = carry
        x, valid = inp
        z = jnp.concatenate([x, h.astype(compute_dtype)], axis=-1) @ w
        z = z.astype(jnp.float32) + bg
        i, fg, g, o = jnp.split(z, 4, axis=-1)
        i, fg, o = jax.nn.sigmoid(i), jax.nn.sigmoid(fg), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = fg * c + i * g
        h_new = o * jnp.tanh(c_new)
        m = valid[:, None]
        return (jnp.where(m, h_new, h), jnp.where(m, c_new, c)), None

    h0 = jnp.zeros((b, hidden), jnp.float32)
    c0 = jnp.zeros((b, hidden), jnp.float32)
    (h, _), _ = jax.lax.scan(step, (h0, c0), (xs, step_valid))

    z = jax.nn.relu(h @ params["w_head1"] + params["b_head1"])
    return (z @ params["w_head2"] + params["b_head2"])[:, 0]


@jax.jit
def lstm_predict(
    params: Dict[str, jax.Array],
    sequences: jax.Array,
    lengths: jax.Array | None = None,
) -> jax.Array:
    """Fraud probability per sequence. f32[B]."""
    return jax.nn.sigmoid(lstm_logits(params, sequences, lengths))

"""Deterministic fraud-domain tokenizer.

The reference loads ``distilbert-base-uncased``'s pretrained tokenizer from
the HuggingFace hub (bert_text_analyzer.py:47-66) and falls back to a dummy
when offline. This environment has zero egress, and the reference's served
weights were random anyway (model_manager.py:332-336 stubs the transformers
branch), so the framework ships its own deterministic tokenizer:

- preprocessing identical to the reference (:228-251): lowercase, strip
  non-alphanumerics, collapse whitespace;
- a built-in fraud-domain vocabulary (every keyword the rule engine knows,
  merchant categories, template words) with stable ids;
- hash-bucketed OOV words (crc32 into a reserved id range) so ANY merchant
  string tokenizes deterministically with no vocab file;
- BERT-convention special ids: [PAD]=0, [UNK]=100, [CLS]=101, [SEP]=102.
"""

from __future__ import annotations

import re
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from realtime_fraud_detection_tpu.models.keywords import vocabulary_words

PAD_ID, UNK_ID, CLS_ID, SEP_ID = 0, 100, 101, 102
_WORD_ID_START = 1000
_HASH_ID_START = 2000


class TokenLruCache:
    """Bounded LRU of text -> token-id rows for the assembly hot path.

    Merchant/description strings are heavily templated, so whole-text rows
    repeat constantly across a stream; caching the encoded row turns most
    per-record tokenization into one dict hit. True LRU (not the old
    clear-when-full wipe): under eviction pressure the hot merchant texts
    stay resident while one-off strings age out. ``hits``/``misses`` are
    cumulative and feed the host-assembly Prometheus series
    (obs/metrics.MetricsCollector.sync_host_stats).
    """

    __slots__ = ("max_entries", "hits", "misses", "_data")

    def __init__(self, max_entries: int = 65_536):
        self.max_entries = max(1, int(max_entries))
        self.hits = 0
        self.misses = 0
        self._data: "OrderedDict[str, Tuple[int, ...]]" = OrderedDict()

    def get(self, key: str) -> Optional[Tuple[int, ...]]:
        row = self._data.get(key)
        if row is None:
            self.misses += 1
            return None
        self._data.move_to_end(key)
        self.hits += 1
        return row

    def put(self, key: str, row: Sequence[int]) -> None:
        data = self._data
        data[key] = tuple(row)
        data.move_to_end(key)
        while len(data) > self.max_entries:
            data.popitem(last=False)

    def __len__(self) -> int:
        return len(self._data)

    def clear(self) -> None:
        self._data.clear()

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "entries": len(self._data), "max_entries": self.max_entries}


class FraudTokenizer:
    """Whitespace word tokenizer with fixed domain vocab + hashed OOV."""

    def __init__(self, vocab_size: int = 30522, max_length: int = 128,
                 cache_entries: int = 65_536):
        self.vocab_size = vocab_size
        self.max_length = max_length
        self.vocab = {w: _WORD_ID_START + i for i, w in enumerate(vocabulary_words())}
        assert _WORD_ID_START + len(self.vocab) <= _HASH_ID_START
        # memo caches for the scoring hot path: whole-text rows in a true
        # LRU (see TokenLruCache), and OOV words repeating across texts
        # (bounded: cleared when full)
        self.text_cache = TokenLruCache(cache_entries)
        self._oov_cache: dict[str, int] = {}

    @staticmethod
    def preprocess(text: str) -> str:
        """Reference preprocessing (bert_text_analyzer.py:228-251)."""
        if not text:
            return ""
        text = text.strip().lower()
        text = re.sub(r"[^a-zA-Z0-9\s]", " ", text)
        return " ".join(text.split())

    def _word_id(self, word: str) -> int:
        wid = self.vocab.get(word)
        if wid is not None:
            return wid
        wid = self._oov_cache.get(word)
        if wid is None:
            span = self.vocab_size - _HASH_ID_START
            wid = _HASH_ID_START + zlib.crc32(word.encode()) % span
            if len(self._oov_cache) >= 100_000:
                self._oov_cache.clear()
            self._oov_cache[word] = wid
        return wid

    def encode(self, text: str) -> List[int]:
        cached = self.text_cache.get(text)
        if cached is not None:
            return list(cached)     # copy: callers may mutate their row
        words = self.preprocess(text).split()
        ids = [CLS_ID] + [self._word_id(w) for w in words] + [SEP_ID]
        ids = ids[: self.max_length]
        self.text_cache.put(text, ids)
        return ids

    def encode_batch(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Batch to fixed (B, max_length) ids + attention mask."""
        b = len(texts)
        ids = np.full((b, self.max_length), PAD_ID, np.int32)
        mask = np.zeros((b, self.max_length), bool)
        for i, text in enumerate(texts):
            row = self.encode(text)
            ids[i, : len(row)] = row
            mask[i, : len(row)] = True
        return ids, mask

    def cache_stats(self) -> dict:
        return self.text_cache.stats()

from realtime_fraud_detection_tpu.models.trees import (  # noqa: F401
    TreeEnsemble,
    tree_ensemble_predict,
    tree_ensemble_logits,
)

"""WordPiece subword tokenizer: trainer + greedy encoder, zero downloads.

The reference loads ``distilbert-base-uncased``'s pretrained WordPiece
tokenizer from the HuggingFace hub (bert_text_analyzer.py:47-66). This
environment has zero egress, so instead of vendoring Google's vocab this
module implements the WordPiece ALGORITHM itself:

- ``train_wordpiece_vocab`` — the likelihood-scored merge trainer (the
  HuggingFace-documented WordPiece objective: repeatedly merge the symbol
  pair maximizing ``count(ab) / (count(a) * count(b))`` — BPE picks the
  raw-count max; WordPiece normalizes by the parts' frequencies), trained
  on the fraud domain's own text distribution (merchant names, categories,
  descriptions from the simulator — the same strings serving tokenizes).
- ``WordPieceTokenizer`` — BERT's greedy longest-match-first encoding with
  ``##`` continuation pieces and per-word [UNK] fallback, the exact
  inference algorithm of the reference's tokenizer, over the trained vocab.

Special ids follow the BERT convention used across this framework
(models/tokenizer.py): [PAD]=0, [UNK]=100, [CLS]=101, [SEP]=102; vocab
pieces start at 1000. A domain vocab trained by ``build_default_vocab`` is
committed at ``wordpiece_vocab.txt`` so serving loads it with no network
and no training step; regenerate with ``python -m
realtime_fraud_detection_tpu.models.wordpiece``.

Unlike the hash-OOV word tokenizer (the throughput-default), every id here
maps to a learned subword: no collisions, graceful decomposition of unseen
merchant names ("cryptopay" -> "crypto ##pay"), which is the property the
reference's text branch relies on for novel merchant strings.
"""

from __future__ import annotations

import collections
from pathlib import Path
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

from realtime_fraud_detection_tpu.models.tokenizer import (
    CLS_ID,
    PAD_ID,
    SEP_ID,
    UNK_ID,
    FraudTokenizer,
)

_PIECE_ID_START = 1000
DEFAULT_VOCAB_PATH = Path(__file__).with_name("wordpiece_vocab.txt")

__all__ = ["train_wordpiece_vocab", "WordPieceTokenizer",
           "build_default_vocab", "DEFAULT_VOCAB_PATH"]


def _word_counts(texts: Iterable[str]) -> Dict[str, int]:
    counts: Dict[str, int] = collections.Counter()
    for text in texts:
        for w in FraudTokenizer.preprocess(text).split():
            counts[w] += 1
    return counts


def train_wordpiece_vocab(
    texts: Iterable[str],
    vocab_size: int = 4096,
    min_pair_count: int = 2,
) -> List[str]:
    """Learn a WordPiece vocabulary from raw texts.

    Initializes with every character (word-initial form and ``##``
    continuation form), then greedily merges the adjacent pair with the
    best WordPiece score ``count(ab) / (count(a)*count(b))`` until the
    vocabulary reaches ``vocab_size`` pieces or no pair clears
    ``min_pair_count``. Deterministic: ties break lexicographically.
    """
    word_counts = _word_counts(texts)
    # each word is a list of current symbols; first symbol bare, rest ##'d
    splits: Dict[str, List[str]] = {
        w: [w[0]] + [f"##{c}" for c in w[1:]] for w in word_counts
    }
    vocab: Dict[str, None] = dict.fromkeys(
        s for parts in splits.values() for s in parts)

    while len(vocab) < vocab_size:
        pair_counts: Dict[Tuple[str, str], int] = collections.Counter()
        sym_counts: Dict[str, int] = collections.Counter()
        for w, parts in splits.items():
            c = word_counts[w]
            for s in parts:
                sym_counts[s] += c
            for a, b in zip(parts, parts[1:]):
                pair_counts[(a, b)] += c
        best, best_score = None, 0.0
        for (a, b), c in pair_counts.items():
            if c < min_pair_count:
                continue
            score = c / (sym_counts[a] * sym_counts[b])
            if score > best_score or (score == best_score
                                      and best is not None
                                      and (a, b) < best):
                best, best_score = (a, b), score
        if best is None:
            break
        a, b = best
        merged = a + b[2:] if b.startswith("##") else a + b
        vocab[merged] = None
        for w, parts in splits.items():
            i = 0
            while i < len(parts) - 1:
                if parts[i] == a and parts[i + 1] == b:
                    parts[i:i + 2] = [merged]
                else:
                    i += 1
    return list(vocab)


class WordPieceTokenizer:
    """Greedy longest-match-first subword encoder over a trained vocab.

    Same surface as ``FraudTokenizer`` (encode / encode_batch with CLS/SEP
    framing and fixed-length padding) so the scorer swaps tokenizers by
    config (``ScorerConfig.tokenizer="wordpiece"``), not by code change.
    """

    def __init__(self, vocab: Sequence[str] | None = None,
                 vocab_path: Path | str | None = None,
                 max_length: int = 128, max_word_chars: int = 64,
                 cache_entries: int = 65_536):
        if vocab is None:
            path = Path(vocab_path) if vocab_path else DEFAULT_VOCAB_PATH
            vocab = [ln.rstrip("\n") for ln in
                     path.read_text(encoding="utf-8").splitlines()
                     if ln.strip()]
        self.pieces = list(vocab)
        self.piece_to_id = {p: _PIECE_ID_START + i
                            for i, p in enumerate(self.pieces)}
        self.vocab_size = _PIECE_ID_START + len(self.pieces)
        self.max_length = max_length
        self.max_word_chars = max_word_chars
        # host-assembly hot path: combined merchant/description texts repeat
        # heavily across a stream, and the greedy longest-match encode is the
        # single most expensive per-record step of assembly — the whole-text
        # LRU turns repeats into one dict hit; the word memo speeds the
        # misses (words repeat across distinct texts). The vocab is fixed
        # after construction, so cached rows can never go stale.
        from realtime_fraud_detection_tpu.models.tokenizer import (
            TokenLruCache,
        )

        self.text_cache = TokenLruCache(cache_entries)
        self._word_cache: Dict[str, List[int]] = {}

    # ------------------------------------------------------------ encoding
    def _encode_word(self, word: str) -> List[int]:
        """BERT's WordPiece inference: greedy longest prefix, ## the rest;
        a word with any un-coverable span becomes one [UNK]."""
        if len(word) > self.max_word_chars:
            return [UNK_ID]
        ids: List[int] = []
        start = 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while end > start:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                pid = self.piece_to_id.get(piece)
                if pid is not None:
                    piece_id = pid
                    break
                end -= 1
            if piece_id is None:
                return [UNK_ID]
            ids.append(piece_id)
            start = end
        return ids

    def _encode_word_cached(self, word: str) -> List[int]:
        ids = self._word_cache.get(word)
        if ids is None:
            if len(self._word_cache) >= 200_000:
                self._word_cache.clear()
            self._word_cache[word] = ids = self._encode_word(word)
        return ids

    def encode(self, text: str) -> List[int]:
        cached = self.text_cache.get(text)
        if cached is not None:
            return list(cached)     # copy: callers may mutate their row
        words = FraudTokenizer.preprocess(text).split()
        ids = [CLS_ID]
        for w in words:
            ids.extend(self._encode_word_cached(w))
        ids.append(SEP_ID)
        ids = ids[: self.max_length]
        self.text_cache.put(text, ids)
        return ids

    def cache_stats(self) -> dict:
        return self.text_cache.stats()

    def encode_batch(self, texts: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        b = len(texts)
        ids = np.full((b, self.max_length), PAD_ID, np.int32)
        mask = np.zeros((b, self.max_length), bool)
        for i, text in enumerate(texts):
            row = self.encode(text)
            ids[i, : len(row)] = row
            mask[i, : len(row)] = True
        return ids, mask

    # ------------------------------------------------------------ decoding
    def decode_pieces(self, ids: Sequence[int]) -> List[str]:
        """Id list back to piece strings (specials named) — for tests and
        debugging, not a serving path."""
        names = {PAD_ID: "[PAD]", UNK_ID: "[UNK]", CLS_ID: "[CLS]",
                 SEP_ID: "[SEP]"}
        out = []
        for i in ids:
            if i in names:
                out.append(names[i])
            elif _PIECE_ID_START <= i < self.vocab_size:
                out.append(self.pieces[i - _PIECE_ID_START])
            else:
                out.append(f"[{i}?]")
        return out


def build_default_vocab(vocab_size: int = 4096, n_texts: int = 40_000,
                        seed: int = 0) -> List[str]:
    """Train the committed domain vocab from the simulator's text
    distribution — the same merchant/category/description strings serving
    assembles (models/text.py combined_text), plus the rule keywords so
    every fraud-signal word is guaranteed a whole-word piece."""
    from realtime_fraud_detection_tpu.models.keywords import vocabulary_words
    from realtime_fraud_detection_tpu.models.text import combined_text
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )

    gen = TransactionGenerator(num_users=4000, num_merchants=1500, seed=seed)
    mp = gen.merchants
    texts = [" ".join(vocabulary_words())]
    _, lab = gen.generate_encoded(n_texts)
    for i in range(n_texts):
        m = int(lab["merchant_index"][i])
        texts.append(combined_text({
            "merchant_name": str(mp.names[m]),
            "category": str(mp.category[m]),
        }))
    return train_wordpiece_vocab(texts, vocab_size=vocab_size)


if __name__ == "__main__":
    pieces = build_default_vocab()
    DEFAULT_VOCAB_PATH.write_text("\n".join(pieces) + "\n", encoding="utf-8")
    print(f"wrote {len(pieces)} pieces to {DEFAULT_VOCAB_PATH}")

"""Transaction text analysis: BERT scoring + keyword rules + text stats.

Capability mirror of ``BertTextAnalyzer`` (bert_text_analyzer.py:21-412),
batched: where the reference runs three separate single-text BERT calls per
transaction (merchant / description / combined, :123-143), this tokenizes
all 3B variants into one (3B, L) batch and makes a single encoder call.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Sequence

import jax
import numpy as np

from realtime_fraud_detection_tpu.models.bert import (
    BertConfig,
    bert_predict,
    init_bert_params,
)
from realtime_fraud_detection_tpu.models.tokenizer import FraudTokenizer

from realtime_fraud_detection_tpu.models.keywords import (  # noqa: F401
    CRYPTO_KEYWORDS,
    GIFT_CARD_KEYWORDS,
    SCAM_PATTERNS,
    SUSPICIOUS_PATTERNS,
    URGENT_KEYWORDS,
)

# Per-field weights for the overall risk (bert_text_analyzer.py:148-152)
FIELD_WEIGHTS = {"merchant_name_risk": 0.4, "description_risk": 0.3,
                 "combined_text_risk": 0.3}


def combined_text(text_data: Mapping[str, str]) -> str:
    """Combined contextual text (bert_text_analyzer.py:253-281)."""
    parts = []
    if text_data.get("merchant_name"):
        parts.append(f"Merchant: {text_data['merchant_name']}")
    if text_data.get("description"):
        parts.append(f"Description: {text_data['description']}")
    if text_data.get("category"):
        parts.append(f"Category: {text_data['category']}")
    if text_data.get("location"):
        parts.append(f"Location: {text_data['location']}")
    return " | ".join(parts)


def _keyword_hit(text: str, keywords) -> bool:
    # word-boundary match for single short keywords ("irs" must not fire
    # inside "first"); plain substring for multi-word phrases
    for k in keywords:
        if " " in k or len(k) >= 6:
            if k in text:
                return True
        elif re.search(rf"\b{re.escape(k)}\b", text):
            return True
    return False


def detect_fraud_patterns(text_data: Mapping[str, str]) -> Dict[str, bool]:
    """Rule-based keyword detection (bert_text_analyzer.py:283-344)."""
    all_text = " ".join(
        text_data.get(k, "") or ""
        for k in ("merchant_name", "description", "category", "location")
    ).lower()
    return {
        "crypto_keywords": _keyword_hit(all_text, CRYPTO_KEYWORDS),
        "gift_card_keywords": _keyword_hit(all_text, GIFT_CARD_KEYWORDS),
        "urgent_language": _keyword_hit(all_text, URGENT_KEYWORDS),
        "suspicious_merchant": _keyword_hit(all_text, SUSPICIOUS_PATTERNS),
        "known_scam_patterns": _keyword_hit(all_text, SCAM_PATTERNS),
    }


def get_text_features(text_data: Mapping[str, str]) -> Dict[str, float]:
    """Numeric text statistics (bert_text_analyzer.py:346-399)."""
    merchant = text_data.get("merchant_name", "") or ""
    description = text_data.get("description", "") or ""
    f: Dict[str, float] = {
        "merchant_name_length": len(merchant),
        "description_length": len(description),
    }
    f["total_text_length"] = f["merchant_name_length"] + f["description_length"]
    if merchant:
        f["merchant_name_unique_chars"] = len(set(merchant.lower()))
        f["merchant_name_char_diversity"] = (
            f["merchant_name_unique_chars"] / max(len(merchant), 1)
        )
    else:
        f["merchant_name_unique_chars"] = 0
        f["merchant_name_char_diversity"] = 0
    f["numbers_in_merchant"] = len(re.findall(r"\d", merchant))
    f["numbers_in_description"] = len(re.findall(r"\d", description))
    f["total_numbers"] = f["numbers_in_merchant"] + f["numbers_in_description"]
    f["special_chars_merchant"] = len(re.findall(r"[^a-zA-Z0-9\s]", merchant))
    f["special_chars_description"] = len(re.findall(r"[^a-zA-Z0-9\s]", description))
    f["total_special_chars"] = (
        f["special_chars_merchant"] + f["special_chars_description"]
    )
    f["merchant_word_count"] = len(merchant.split()) if merchant else 0
    f["description_word_count"] = len(description.split()) if description else 0
    f["total_word_count"] = f["merchant_word_count"] + f["description_word_count"]
    return f


class TextAnalyzer:
    """Batched BERT text analyzer."""

    def __init__(
        self,
        config: BertConfig | None = None,
        params: Dict | None = None,
        max_length: int = 128,
        use_pallas: bool = False,
        seed: int = 0,
    ):
        self.config = config or BertConfig()
        self.tokenizer = FraudTokenizer(self.config.vocab_size, max_length)
        self.params = params if params is not None else init_bert_params(
            jax.random.PRNGKey(seed), self.config
        )
        self.use_pallas = use_pallas
        self.total_predictions = 0
        self.total_time_ms = 0.0
        self._predict = jax.jit(
            lambda p, ids, mask: bert_predict(
                p, ids, mask, self.config, self.use_pallas
            )
        )

    def score_texts(self, texts: Sequence[str]) -> np.ndarray:
        """Fraud probability per text, one compiled encoder call. f32[N].

        Batch is padded to the shared bucket set (core/batching.BATCH_BUCKETS)
        so ragged per-transaction field counts don't trigger a recompile per
        distinct size.
        """
        from realtime_fraud_detection_tpu.core.batching import bucket_for

        n = len(texts)
        bucket = bucket_for(n)
        ids, mask = self.tokenizer.encode_batch(
            list(texts) + [""] * (bucket - n)
        )
        return np.asarray(self._predict(self.params, ids, mask))[:n]

    def analyze_transaction_text(
        self, batch: Sequence[Mapping[str, str]]
    ) -> List[Dict[str, float]]:
        """Per-transaction field risks + weighted overall
        (bert_text_analyzer.py:104-177), batched 3B-wide."""
        import time as _time

        start = _time.time()
        texts: List[str] = []
        index: List[List[tuple[str, int]]] = []
        for td in batch:
            fields = []
            if td.get("merchant_name"):
                fields.append(("merchant_name_risk", len(texts)))
                texts.append(td["merchant_name"])
            if td.get("description"):
                fields.append(("description_risk", len(texts)))
                texts.append(td["description"])
            combo = combined_text(td)
            if combo:
                fields.append(("combined_text_risk", len(texts)))
                texts.append(combo)
            index.append(fields)

        scores = self.score_texts(texts) if texts else np.zeros((0,))
        results = []
        for fields in index:
            res = {name: float(scores[i]) for name, i in fields}
            if res:
                total_w = sum(FIELD_WEIGHTS.get(n, 0.1) for n in res)
                res["overall_text_risk"] = (
                    sum(s * FIELD_WEIGHTS.get(n, 0.1) for n, s in res.items()) / total_w
                    if total_w > 0 else 0.0
                )
            else:
                res["overall_text_risk"] = 0.0
            results.append(res)
        elapsed = (_time.time() - start) * 1000
        self.total_predictions += len(batch)
        self.total_time_ms += elapsed
        return results

    def get_performance_stats(self) -> Dict[str, float]:
        """(bert_text_analyzer.py:401-412)"""
        n = self.total_predictions
        return {
            "total_predictions": n,
            "avg_processing_time_ms": self.total_time_ms / n if n else 0.0,
            "total_processing_time_ms": self.total_time_ms,
        }

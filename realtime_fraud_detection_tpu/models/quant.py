"""Weight-only int8 quantization for the BERT branch.

The fused program's latency is dominated by the text encoder (BENCH_r04:
the BERT branch is the largest per-branch slice of the batch-256 program),
and ``DevicePool`` replicates FULL f32 params onto every chip — so BERT
bytes are both the HBM cap on model size and the bulk of the hot-swap /
replication payload. Per the reduced-precision serving result in the 300M
predictions/sec paper (arXiv:2109.09541) and the repo's own precision
policy (bf16 matmuls / f32 layernorm+softmax, core/precision.py), the
weights can drop to int8 as long as quality is GATED, not assumed:

- **per-output-channel symmetric scales** for every dense kernel
  (``q/k/v/o/ffn1/ffn2``): ``scale[j] = max|w[:, j]| / 127``,
  ``q = round(w / scale)`` clipped to [-127, 127] — symmetric so dequant
  is one multiply, per-channel so one outlier column cannot crush the
  resolution of the rest;
- **per-row scales** for the embedding tables (``word_emb``/``pos_emb``):
  the gather pulls whole rows, so the row is the output channel;
- **dequant-to-bf16 at the matmul seam**: ``models/bert.py`` detects the
  quantized layout structurally and widens ``q * scale`` straight into
  the existing compute-dtype cast, so XLA fuses the dequant into the
  matmul read and the f32 weights never exist in HBM;
- layer norms, biases and the 2-logit classification head stay f32 — they
  are a rounding error in bytes and the head feeds the decision ladder
  directly.

Quantization itself runs HOST-SIDE at model-swap time (set_models /
checkpoint restore), never in the dispatch path: it is calibration work
(one pass over the weights), and the quantized pytree then replicates /
hot-swaps through the exact same score-lock discipline as f32 params.

The quality gate that makes this shippable is ``rtfd quant-drill``
(scoring/quant_drill.py): max quantized-vs-f32 score divergence pinned
below calibration noise, zero operating-point decision flips, AUC
unchanged on the committed quality protocol.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

__all__ = [
    "quantize_dense",
    "quantize_embedding",
    "quantize_bert_params",
    "is_quantized_bert",
    "bert_param_bytes",
    "quant_error_bound",
]

# int8 symmetric range: one code reserved so +/-scale*127 is symmetric
_QMAX = 127.0


def _channel_scales(w: np.ndarray, axis: int) -> np.ndarray:
    """Symmetric per-channel scales over ``axis`` (the reduction axis the
    scale must cover). A zero channel gets scale 1 so dequant stays exact
    zero instead of 0/0."""
    amax = np.max(np.abs(w), axis=axis)
    return np.where(amax > 0.0, amax / _QMAX, 1.0).astype(np.float32)


def quantize_dense(p: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize one dense layer dict ``{"w": f32[in, out], "b": ...}`` to
    ``{"qw": i8[in, out], "scale": f32[out], "b": ...}`` — per-OUTPUT-
    channel symmetric scales, bias untouched."""
    # rtfd-lint: allow[d2h] host-side weight calibration at model-swap time, never in the dispatch path
    w = np.asarray(p["w"], np.float32)
    scale = _channel_scales(w, axis=0)                      # [out]
    q = np.clip(np.rint(w / scale[None, :]), -_QMAX, _QMAX).astype(np.int8)
    return {"qw": q, "scale": scale, "b": p["b"]}


def quantize_embedding(w: Any) -> Dict[str, Any]:
    """Quantize an embedding table f32[rows, h] to ``{"qe": i8[rows, h],
    "scale": f32[rows]}`` — per-ROW scales (the gather's output channel
    is the row)."""
    # rtfd-lint: allow[d2h] host-side weight calibration at model-swap time, never in the dispatch path
    w = np.asarray(w, np.float32)
    scale = _channel_scales(w, axis=1)                      # [rows]
    q = np.clip(np.rint(w / scale[:, None]), -_QMAX, _QMAX).astype(np.int8)
    return {"qe": q, "scale": scale}


def quantize_bert_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize a ``models.bert.init_bert_params``-shaped pytree.

    Every per-layer dense (q/k/v/o/ffn1/ffn2) and both embedding tables go
    int8; layer norms, biases and the classification head (pre_classifier
    + classifier) stay f32. Idempotent: an already-quantized pytree is
    returned unchanged, so a hot-swap path can apply this unconditionally.
    """
    if is_quantized_bert(params):
        return params
    out: Dict[str, Any] = {
        "word_emb": quantize_embedding(params["word_emb"]),
        "pos_emb": quantize_embedding(params["pos_emb"]),
        "emb_ln": params["emb_ln"],
        "pre_classifier": params["pre_classifier"],
        "classifier": params["classifier"],
        "layers": [],
    }
    for layer in params["layers"]:
        out["layers"].append({
            "q": quantize_dense(layer["q"]),
            "k": quantize_dense(layer["k"]),
            "v": quantize_dense(layer["v"]),
            "o": quantize_dense(layer["o"]),
            "attn_ln": layer["attn_ln"],
            "ffn1": quantize_dense(layer["ffn1"]),
            "ffn2": quantize_dense(layer["ffn2"]),
            "ffn_ln": layer["ffn_ln"],
        })
    return out


def is_quantized_bert(params: Any) -> bool:
    """Structural detection of the quantized layout (the same detection
    the compute seam in ``models/bert.py`` uses): the word embedding is a
    ``{"qe", "scale"}`` dict instead of a bare array."""
    try:
        return isinstance(params["word_emb"], dict) \
            and "qe" in params["word_emb"]
    except (TypeError, KeyError, IndexError):
        return False


def bert_param_bytes(params: Any) -> int:
    """Total serialized parameter bytes of a (plain or quantized) BERT
    pytree — the number the ``quantization`` bench stage and the
    ``quant_param_bytes`` Prometheus series report. Uses leaf ``nbytes``
    metadata only; never pulls device buffers."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(params):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            nbytes = np.dtype(np.float32).itemsize * int(np.size(leaf))
        total += int(nbytes)
    return total


def quant_error_bound(params: Dict[str, Any]) -> float:
    """Max absolute weight reconstruction error across quantized leaves —
    half an LSB per channel by construction; reported (not gated) by the
    bench stage as a sanity number."""
    if not is_quantized_bert(params):
        return 0.0
    scales = [params["word_emb"]["scale"], params["pos_emb"]["scale"]]
    for layer in params["layers"]:
        scales.extend(layer[key]["scale"]
                      for key in ("q", "k", "v", "o", "ffn1", "ffn2"))
    # rtfd-lint: allow[d2h] host-side calibration report over weight scales
    worsts = [float(np.max(np.asarray(s))) for s in scales]
    return 0.5 * max(worsts)

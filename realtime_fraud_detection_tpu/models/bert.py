"""DistilBERT-style text encoder in pure JAX with blockwise attention.

The reference's ``bert_text`` branch is a DistilBERT sequence classifier
(config.py:165-170: distilbert-base-uncased, 2 labels; served path stubbed
random at model_manager.py:332-336; the real torch path lives in
bert_text_analyzer.py:179-226). This is the architecture rebuilt TPU-first:

- standard DistilBERT shape: 6 post-LN layers, 12 heads, hidden 768,
  GELU FFN 3072, learned positions, LayerNorm'd embeddings;
- attention runs through the Pallas blockwise kernel (ops/attention.py) on
  TPU, falling back to the XLA reference implementation elsewhere;
- classification head = pre_classifier(768->768, ReLU) -> classifier(768->2)
  on the [CLS] token, exactly DistilBertForSequenceClassification's head;
- bf16 matmuls / f32 layernorm+softmax per the precision policy.
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from realtime_fraud_detection_tpu.ops.attention import (
    attention_reference,
    flash_attention,
)
from realtime_fraud_detection_tpu.ops.dequant_matmul import (
    dequant_matmul,
    dequant_rows,
    matmul_supported,
    rows_supported,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 6
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    layer_norm_eps: float = 1e-12
    num_labels: int = 2

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


TINY_CONFIG = BertConfig(hidden_size=128, num_layers=2, num_heads=2,
                         intermediate_size=256, vocab_size=30522)


def init_bert_params(key: jax.Array, config: BertConfig) -> Dict:
    """Truncated-normal(0.02) init, matching BERT convention."""
    h, ffn = config.hidden_size, config.intermediate_size

    def dense(k, shape):
        return {
            "w": jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) * 0.02,
            "b": jnp.zeros((shape[-1],), jnp.float32),
        }

    def ln():
        return {"scale": jnp.ones((h,), jnp.float32), "bias": jnp.zeros((h,), jnp.float32)}

    keys = jax.random.split(key, 3 + 6 * config.num_layers)
    params: Dict = {
        "word_emb": jax.random.truncated_normal(
            keys[0], -2, 2, (config.vocab_size, h), jnp.float32) * 0.02,
        "pos_emb": jax.random.truncated_normal(
            keys[1], -2, 2, (config.max_position_embeddings, h), jnp.float32) * 0.02,
        "emb_ln": ln(),
        "layers": [],
        "pre_classifier": dense(keys[2], (h, h)),
    }
    for i in range(config.num_layers):
        k = keys[3 + 6 * i : 9 + 6 * i]
        params["layers"].append({
            "q": dense(k[0], (h, h)),
            "k": dense(k[1], (h, h)),
            "v": dense(k[2], (h, h)),
            "o": dense(k[3], (h, h)),
            "attn_ln": ln(),
            "ffn1": dense(k[4], (h, ffn)),
            "ffn2": dense(k[5], (ffn, h)),
            "ffn_ln": ln(),
        })
    params["classifier"] = dense(
        jax.random.fold_in(keys[2], 7), (h, config.num_labels)
    )
    return params


def _layer_norm(x, p, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * p["scale"] + p["bias"])


def _dense(x, p, compute_dtype, dequant_kernel="off", kernel_interpret=False):
    if "qw" in p:
        if dequant_kernel == "pallas":
            # hand-fused Pallas path (ops/dequant_matmul.py): the i8 weight
            # block dequantizes in VMEM right before the MXU dot, guarded
            # by the SAME supports() predicate the scorer's fallback
            # counters consult
            lead = x.shape[:-1]
            k, n = p["qw"].shape
            m = int(np.prod(lead)) if lead else 1
            if matmul_supported(m, k, n):
                y = dequant_matmul(
                    x.reshape(m, k), p["qw"], p["scale"], p["b"],
                    compute_dtype=compute_dtype, interpret=kernel_interpret)
                return y.reshape(*lead, n)
        # weight-only int8 (models/quant.py): dequantize per-output-channel
        # right at the compute-dtype seam — XLA fuses the (i8 -> bf16) *
        # scale widen into the matmul's weight read, so the full-precision
        # kernel never materializes in HBM
        w = p["qw"].astype(compute_dtype) * p["scale"].astype(compute_dtype)
        return x.astype(compute_dtype) @ w + p["b"]
    return x.astype(compute_dtype) @ p["w"].astype(compute_dtype) + p["b"]


def _embedding_rows(table, idx=None, length=None, dequant_kernel="off",
                    kernel_interpret=False):
    """Embedding lookup that understands both layouts: a bare f32 table,
    or the quantized ``{"qe": i8[rows, h], "scale": f32[rows]}`` form
    (per-row scales — the gather's output channel is the row). Returns
    f32 rows either way; ``idx`` gathers, ``length`` slices a prefix."""
    if isinstance(table, dict) and "qe" in table:
        if idx is not None:
            q, s = table["qe"][idx], table["scale"][idx]
        else:
            q, s = table["qe"][:length], table["scale"][:length]
        if dequant_kernel == "pallas":
            # the arbitrary-index gather stays an XLA i8 gather; the
            # per-row widen x scale runs through the Pallas kernel so only
            # i8 rows cross HBM at full width
            h = q.shape[-1]
            rows = int(np.prod(q.shape[:-1]))
            if rows_supported(rows, h):
                out = dequant_rows(q.reshape(rows, h), s.reshape(rows),
                                   interpret=kernel_interpret)
                return out.reshape(*q.shape[:-1], h)
        return q.astype(jnp.float32) * s[..., None]
    return table[idx] if idx is not None else table[:length]


def bert_encode(
    params: Dict,
    input_ids: jax.Array,       # i32[B, S]
    attention_mask: jax.Array,  # bool[B, S]
    config: BertConfig,
    use_pallas: bool = False,
    compute_dtype=jnp.bfloat16,
    attention_fn=None,
    dequant_kernel: str = "off",
    kernel_interpret: bool = False,
) -> jax.Array:
    """Hidden states f32[B, S, H].

    ``attention_fn(q, k, v, key_mask) -> ctx`` overrides the attention
    implementation — the hook context parallelism plugs into
    (``parallel.context.bert_context_parallel_predict`` passes ring
    attention here; everything else in the layer is per-token and shards
    along S for free).

    ``dequant_kernel``/``kernel_interpret`` select the hand-fused Pallas
    dequant path for int8 params (ops/dequant_matmul.py, KernelSettings);
    both are static and only consulted where the quantized layout is
    structurally present.
    """
    x = bert_embed(params, input_ids, config,
                   dequant_kernel=dequant_kernel,
                   kernel_interpret=kernel_interpret)
    for layer in params["layers"]:
        x = bert_layer(layer, x, attention_mask, config,
                       use_pallas=use_pallas, compute_dtype=compute_dtype,
                       attention_fn=attention_fn,
                       dequant_kernel=dequant_kernel,
                       kernel_interpret=kernel_interpret)
    return x


def bert_embed(params: Dict, input_ids: jax.Array,
               config: BertConfig, dequant_kernel: str = "off",
               kernel_interpret: bool = False) -> jax.Array:
    """Token + position embeddings with the embedding layer norm — shared
    by the sequential and pipeline-parallel encoders."""
    s = input_ids.shape[1]
    x = (_embedding_rows(params["word_emb"], idx=input_ids,
                         dequant_kernel=dequant_kernel,
                         kernel_interpret=kernel_interpret)
         + _embedding_rows(params["pos_emb"], length=s,
                           dequant_kernel=dequant_kernel,
                           kernel_interpret=kernel_interpret)[None, :, :])
    return _layer_norm(x, params["emb_ln"], config.layer_norm_eps)


def bert_layer(
    layer: Dict,
    x: jax.Array,               # f32[B, S, H]
    attention_mask: jax.Array,  # bool[B, S]
    config: BertConfig,
    use_pallas: bool = False,
    compute_dtype=jnp.bfloat16,
    attention_fn=None,
    dequant_kernel: str = "off",
    kernel_interpret: bool = False,
) -> jax.Array:
    """One post-LN transformer block — the unit the pipeline-parallel
    schedule (parallel/pipeline.bert_pipeline_encode) spans over stages."""
    b, s = x.shape[:2]
    dk = dict(dequant_kernel=dequant_kernel, kernel_interpret=kernel_interpret)
    q = _dense(x, layer["q"], compute_dtype, **dk)
    k = _dense(x, layer["k"], compute_dtype, **dk)
    v = _dense(x, layer["v"], compute_dtype, **dk)

    def split(t):
        return t.reshape(b, s, config.num_heads,
                         config.head_dim).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q), split(k), split(v)
    if attention_fn is not None:
        ctx = attention_fn(qh, kh, vh, attention_mask)
    elif use_pallas:
        ctx = flash_attention(qh, kh, vh, attention_mask,
                              interpret=kernel_interpret)
    else:
        ctx = attention_reference(qh, kh, vh, attention_mask)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, config.hidden_size)
    attn_out = _dense(ctx, layer["o"], compute_dtype, **dk)
    x = _layer_norm(x + attn_out, layer["attn_ln"], config.layer_norm_eps)

    ffn = _dense(jax.nn.gelu(_dense(x, layer["ffn1"], compute_dtype, **dk)),
                 layer["ffn2"], compute_dtype, **dk)
    return _layer_norm(x + ffn, layer["ffn_ln"], config.layer_norm_eps)


def bert_logits(
    params: Dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: BertConfig,
    use_pallas: bool = False,
    compute_dtype=jnp.bfloat16,
    attention_fn=None,
    dequant_kernel: str = "off",
    kernel_interpret: bool = False,
) -> jax.Array:
    """Sequence-classification logits f32[B, num_labels] from [CLS]."""
    hidden = bert_encode(params, input_ids, attention_mask, config,
                         use_pallas, compute_dtype=compute_dtype,
                         attention_fn=attention_fn,
                         dequant_kernel=dequant_kernel,
                         kernel_interpret=kernel_interpret)
    cls = hidden[:, 0, :]
    z = jax.nn.relu(cls @ params["pre_classifier"]["w"] + params["pre_classifier"]["b"])
    return z @ params["classifier"]["w"] + params["classifier"]["b"]


def bert_predict(
    params: Dict,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    config: BertConfig,
    use_pallas: bool = False,
    compute_dtype=jnp.bfloat16,
    attention_fn=None,
    dequant_kernel: str = "off",
    kernel_interpret: bool = False,
) -> jax.Array:
    """Fraud probability f32[B] = softmax(logits)[:, 1]
    (bert_text_analyzer.py:216-222).

    ``compute_dtype`` widens the matmul seam (core/precision.py); the
    quant drill uses f32 here to measure the calibration-noise floor the
    committed bf16 policy already accepts."""
    logits = bert_logits(params, input_ids, attention_mask, config,
                         use_pallas, compute_dtype=compute_dtype,
                         attention_fn=attention_fn,
                         dequant_kernel=dequant_kernel,
                         kernel_interpret=kernel_interpret)
    return jax.nn.softmax(logits, axis=-1)[:, 1]

"""GraphSAGE user-merchant network scorer.

The reference's "GNN" is a 3-layer MLP over the 64-feature vector
(model_manager.py:202-242) with graph statistics bolted on host-side
(graph_neural_network.py:244-315, last-100-transaction entity graph). The
baseline contract (BASELINE.json config 5) asks for a real **GraphSAGE
user-merchant network scorer**, so that is what this is:

- node features: user nodes and merchant nodes carry small profile-stat
  vectors (padded to a common node_dim);
- one SAGE layer per hop: h' = relu(W [h_self ; mean(h_neighbors)]) with
  mask-aware mean over a fixed fan-out K (padded neighbor tensors from
  state.EntityGraphStore — dense, static shapes, vmap-free batching);
- the scored edge (user u, merchant m) combines both embeddings with the
  transaction's 64-feature vector through an MLP head.

Two-hop batching: neighbors-of-neighbors arrive as [B, K, K] tensors; the
first SAGE layer embeds the 1-hop frontier using 2-hop aggregates, the
second embeds the centers. All gathers are host-prepared index tensors; the
device sees only dense matmuls and masked means (MXU + VPU, no scatter).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

# Node-type tag slots in the node_dim feature row. Users carry no tag
# (their stat slots 0-7 are dense); merchant=8 predates the typed graph;
# device/ip joined with the heterogeneous entity graph (graph/store.py).
MERCHANT_TAG_SLOT = 8
DEVICE_TAG_SLOT = 9
IP_TAG_SLOT = 10
TYPED_MIN_NODE_DIM = 12     # 8 user stats + 3 type tags + 1 degree slot


def init_gnn_params(
    key: jax.Array,
    node_dim: int = 16,
    txn_dim: int = 64,
    hidden: int = 64,
    head_hidden: int = 64,
    typed: bool = False,
) -> Dict[str, jax.Array]:
    """GraphSAGE (2 layers) + head parameters (config.py:177-184: hidden 64,
    3 layers total counting the head, dropout 0.1).

    ``typed=True`` adds per-node-type projection weights (the
    heterogeneous-SAGE / R-GCN relation-weight idiom) consumed by
    :func:`typed_node_projection` ahead of every SAGE aggregation — the
    graph plane's device/IP node types carry degree features in a
    different basis than user/merchant profile stats, and one shared
    aggregation matrix would have to serve all four. The typed layout is
    detected STRUCTURALLY by :func:`gnn_logits` (the models/quant.py
    discipline: a scorer serves whatever parameter form it holds), and
    the checkpoint plane arch-stamps it (``checkpoint._derive_graph_mode``)
    so a cross-form restore is refused, never silent. The (D, D) squares
    follow parallel/layouts.leaf_storage_spec's largest-divisible-dim
    rule for mesh storage sharding like every other GNN leaf."""
    # split count is mode-dependent ON PURPOSE: threefry hashes the full
    # count into every derived key, so splitting 10 unconditionally would
    # silently re-seed the PRE-EXISTING bipartite init (every seed-pinned
    # untyped model would drift). typed=False keeps the committed stream.
    ks = jax.random.split(key, 10 if typed else 6)

    def glorot(k, shape):
        return jax.random.normal(k, shape, jnp.float32) * float(
            np.sqrt(2.0 / (shape[0] + shape[1]))
        )

    params = {
        # layer 1: embeds the 1-hop frontier from raw node features
        "w_sage1": glorot(ks[0], (2 * node_dim, hidden)),
        "b_sage1": jnp.zeros((hidden,), jnp.float32),
        # layer 2: embeds the centers from (raw self, hidden neighbors)
        "w_sage2": glorot(ks[1], (node_dim + hidden, hidden)),
        "b_sage2": jnp.zeros((hidden,), jnp.float32),
        "w_head1": glorot(ks[2], (2 * hidden + txn_dim, head_hidden)),
        "b_head1": jnp.zeros((head_hidden,), jnp.float32),
        "w_head2": glorot(ks[3], (head_hidden, 1)),
        "b_head2": jnp.zeros((1,), jnp.float32),
    }
    if typed:
        if node_dim < TYPED_MIN_NODE_DIM:
            raise ValueError(
                f"typed GNN params need node_dim >= {TYPED_MIN_NODE_DIM} "
                f"(type tags at slots {MERCHANT_TAG_SLOT}/"
                f"{DEVICE_TAG_SLOT}/{IP_TAG_SLOT}), got {node_dim}")
        eye = jnp.eye(node_dim, dtype=jnp.float32)
        for i, name in enumerate(("user", "merchant", "device", "ip")):
            # near-identity init: an untrained typed GNN starts close to
            # the homogeneous one instead of scrambling the node basis
            params[f"w_node_{name}"] = (
                eye + 0.1 * glorot(ks[4 + i], (node_dim, node_dim)))
    return params


def is_typed_gnn(params: Dict[str, jax.Array]) -> bool:
    """Structural detection of the typed parameter layout (no static flag
    — the quant-plane discipline)."""
    return "w_node_user" in params


def typed_node_projection(params: Dict[str, jax.Array],
                          feat: jax.Array) -> jax.Array:
    """Per-node-type linear projection before aggregation.

    The node type is read from the feature row's own tag slots (one-hot
    by construction: the featurizers set exactly one of merchant/device/
    ip, users none), so no extra type tensor rides the batch — the
    projection blends the four relation weights by the tags, which for
    one-hot tags selects exactly one matrix."""
    tm = feat[..., MERCHANT_TAG_SLOT:MERCHANT_TAG_SLOT + 1]
    td = feat[..., DEVICE_TAG_SLOT:DEVICE_TAG_SLOT + 1]
    ti = feat[..., IP_TAG_SLOT:IP_TAG_SLOT + 1]
    tu = jnp.clip(1.0 - tm - td - ti, 0.0, 1.0)
    return (tu * (feat @ params["w_node_user"])
            + tm * (feat @ params["w_node_merchant"])
            + td * (feat @ params["w_node_device"])
            + ti * (feat @ params["w_node_ip"]))


def _masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
    """Mean over axis -2 where mask, else zeros. x: [..., K, D], mask [..., K]."""
    m = mask[..., None].astype(x.dtype)
    total = (x * m).sum(axis=-2)
    count = jnp.maximum(m.sum(axis=-2), 1.0)
    return total / count


def _sage(w, b, self_feat, neigh_feat, neigh_mask):
    agg = _masked_mean(neigh_feat, neigh_mask)
    z = jnp.concatenate([self_feat, agg], axis=-1)
    return jax.nn.relu(z @ w + b)


def gnn_logits(
    params: Dict[str, jax.Array],
    txn_features: jax.Array,     # f32[B, 64]
    user_feat: jax.Array,        # f32[B, node_dim] center user nodes
    merchant_feat: jax.Array,    # f32[B, node_dim] center merchant nodes
    user_neigh_feat: jax.Array,  # f32[B, K, node_dim] merchants around user
    user_neigh_mask: jax.Array,  # bool[B, K]
    merch_neigh_feat: jax.Array,  # f32[B, K, node_dim] users around merchant
    merch_neigh_mask: jax.Array,  # bool[B, K]
    user_neigh2_feat: jax.Array | None = None,   # f32[B, K, K, node_dim]
    user_neigh2_mask: jax.Array | None = None,   # bool[B, K, K]
    merch_neigh2_feat: jax.Array | None = None,  # f32[B, K, K, node_dim]
    merch_neigh2_mask: jax.Array | None = None,  # bool[B, K, K]
) -> jax.Array:
    """Fraud logit per scored (user, merchant, txn) edge. f32[B]."""
    def _empty_frontier(x):
        # [B, K, 1, D] zeros with an all-False mask -> masked mean yields 0
        return x[..., None, :] * 0.0, jnp.zeros(x.shape[:-1] + (1,), bool)

    if is_typed_gnn(params):
        # heterogeneous mode: the txn-feature input is clipped INSIDE the
        # program (the LSTM branch's serving-side-clip precedent,
        # build_sequence_dataset: raw velocity/amount features reach 1e4,
        # far outside a trainable range) — baking the clip into the typed
        # program means training (train_typed_gnn) and serving see
        # identical ranges by construction, with zero train/serve skew.
        # The bipartite program is untouched: its committed behavior
        # (and every score pinned against it) predates the clip.
        txn_features = jnp.clip(txn_features, -10.0, 10.0)
        # rotate every node-feature tensor through its type's projection
        # before any aggregation (the tags live in the rows themselves,
        # so padded/masked rows project to near-zero and the masks still
        # gate them out)
        proj = lambda x: typed_node_projection(params, x)   # noqa: E731
        user_feat, merchant_feat = proj(user_feat), proj(merchant_feat)
        user_neigh_feat = proj(user_neigh_feat)
        merch_neigh_feat = proj(merch_neigh_feat)
        if user_neigh2_feat is not None:
            user_neigh2_feat = proj(user_neigh2_feat)
        if merch_neigh2_feat is not None:
            merch_neigh2_feat = proj(merch_neigh2_feat)

    # layer 1: embed 1-hop frontier (uses 2-hop context when provided)
    if user_neigh2_feat is None:
        user_neigh2_feat, user_neigh2_mask = _empty_frontier(user_neigh_feat)
    if merch_neigh2_feat is None:
        merch_neigh2_feat, merch_neigh2_mask = _empty_frontier(merch_neigh_feat)
    u_frontier = _sage(params["w_sage1"], params["b_sage1"],
                       user_neigh_feat, user_neigh2_feat, user_neigh2_mask)
    m_frontier = _sage(params["w_sage1"], params["b_sage1"],
                       merch_neigh_feat, merch_neigh2_feat, merch_neigh2_mask)

    # layer 2: embed the centers from their (raw, embedded-frontier) context
    h_user = _sage(params["w_sage2"], params["b_sage2"],
                   user_feat, u_frontier, user_neigh_mask)
    h_merch = _sage(params["w_sage2"], params["b_sage2"],
                    merchant_feat, m_frontier, merch_neigh_mask)

    z = jnp.concatenate([h_user, h_merch, txn_features], axis=-1)
    z = jax.nn.relu(z @ params["w_head1"] + params["b_head1"])
    return (z @ params["w_head2"] + params["b_head2"])[:, 0]


@jax.jit
def gnn_predict(params, txn_features, user_feat, merchant_feat,
                user_neigh_feat, user_neigh_mask,
                merch_neigh_feat, merch_neigh_mask) -> jax.Array:
    """1-hop fraud probability (the serving path; 2-hop is a training option)."""
    return jax.nn.sigmoid(gnn_logits(
        params, txn_features, user_feat, merchant_feat,
        user_neigh_feat, user_neigh_mask, merch_neigh_feat, merch_neigh_mask,
    ))


def build_node_features(
    user_pool, merchant_pool, node_dim: int = 16
) -> tuple[np.ndarray, np.ndarray]:
    """Static node feature tables from the profile pools.

    user nodes:   [risk, log-avg-amount, freq, age/365, verified, weekend,
                   intl, online] zero-padded to node_dim
    merchant nodes: [risk_code/2, fraud_rate, log-avg-amount, blacklisted,
                   category/10, op_start/24, op_end/24] zero-padded; slot 8
                   is the merchant type tag, so node_dim must be >= 9.
    """
    if node_dim < 9:
        raise ValueError(f"node_dim must be >= 9 (8 stat slots + type tag), got {node_dim}")
    u = np.zeros((user_pool.n, node_dim), np.float32)
    u[:, 0] = user_pool.risk_score
    u[:, 1] = np.log1p(user_pool.avg_amount)
    u[:, 2] = user_pool.txn_frequency
    u[:, 3] = user_pool.account_age_days / 365.0
    u[:, 4] = (user_pool.kyc_code == 0)
    u[:, 5] = user_pool.weekend_activity
    u[:, 6] = user_pool.intl_ratio
    u[:, 7] = user_pool.online_preference

    m = np.zeros((merchant_pool.n, node_dim), np.float32)
    m[:, 0] = merchant_pool.risk_code / 2.0
    m[:, 1] = merchant_pool.fraud_rate
    m[:, 2] = np.log1p(merchant_pool.avg_amount)
    m[:, 3] = merchant_pool.is_blacklisted
    m[:, 4] = merchant_pool.category_code / 10.0
    m[:, 5] = merchant_pool.op_start / 24.0
    m[:, 6] = merchant_pool.op_end / 24.0
    m[:, 8] = 1.0  # type tag distinguishing merchant nodes from user nodes
    return u, m


def gather_neighbor_features(
    node_table: np.ndarray, idx: np.ndarray, mask: np.ndarray
) -> np.ndarray:
    """Safe gather: padded (-1) indices read row 0 but are masked out."""
    safe = np.where(mask, idx, 0)
    return node_table[safe]


def typed_entity_features(kind: str, degrees: np.ndarray, node_dim: int,
                          fanout: int) -> np.ndarray:
    """Node feature rows for the profile-less entity types (device / IP /
    cold merchant) of the typed graph (graph/store.py).

    These nodes have no profile store behind them; their learnable signal
    is STRUCTURAL — how many distinct users funnel through them, which is
    exactly the fraud-ring signature (a benign device serves one user; a
    ring device serves the cohort). One definition shared by the serving
    sampler AND the training dataset builder, so the GNN always sees the
    featurization it was trained on:

    - slot 0: ring occupancy / fanout  (bounded degree, in [0, 1])
    - slot 1: log1p(degree)            (unsaturated low-end resolution)
    - tag slot (8/9/10): 1.0 for merchant/device/ip respectively
    """
    tag = {"merchant": MERCHANT_TAG_SLOT, "device": DEVICE_TAG_SLOT,
           "ip": IP_TAG_SLOT}.get(kind)
    if tag is None:
        raise ValueError(f"typed_entity_features kind must be "
                         f"merchant|device|ip, got {kind!r}")
    if node_dim < TYPED_MIN_NODE_DIM:
        raise ValueError(
            f"typed entity features need node_dim >= {TYPED_MIN_NODE_DIM}, "
            f"got {node_dim}")
    deg = np.asarray(degrees, np.float32)
    rows = np.zeros((len(deg), node_dim), np.float32)
    rows[:, 0] = np.minimum(deg, float(fanout)) / max(float(fanout), 1.0)
    rows[:, 1] = np.log1p(deg)
    rows[:, tag] = 1.0
    return rows

"""Fraud keyword lists — single source for text rules AND tokenizer vocab.

Groups mirror bert_text_analyzer.py:309-342; the tokenizer derives its
domain vocabulary from these same tuples so a keyword added to a rule group
automatically gets a stable token id.
"""

CRYPTO_KEYWORDS = ("bitcoin", "btc", "ethereum", "eth", "crypto", "blockchain",
                   "coinbase", "binance", "wallet", "mining", "satoshi")
GIFT_CARD_KEYWORDS = ("gift card", "giftcard", "itunes", "amazon card",
                      "google play", "steam card", "prepaid card", "reload card")
URGENT_KEYWORDS = ("urgent", "emergency", "immediate", "quickly", "asap",
                   "limited time", "act now", "expires soon")
SUSPICIOUS_PATTERNS = ("temp", "temporary", "cash advance", "payday", "loan",
                       "invest", "forex", "trading", "pyramid", "mlm")
SCAM_PATTERNS = ("nigerian prince", "inheritance", "lottery winner", "tax refund",
                 "irs", "social security", "medicare", "warranty expired")

ALL_KEYWORD_GROUPS = (CRYPTO_KEYWORDS, GIFT_CARD_KEYWORDS, URGENT_KEYWORDS,
                      SUSPICIOUS_PATTERNS, SCAM_PATTERNS)

# Extra vocabulary: regex tokens (FeatureExtractor.java:30-41), merchant
# categories (simulator.py:255-266), template/common merchant words.
EXTRA_VOCAB_WORDS = (
    "exchange vanilla western union moneygram remit transfer wire paypal venmo "
    "casino gambling betting lottery investment "
    "retail grocery gas station restaurant online pharmacy jewelry electronics "
    "adult entertainment "
    "merchant description category location biz market store shop house depot "
    "corner bros royale mart outlet co company inc llc payment purchase refund "
    "authorization winner prince play card prepaid reload the and of for a"
).split()


def vocabulary_words() -> list[str]:
    """Flat, order-stable word list (multi-word phrases split)."""
    words: list[str] = []
    for group in ALL_KEYWORD_GROUPS:
        for phrase in group:
            words.extend(phrase.split())
    words.extend(EXTRA_VOCAB_WORDS)
    return list(dict.fromkeys(words))

"""TuningPlane: forecaster + JIT closer + online tuner behind one object.

This is what the stream job and the serving app actually hold (the
QosPlane/Tracer pattern): the microbatchers call ``observe``/``should_close``
on the hot path, the completion paths call ``on_batch_complete``, and the
Prometheus mirror reads ``snapshot()`` at exposition time
(``obs.metrics.MetricsCollector.sync_autotune`` — honest counter deltas,
identical series from the stream job and the serving app).

Duck-typing contract: the plane IS the ``controller`` object the
microbatchers take (``MicrobatchAssembler(controller=...)``,
``RequestMicrobatcher(controller=...)``) — they only ever call
``observe(now, n)`` and ``should_close(n, first_ts, now, close_by)``.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

from realtime_fraud_detection_tpu.tuning.controller import (
    CloseDecision,
    JitBatchController,
)
from realtime_fraud_detection_tpu.tuning.forecast import ArrivalForecaster
from realtime_fraud_detection_tpu.tuning.tuner import ConfigTuner

__all__ = ["TuningPlane"]


class TuningPlane:
    """One self-tuning plane per serving app / stream job."""

    def __init__(self, settings: Optional[Any] = None):
        from realtime_fraud_detection_tpu.utils.config import TuningSettings

        self.settings = (settings if settings is not None
                         else TuningSettings(enabled=True))
        s = self.settings
        self.controller = JitBatchController(
            forecaster=ArrivalForecaster(
                bucket_s=s.forecast_bucket_s,
                alpha=s.forecast_alpha,
                beta=s.forecast_beta),
            buckets=tuple(s.bucket_sets[0]),
            max_wait_ms=s.deadline_max_ms,
            patience_factor=s.patience_factor)
        self.tuner = ConfigTuner(s, self.controller)
        # optional burn/ladder source (the serving app wires this to its
        # tracer + QoS plane): () -> (slo_burn_rate, ladder_level). Used
        # when on_batch_complete isn't handed the signals explicitly.
        self.signals_fn = None
        # the completion paths run on a different thread than the
        # microbatcher in serving — one small lock for the shared state
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.settings, "enabled", True))

    # --------------------------------------------------- hot path (batcher)
    def observe(self, now: float, n: int = 1) -> None:
        self.controller.observe(now, n)

    def should_close(self, n: int, first_ts: float, now: float,
                     close_by: Optional[float] = None) -> CloseDecision:
        return self.controller.should_close(n, first_ts, now,
                                            close_by=close_by)

    # ------------------------------------------------------ completion path
    def on_batch_complete(self, n_rows: int, service_s: float, now: float,
                          latencies_ms=None, burn_rate: float = None,
                          ladder_level: int = None) -> None:
        """One completed microbatch: feed the service model, the tuner's
        objective, and the epoch machine. ``latencies_ms`` are the
        admitted per-txn end-to-end latencies the batch just served;
        ``burn_rate``/``ladder_level`` come from the tracing/QoS planes
        when attached — explicitly (the stream job) or via ``signals_fn``
        (the serving app); absent both, calm (0) is assumed."""
        if burn_rate is None or ladder_level is None:
            sig = self.signals_fn() if self.signals_fn is not None \
                else (0.0, 0)
            burn_rate = sig[0] if burn_rate is None else burn_rate
            ladder_level = sig[1] if ladder_level is None else ladder_level
        with self._lock:
            if n_rows > 0:
                self.controller.observe_batch(n_rows, service_s)
            for ms in (latencies_ms or ()):
                self.tuner.observe_result(ms)
            self.tuner.on_batch(now, burn_rate=burn_rate,
                                ladder_level=ladder_level)

    def recommended_inflight_depth(self) -> int:
        """The tuner's current overlap/in-flight depth pick — the run
        loops re-read this each iteration, so a tuner move takes effect
        one batch later with no restart."""
        return int(self.tuner.inflight_depth)

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, Any]:
        """Cumulative plane state for the Prometheus mirror
        (sync_autotune) and the drill verdicts. Counters only ever grow
        (honest-counter discipline)."""
        with self._lock:
            c = self.controller.snapshot()
            t = self.tuner.snapshot()
        return {
            "enabled": self.enabled,
            "controller": c,
            "tuner": t,
            "forecast_tps": round(
                (c["forecast"].get("level_tps") or 0.0)
                + (c["forecast"].get("trend_tps") or 0.0), 3),
        }

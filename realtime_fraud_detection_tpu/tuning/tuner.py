"""Gradient-free online config tuning: hill-climb the host-pipeline knobs.

The pipeline's latency knobs (max-wait deadline bound, bucket set,
overlap/in-flight depth) were hand-set flags frozen at deploy time; this
tuner adjusts them online, tf.data-autotune style (arXiv:2101.12127): one
knob at a time, trial an adjacent value for one epoch, keep it only when
the measured admitted p99 improves past a hysteresis margin at
equal-or-better throughput, revert otherwise. Deterministic by
construction — the dimension rotation and step directions are fixed
round-robin state, never random draws, so a virtual-clock replay makes
identical moves.

Safety rails (the acceptance contract):

- the deadline search space is CLAMPED to ``[deadline_min_ms,
  deadline_max_ms]``, and ``TuningSettings.validate`` refuses a
  deadline_max_ms past the QoS budget's assembly slice — no tuner move
  can ever hold a batch beyond the deadline the QoS plane promised;
- while the QoS degradation ladder sits above rung 0 (or the SLO burn
  gate is engaged) the tuner FREEZES: an in-flight trial reverts
  immediately and no new trial starts — the ladder is shedding work to
  recover, and a knob experiment underneath it would fight the control
  loop that owns the emergency.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from realtime_fraud_detection_tpu.tuning.controller import JitBatchController

__all__ = ["ConfigTuner"]

_DIMS = ("max_wait", "bucket_set", "inflight")


class ConfigTuner:
    """One-knob-at-a-time trial/revert hill climber with hysteresis."""

    MAX_WAIT_STEP = 1.4          # multiplicative deadline step
    EPOCH_LATENCY_CAP = 8192     # per-epoch latency sample bound

    def __init__(self, settings: Any, controller: JitBatchController):
        self.settings = settings
        self.controller = controller
        s = settings
        self.bucket_sets: List[tuple] = [tuple(bs) for bs in s.bucket_sets]
        self.bucket_set_idx = 0
        self.inflight_depth = max(2, s.inflight_min)
        self._clamp_and_apply()
        # epoch accumulators: exact served count (the throughput term)
        # plus a bounded, deterministically stride-decimated latency
        # sample covering the WHOLE epoch (the p99 term) — truncating to
        # the epoch's earliest traffic would bias both sides of the
        # accept/revert comparison under a ramping load
        self._batches = 0
        self._latencies: List[float] = []
        self._lat_count = 0
        self._lat_stride = 1
        self._lat_seen = 0
        self._epoch_start: Optional[float] = None
        # worst emergency signal seen ANYWHERE in the epoch (latched per
        # batch): a mid-epoch ladder excursion must freeze the epoch even
        # if the ladder recovered by the closing batch
        self._epoch_burn = 0.0
        self._epoch_ladder = 0
        # trial state machine
        self._baseline: Optional[Dict[str, float]] = None  # p99/tput
        self._trial: Optional[Dict[str, Any]] = None       # dim + saved value
        self._dim_i = 0
        self._dir: Dict[str, int] = {d: 1 for d in _DIMS}
        self._cooldown = 0
        self.frozen = False
        self.counters: Dict[str, int] = {
            "epochs": 0, "trials": 0, "accepted": 0, "reverted": 0,
            "frozen_epochs": 0,
        }

    # ---------------------------------------------------------- knob state
    def _clamp_and_apply(self) -> None:
        s = self.settings
        c = self.controller
        c.max_wait_ms = min(max(c.max_wait_ms, s.deadline_min_ms),
                            s.deadline_max_ms)
        c.buckets = self.bucket_sets[self.bucket_set_idx]
        self.inflight_depth = min(max(self.inflight_depth, s.inflight_min),
                                  s.inflight_max)

    def _get(self, dim: str):
        if dim == "max_wait":
            return self.controller.max_wait_ms
        if dim == "bucket_set":
            return self.bucket_set_idx
        return self.inflight_depth

    def _set(self, dim: str, value) -> None:
        if dim == "max_wait":
            self.controller.max_wait_ms = float(value)
        elif dim == "bucket_set":
            self.bucket_set_idx = int(value)
        else:
            self.inflight_depth = int(value)
        self._clamp_and_apply()

    def _propose(self, dim: str):
        """The adjacent value in the current direction; None when the
        dimension is pinned at its boundary in that direction."""
        s = self.settings
        d = self._dir[dim]
        if dim == "max_wait":
            cur = self.controller.max_wait_ms
            new = cur * (self.MAX_WAIT_STEP if d > 0
                         else 1.0 / self.MAX_WAIT_STEP)
            new = min(max(new, s.deadline_min_ms), s.deadline_max_ms)
            return None if abs(new - cur) < 1e-9 else new
        if dim == "bucket_set":
            if len(self.bucket_sets) < 2:
                return None
            return (self.bucket_set_idx + d) % len(self.bucket_sets)
        new = self.inflight_depth + d
        if not s.inflight_min <= new <= s.inflight_max:
            return None
        return new

    # ------------------------------------------------------- observations
    def observe_result(self, latency_ms: float, n: int = 1) -> None:
        """Admitted-transaction completion latencies (the objective).

        Every observation counts toward throughput; the latency SAMPLE
        keeps every ``_lat_stride``-th value and, at the cap, halves
        itself and doubles the stride — a deterministic uniform-ish
        sample over the whole epoch, never just its start."""
        self._lat_count += max(1, int(n))
        self._lat_seen += 1
        if self._lat_seen % self._lat_stride:
            return
        self._latencies.append(float(latency_ms))
        if len(self._latencies) >= self.EPOCH_LATENCY_CAP:
            self._latencies = self._latencies[::2]
            self._lat_stride *= 2

    def on_batch(self, now: float, burn_rate: float = 0.0,
                 ladder_level: int = 0) -> None:
        """One completed batch; closes an epoch every
        ``tune_interval_batches`` and runs the trial state machine. The
        emergency signals are latched per batch — and an in-flight trial
        reverts IMMEDIATELY when one fires, not at epoch close: a knob
        experiment must never keep running under a degraded ladder."""
        if self._epoch_start is None:
            self._epoch_start = now
        self._epoch_burn = max(self._epoch_burn, burn_rate)
        self._epoch_ladder = max(self._epoch_ladder, int(ladder_level))
        if (ladder_level > 0 or burn_rate > 1.0) \
                and self._trial is not None:
            self._set(self._trial["dim"], self._trial["saved"])
            self.counters["reverted"] += 1
            self._trial = None
            self.frozen = True
        self._batches += 1
        if self._batches < self.settings.tune_interval_batches:
            return
        self._close_epoch(now, self._epoch_burn, self._epoch_ladder)

    # ------------------------------------------------------ epoch machine
    def _objective(self, now: float) -> Optional[Dict[str, float]]:
        if not self._latencies:
            return None
        from realtime_fraud_detection_tpu.obs.profiling import (
            interpolated_percentile,
        )

        lat = sorted(self._latencies)
        dur = max(1e-9, now - (self._epoch_start or now))
        return {"p99_ms": interpolated_percentile(lat, 0.99),
                "tput": self._lat_count / dur}

    def _reset_epoch(self, now: float) -> None:
        self._batches = 0
        self._latencies = []
        self._lat_count = 0
        self._lat_stride = 1
        self._lat_seen = 0
        self._epoch_start = now
        self._epoch_burn = 0.0
        self._epoch_ladder = 0

    def _close_epoch(self, now: float, burn_rate: float,
                     ladder_level: int) -> None:
        self.counters["epochs"] += 1
        obj = self._objective(now)
        frozen = ladder_level > 0 or burn_rate > 1.0
        if frozen:
            # the QoS ladder (or SLO burn) owns the emergency: revert any
            # trial to its saved value and stand down
            self.counters["frozen_epochs"] += 1
            if self._trial is not None:
                self._set(self._trial["dim"], self._trial["saved"])
                self.counters["reverted"] += 1
                self._trial = None
            self.frozen = True
            self._baseline = None       # post-emergency load is new load
            self._reset_epoch(now)
            return
        self.frozen = False
        if obj is None:
            self._reset_epoch(now)
            return
        h = self.settings.hysteresis_frac
        if self._trial is not None:
            base = self._trial["baseline"]
            better = (obj["p99_ms"] < base["p99_ms"] * (1.0 - h)
                      and obj["tput"] >= base["tput"] * (1.0 - h))
            if better:
                self.counters["accepted"] += 1
                self._baseline = obj    # the trial config is the new base
            else:
                dim = self._trial["dim"]
                self._set(dim, self._trial["saved"])
                self._dir[dim] = -self._dir[dim]   # try the other way next
                self.counters["reverted"] += 1
                self._baseline = None   # re-measure under the restored knob
            self._trial = None
            self._cooldown = self.settings.tuner_cooldown_epochs
            self._reset_epoch(now)
            return
        if self._baseline is None:
            self._baseline = obj        # fresh baseline epoch
            self._reset_epoch(now)
            return
        # rolling baseline: the most recent non-trial epoch represents
        # current load better than a stale measurement ever could
        self._baseline = obj
        if self._cooldown > 0:
            self._cooldown -= 1
            self._reset_epoch(now)
            return
        # propose the next move, round-robin over dimensions
        for _ in range(len(_DIMS)):
            dim = _DIMS[self._dim_i]
            self._dim_i = (self._dim_i + 1) % len(_DIMS)
            new = self._propose(dim)
            if new is None:
                self._dir[dim] = -self._dir[dim]
                continue
            self._trial = {"dim": dim, "saved": self._get(dim),
                           "baseline": self._baseline}
            self._set(dim, new)
            self.counters["trials"] += 1
            break
        self._reset_epoch(now)

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, Any]:
        return {
            "max_wait_ms": round(self.controller.max_wait_ms, 4),
            "bucket_set_idx": self.bucket_set_idx,
            "bucket_set": list(self.bucket_sets[self.bucket_set_idx]),
            "inflight_depth": self.inflight_depth,
            "frozen": self.frozen,
            "in_trial": self._trial is not None,
            "trial_dim": (self._trial or {}).get("dim"),
            "counters": dict(self.counters),
        }

"""Just-in-time batch closing: wait for one more txn only when it pays.

A fixed assembly deadline is wrong at both ends of the load curve: at
trough a lone transaction idles out the whole window on top of its service
time, and at peak the deadline truncates batches below the bucket sizes
the padded transfer actually prices (core/batching.BATCH_BUCKETS — a
101-row batch pays the 128-row program). The JIT closer replaces the fixed
deadline with a marginal decision per poll iteration (arXiv:1904.07421):

    is waiting for ONE more transaction expected to lower admitted p99?

evaluated from three live inputs —

- the arrival forecast (tuning/forecast.py): when is the next txn due;
- the bucket pad-waste curve: a txn landing on a pad row is service-FREE
  (the padded program runs regardless), a txn that bumps the batch into
  the next bucket re-prices service for every waiter;
- the measured service-time curve T(bucket): per-bucket EWMAs fed from
  completed batches (or the tracing plane's stage costs when attached).

Decision rule (deterministic — no randomness, no wall-clock reads of its
own, so a virtual-clock replay reproduces every decision bit-for-bit):

- sustainability first: while the batch's per-transaction service cost
  ``T(bucket(n)) / n`` exceeds ``RHO_TARGET × expected_gap``, closing
  would run the device past the utilization target and grow the queue —
  keep filling as long as the next arrival is forecast inside the
  headroom (at trough the expected gap is huge, so a lone transaction is
  "sustainable" immediately and closes with zero added wait);
- once sustainable, the marginal test: waiting for one more txn costs
  every current waiter the expected gap (plus any bucket-step service
  re-price) and buys the newcomer the batch's amortized fixed cost —
  wait only while ``n × gap + ΔT < patience_factor × T(first_bucket)``;
- never past the tuned max-wait bound, and never past the QoS budget's
  close-by instant (the budget check runs FIRST in both microbatchers —
  the controller only ever closes earlier than the budget would).

The tuner (tuning/tuner.py) owns ``max_wait_ms`` and ``buckets``; this
object just reads them on every decision.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from realtime_fraud_detection_tpu.core.batching import (
    BATCH_BUCKETS,
    bucket_for,
)
from realtime_fraud_detection_tpu.tuning.forecast import ArrivalForecaster

__all__ = ["CloseDecision", "JitBatchController"]


@dataclasses.dataclass(frozen=True)
class CloseDecision:
    close: bool
    reason: str          # jit | deadline | wait
    recheck_s: float     # advisory re-decision delay while waiting


class _ServiceModel:
    """Per-bucket service-time EWMAs with a linear (fixed + per-row) prior.

    ``observe(bucket, service_s)`` feeds completed batches; ``ms(bucket)``
    answers for any bucket — seen buckets from their EWMA, unseen ones
    from a line through the two most extreme seen buckets (or the prior
    until anything is seen)."""

    def __init__(self, prior_fixed_ms: float = 0.5,
                 prior_row_us: float = 5.0, alpha: float = 0.3):
        self.prior_fixed_ms = float(prior_fixed_ms)
        self.prior_row_us = float(prior_row_us)
        self.alpha = float(alpha)
        self._ewma: Dict[int, float] = {}     # bucket -> service ms

    def observe(self, bucket: int, service_s: float) -> None:
        if bucket < 1 or service_s < 0:
            return
        ms = service_s * 1e3
        prev = self._ewma.get(bucket)
        self._ewma[bucket] = (ms if prev is None
                              else self.alpha * ms
                              + (1.0 - self.alpha) * prev)

    def ms(self, bucket: int) -> float:
        hit = self._ewma.get(bucket)
        if hit is not None:
            return hit
        if len(self._ewma) >= 2:
            b_lo, b_hi = min(self._ewma), max(self._ewma)
            t_lo, t_hi = self._ewma[b_lo], self._ewma[b_hi]
            if b_hi > b_lo:
                slope = (t_hi - t_lo) / (b_hi - b_lo)
                return max(0.0, t_lo + slope * (bucket - b_lo))
        if len(self._ewma) == 1:
            (b0, t0), = self._ewma.items()
            # one point: keep its fixed cost, scale the row part by the
            # prior's per-row slope
            return max(0.0, t0 + (bucket - b0) * self.prior_row_us / 1e3)
        return self.prior_fixed_ms + bucket * self.prior_row_us / 1e3

    def snapshot(self) -> Dict[str, float]:
        return {str(b): round(v, 4) for b, v in sorted(self._ewma.items())}


class JitBatchController:
    """The decision object both microbatchers consult per poll iteration."""

    # device-utilization target the sustainability phase fills toward:
    # closing a batch whose per-txn service cost exceeds this fraction of
    # the inter-arrival gap runs the device too close to saturation and
    # the queue (not the assembly wait) becomes the tail; the 0.15 slack
    # is what drains a transient hole while a burst is still on
    RHO_TARGET = 0.85

    def __init__(self, forecaster: Optional[ArrivalForecaster] = None,
                 buckets: Tuple[int, ...] = BATCH_BUCKETS,
                 max_wait_ms: float = 10.0,
                 patience_factor: float = 1.0,
                 prior_fixed_ms: float = 0.5,
                 prior_row_us: float = 5.0):
        self.forecaster = forecaster or ArrivalForecaster()
        self.buckets: Tuple[int, ...] = tuple(buckets)
        self.max_wait_ms = float(max_wait_ms)
        self.patience_factor = float(patience_factor)
        self.service = _ServiceModel(prior_fixed_ms, prior_row_us)
        self.decisions: Dict[str, int] = {"jit": 0, "deadline": 0,
                                          "wait": 0}

    # ------------------------------------------------------------- inputs
    def observe(self, now: float, n: int = 1) -> None:
        """Admissions into the forecaster (the batchers call this on every
        poll/submit, with THEIR clock — one time base per instance)."""
        self.forecaster.observe(now, n)

    def observe_batch(self, n_rows: int, service_s: float) -> None:
        """A completed batch's dispatch→complete duration, keyed by the
        bucket it padded onto — the live T(bucket) curve."""
        self.service.observe(self.bucket_for(n_rows), service_s)

    # ------------------------------------------------------------ buckets
    def bucket_for(self, n: int) -> int:
        """The padded shape ``n`` rows land on — core/batching's rule
        over THIS controller's (tuner-selected) close-boundary set."""
        return bucket_for(n, self.buckets)

    def _next_bucket(self, b: int) -> Optional[int]:
        for cand in self.buckets:
            if cand > b:
                return cand
        return None

    # ----------------------------------------------------------- decision
    def should_close(self, n: int, first_ts: float, now: float,
                     close_by: Optional[float] = None) -> CloseDecision:
        """The JIT decision for a batch of ``n`` waiters whose first
        record arrived at ``first_ts``. ``close_by`` is the QoS budget's
        latest hand-off instant for the oldest waiter (already enforced
        upstream; passed so the headroom math can't plan past it)."""
        waited_ms = max(0.0, (now - first_ts) * 1e3)
        headroom_ms = self.max_wait_ms - waited_ms
        if close_by is not None:
            headroom_ms = min(headroom_ms, (close_by - now) * 1e3)
        if headroom_ms <= 0.0:
            self.decisions["deadline"] += 1
            return CloseDecision(True, "deadline", 0.0)
        gap_ms = self.forecaster.expected_gap_s(now) * 1e3
        bucket = self.bucket_for(n)
        t_bucket = self.service.ms(bucket)
        # phase 1 — sustainability: closing an undersized batch runs the
        # device past the utilization target (queue growth costs the tail
        # far more than assembly wait does); keep filling while the next
        # arrival is forecast inside the headroom. At trough gap_ms is
        # huge, so n=1 is sustainable immediately — zero idle wait.
        if t_bucket / max(n, 1) > self.RHO_TARGET * gap_ms:
            if gap_ms <= headroom_ms:
                self.decisions["wait"] += 1
                return CloseDecision(
                    False, "wait", self._recheck_s(gap_ms, headroom_ms))
            self.decisions["jit"] += 1
            return CloseDecision(True, "jit", 0.0)
        # phase 2 — marginal free-rider test: one more txn costs every
        # current waiter the gap (plus the bucket-step re-price when n
        # sits on a boundary) and buys the newcomer a skipped service
        # cycle of the batch being built (under load, a txn left out of
        # this batch waits a full T(bucket) for the next one). Valuing
        # the gain at the TARGET bucket makes the closer ride pad rows to
        # the boundary when arrivals are due, and snap shut at the
        # boundary when the next bucket's re-price outweighs it — the
        # pad-waste curve driving the decision directly.
        target = self.bucket_for(n + 1)
        delta_ms = max(0.0, self.service.ms(target) - t_bucket)
        gain_ms = self.patience_factor * self.service.ms(target)
        if n * gap_ms + delta_ms < gain_ms and gap_ms <= headroom_ms:
            self.decisions["wait"] += 1
            return CloseDecision(
                False, "wait", self._recheck_s(gap_ms, headroom_ms))
        self.decisions["jit"] += 1
        return CloseDecision(True, "jit", 0.0)

    @staticmethod
    def _recheck_s(gap_ms: float, headroom_ms: float) -> float:
        """Advisory wait before re-deciding (the asyncio batcher's
        timeout; a new arrival re-decides immediately regardless)."""
        bound_ms = min(max(gap_ms, 0.05), headroom_ms)
        return max(0.0001, min(bound_ms / 1e3, 0.005))

    def snapshot(self) -> Dict[str, Any]:
        return {
            "max_wait_ms": round(self.max_wait_ms, 4),
            "buckets": list(self.buckets),
            "patience_factor": self.patience_factor,
            "decisions": dict(self.decisions),
            "forecast": self.forecaster.snapshot(),
            "service_ms": self.service.snapshot(),
        }

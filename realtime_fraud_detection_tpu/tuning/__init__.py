"""Self-tuning host pipeline: arrival-aware just-in-time batching.

- ``forecast.ArrivalForecaster`` — short-horizon Holt (level+trend)
  arrival-rate estimate over admission timestamps, virtual-clock exact;
- ``controller.JitBatchController`` — the just-in-time batch closer both
  microbatchers consult instead of a fixed deadline (arXiv:1904.07421);
- ``tuner.ConfigTuner`` — gradient-free online hill climbing over the
  max-wait bound, bucket set, and in-flight depth, with hysteresis and
  hard QoS-budget floors (arXiv:2101.12127, tf.data autotuning);
- ``plane.TuningPlane`` — the bundle the stream job / serving app hold;
- ``drill`` — the deterministic virtual-clock acceptance drill
  (``rtfd autotune-drill``).
"""

from realtime_fraud_detection_tpu.tuning.controller import (
    CloseDecision,
    JitBatchController,
)
from realtime_fraud_detection_tpu.tuning.forecast import ArrivalForecaster
from realtime_fraud_detection_tpu.tuning.plane import TuningPlane
from realtime_fraud_detection_tpu.tuning.tuner import ConfigTuner

__all__ = [
    "ArrivalForecaster",
    "CloseDecision",
    "ConfigTuner",
    "JitBatchController",
    "TuningPlane",
]

"""Short-horizon arrival-rate forecasting: Holt smoothing over admissions.

The just-in-time batch closer (tuning/controller.py) needs one number the
fixed-deadline assembler never had: "when is the NEXT transaction expected?"
This module estimates the instantaneous offered rate from the admission
timestamps the microbatchers already see, with Holt double-exponential
smoothing (level + trend) over fixed time buckets — the short-horizon
forecast the just-in-time dynamic-batching paper (arXiv:1904.07421) closes
batches against, and the same windowed-counting discipline as
``obs.tracing.SloTracker`` (exact on a virtual clock, O(1) memory).

Clock discipline: every ``observe``/``rate`` call carries an explicit
``now`` from ONE clock base (the assembler's monotonic clock in
production, the virtual clock in drills). Counts land in ``bucket_s``-wide
buckets; a bucket folds into the Holt state only once it is COMPLETE
(``now`` has moved past it), so the estimate never oscillates with partial
buckets and a replayed timeline folds identically — decisions are
reproducible bit-for-bit.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["ArrivalForecaster"]


class ArrivalForecaster:
    """Holt (level+trend) arrival-rate estimator over time buckets."""

    # a silent gap longer than this many buckets re-anchors the state
    # instead of folding thousands of zero buckets one by one (bounds the
    # fold work after an idle period; the result — rate ~0 — is identical)
    MAX_GAP_BUCKETS = 64

    # fast EWMA over observed inter-arrival gaps: the close decision's
    # primary gap estimate. Rate-over-buckets (Holt) answers "what is the
    # trend"; the gap EWMA answers "when is the NEXT txn due" and reacts
    # to a regime change within a handful of arrivals instead of a full
    # counting bucket — the difference between catching a burst's first
    # millisecond and its twentieth
    GAP_ALPHA = 0.25

    def __init__(self, bucket_s: float = 0.02, alpha: float = 0.5,
                 beta: float = 0.2):
        if bucket_s <= 0 or not 0.0 < alpha <= 1.0 or not 0.0 <= beta <= 1.0:
            raise ValueError(
                f"forecaster requires bucket_s > 0, 0 < alpha <= 1, "
                f"0 <= beta <= 1; got bucket_s={bucket_s} alpha={alpha} "
                f"beta={beta}")
        self.bucket_s = float(bucket_s)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.gap_ewma: Optional[float] = None
        self._cur_idx: Optional[int] = None   # bucket currently filling
        self._cur_count = 0
        self.level: Optional[float] = None    # smoothed rate (txn/s)
        self.trend = 0.0                      # txn/s per bucket
        self.last_arrival: Optional[float] = None
        self.observed_total = 0
        self.folds = 0

    # ------------------------------------------------------------- folding
    def _fold_value(self, x: float) -> None:
        """One complete bucket's rate into the Holt recursion."""
        if self.level is None:
            self.level = x
            self.trend = 0.0
        else:
            prev = self.level
            self.level = (self.alpha * x
                          + (1.0 - self.alpha) * (self.level + self.trend))
            self.trend = (self.beta * (self.level - prev)
                          + (1.0 - self.beta) * self.trend)
        self.folds += 1

    def _advance_to(self, idx: int) -> None:
        """Fold every bucket strictly older than ``idx`` (zero-filled
        gaps included, clamped to MAX_GAP_BUCKETS so an idle hour costs
        O(64), not O(hour))."""
        if self._cur_idx is None:
            self._cur_idx = idx
            return
        if idx <= self._cur_idx:
            return
        gap = idx - self._cur_idx
        self._fold_value(self._cur_count / self.bucket_s)
        if gap - 1 > self.MAX_GAP_BUCKETS:
            # long silence: the rate IS ~0 — re-anchor instead of looping
            self.level = 0.0
            self.trend = 0.0
        else:
            for _ in range(gap - 1):
                self._fold_value(0.0)
        self._cur_idx = idx
        self._cur_count = 0

    # ------------------------------------------------------------- observe
    def observe(self, now: float, n: int = 1) -> None:
        """Record ``n`` admissions at time ``now`` (the caller's clock)."""
        if n <= 0:
            return
        self._advance_to(int(now // self.bucket_s))
        self._cur_count += int(n)
        self.observed_total += int(n)
        if self.last_arrival is not None and now >= self.last_arrival:
            # n records since the last observation: each effectively
            # arrived (now - last)/n apart; fold all n EWMA steps at once
            per = (now - self.last_arrival) / n
            if self.gap_ewma is None:
                self.gap_ewma = per
            else:
                w = 1.0 - (1.0 - self.GAP_ALPHA) ** n
                self.gap_ewma = (1.0 - w) * self.gap_ewma + w * per
        if self.last_arrival is None or now > self.last_arrival:
            self.last_arrival = now

    # -------------------------------------------------------------- query
    def rate(self, now: float) -> float:
        """Forecast offered rate (txn/s) for the immediate horizon.

        Folds any buckets ``now`` has completed first, then blends the
        Holt one-step-ahead forecast with the current (partial) bucket's
        observed rate — so a burst is visible within one bucket width,
        not one full bucket behind.
        """
        self._advance_to(int(now // self.bucket_s))
        holt = max(0.0, (self.level or 0.0) + self.trend)
        if self._cur_idx is None:
            return holt
        elapsed = now - self._cur_idx * self.bucket_s
        if elapsed <= 0:
            return holt
        partial = self._cur_count / max(elapsed, self.bucket_s * 0.25)
        # the partial bucket dominates once it has real evidence
        w = min(1.0, elapsed / self.bucket_s)
        return max(0.0, (1.0 - w * self.alpha) * holt
                   + w * self.alpha * partial)

    def expected_gap_s(self, now: float) -> float:
        """Expected inter-arrival time; inf when the forecast rate is ~0.

        The primary estimate is the fast gap EWMA (reacts within a few
        arrivals); the Holt rate is the fallback before any gap has been
        observed. Both are floored by the OBSERVED silence: when
        ``now - last_arrival`` already exceeds the predicted gap, the
        prediction is wrong by direct evidence (a burst just ended, or a
        ramp is falling faster than the smoothing tracks) — believing the
        stale estimate would hold batches open for arrivals that never
        come.
        """
        if self.gap_ewma is not None:
            gap = self.gap_ewma
        else:
            r = self.rate(now)
            gap = 1.0 / r if r > 1e-9 else float("inf")
        if self.last_arrival is not None:
            gap = max(gap, now - self.last_arrival)
        return gap

    def snapshot(self) -> Dict[str, Any]:
        return {
            "level_tps": round(self.level or 0.0, 3),
            "trend_tps": round(self.trend, 3),
            "observed_total": self.observed_total,
            "folds": self.folds,
            "bucket_s": self.bucket_s,
        }

"""Deterministic autotune drill: JIT batching vs every static config.

Drives the REAL stream path — MicrobatchAssembler → StreamJob
dispatch/complete → QoS budget → fan-out — under a nonstationary offered
load (sim/arrivals.py: diurnal ramp + Poisson bursts) on a virtual clock,
with the one substitution every drill here makes: the device is a
deterministic stand-in whose per-batch cost is the BUCKET-PADDED service
curve ``T(bucket(n)) = fixed + per_row * bucket`` of virtual time — the
pad-waste economics the JIT controller reasons about, with exact
arithmetic instead of wall-clock noise.

The same arrival timeline is replayed through a pinned grid of static
fixed-deadline configs AND through the self-tuning plane (forecaster +
just-in-time closer + online tuner). The acceptance bar (ISSUE 6):

- the controller beats EVERY static config on admitted p99 at
  equal-or-better admitted throughput;
- it never sheds high-value traffic a static config would have admitted
  (high-value sheds are zero across the board — checked, not assumed);
- its tuned max-wait bound never leaves the validated range (the QoS
  budget floor), and admitted p99 stays inside the budget;
- decisions are fully reproducible: a second controller run produces a
  bit-identical verdict (p99, close-reason histogram, scored count).

Used by ``rtfd autotune-drill [--fast]`` (final stdout line: a compact
<2 KB JSON verdict, the bench.py convention) and smoke-tested in tier-1.
"""

from __future__ import annotations

import dataclasses
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.core.batching import (
    BATCH_BUCKETS,
    bucket_for,
)
from realtime_fraud_detection_tpu.sim.arrivals import (
    DiurnalBurstConfig,
    DiurnalBurstProcess,
)
from realtime_fraud_detection_tpu.utils.config import (
    QosSettings,
    TuningSettings,
)

__all__ = ["AutotuneDrillConfig", "run_autotune_drill",
           "compact_autotune_summary"]


@dataclasses.dataclass
class AutotuneDrillConfig:
    seed: int = 7
    max_batch: int = 256
    # offered load: one compressed diurnal cycle per period_s, bursts on a
    # deterministic schedule (sim/arrivals.py)
    duration_s: float = 6.0
    trough_tps: float = 150.0
    peak_tps: float = 8_000.0
    period_s: float = 3.0
    burst_every_s: float = 1.5
    burst_offset_s: float = 1.2
    burst_duration_s: float = 0.15
    burst_mult: float = 4.0
    # bucket-padded service model (virtual ms): T(bucket) = fixed + row*B
    fixed_ms: float = 2.0
    per_row_us: float = 6.0
    # pinned static comparison grid: fixed max_delay_ms configs
    static_grid: Tuple[float, ...] = (0.5, 1.0, 2.5, 5.0, 10.0)
    # QoS plane (shared by every run — the budget trigger is fair)
    budget_ms: float = 20.0
    assemble_margin_ms: float = 2.0
    # tuning plane
    deadline_min_ms: float = 0.25
    deadline_max_ms: float = 8.0
    patience_factor: float = 1.0
    tune_interval_batches: int = 40
    # drive-loop evaluation step while a batch is open (virtual s)
    step_s: float = 0.0005

    @staticmethod
    def fast() -> "AutotuneDrillConfig":
        return AutotuneDrillConfig(duration_s=3.0,
                                   static_grid=(0.5, 2.5, 10.0),
                                   tune_interval_batches=25)


class _NoCache:
    def get_transaction(self, txn_id, now=None):
        return None


class _DrillPending:
    __slots__ = ("records", "n", "features", "cost_s")

    def __init__(self, records, cost_s):
        self.records = list(records)
        self.n = len(self.records)
        self.features = None
        self.cost_s = cost_s


class AutotuneDrillScorer:
    """Deterministic stand-in with the bucket-padded service curve."""

    def __init__(self, cfg: AutotuneDrillConfig):
        self.cfg = cfg
        self.model_valid = np.ones(5, bool)
        self.txn_cache = _NoCache()
        self.qos_level = 0
        self.last_cost_s = 0.0

    def set_degradation(self, mask, rules_only: bool = False,
                        level: int = 0) -> None:
        self.qos_level = int(level)

    def cost_s(self, n: int) -> float:
        # bucket-padded, with the REAL compile-cached shapes: a batch
        # pays the program of the bucket it lands on (core/batching)
        b = bucket_for(n, BATCH_BUCKETS)
        return (self.cfg.fixed_ms + b * self.cfg.per_row_us / 1e3) / 1e3

    def dispatch(self, records, now=None, trace=None) -> _DrillPending:
        if trace is not None:
            for s in ("assemble", "pack", "dispatch", "device_wait"):
                trace.mark(s)
        self.last_cost_s = self.cost_s(len(records))
        return _DrillPending(records, self.last_cost_s)

    def finalize(self, pending: _DrillPending, now=None,
                 lock=None) -> List[Dict[str, Any]]:
        out = []
        for r in pending.records:
            tid = str(r.get("transaction_id", ""))
            score = (zlib.crc32(tid.encode()) % 650) / 1000.0
            out.append({
                "transaction_id": tid,
                "fraud_probability": score,
                "fraud_score": score,
                "risk_level": "LOW" if score < 0.3 else "MEDIUM",
                "decision": "APPROVE" if score < 0.6
                            else "APPROVE_WITH_MONITORING",
                "model_predictions": {},
                "confidence": 0.9,
                "processing_time_ms": pending.cost_s * 1e3
                                      / max(pending.n, 1),
                "explanation": {"drill": True},
            })
        return out


def _arrivals(cfg: AutotuneDrillConfig) -> List[Tuple[float, Dict[str, Any]]]:
    """The shared offered-load timeline: diurnal ramp + bursts, with a
    deterministic high/normal/low priority mix by amount."""
    proc = DiurnalBurstProcess(DiurnalBurstConfig(
        trough_tps=cfg.trough_tps, peak_tps=cfg.peak_tps,
        period_s=cfg.period_s, burst_every_s=cfg.burst_every_s,
        burst_offset_s=cfg.burst_offset_s,
        burst_duration_s=cfg.burst_duration_s,
        burst_mult=cfg.burst_mult), seed=cfg.seed)
    times = proc.generate(cfg.duration_s)
    out = []
    for i, ts in enumerate(times.tolist()):
        amount = (1000.0, 60.0, 5.0)[0 if i % 10 < 2
                                     else (1 if i % 10 < 7 else 2)]
        out.append((ts, {
            "transaction_id": f"at-{i}",
            "user_id": f"u{i % 97}",
            "merchant_id": f"m{i % 31}",
            "amount": amount,
            "timestamp": str(ts),
        }))
    return out


def _run_config(cfg: AutotuneDrillConfig,
                arrivals: List[Tuple[float, Dict[str, Any]]],
                max_delay_ms: Optional[float] = None,
                tuning: Optional[Any] = None,
                admission_rate: float = 0.0) -> Dict[str, Any]:
    """One full replay of the arrival timeline through the real stream
    path: either a static fixed-deadline config (``max_delay_ms``) or the
    self-tuning plane (``tuning``). Returns the run's admitted-latency
    stats, scored/shed counts, and the close-reason histogram."""
    from realtime_fraud_detection_tpu.obs.tracing import Tracer
    from realtime_fraud_detection_tpu.qos import QosPlane
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
    from realtime_fraud_detection_tpu.stream.microbatch import (
        MicrobatchAssembler,
    )
    from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker
    from realtime_fraud_detection_tpu.utils.config import TracingSettings

    clock = [0.0]
    vclock = lambda: clock[0]                                  # noqa: E731
    scorer = AutotuneDrillScorer(cfg)
    plane = QosPlane(QosSettings(
        enabled=True, budget_ms=cfg.budget_ms,
        assemble_margin_ms=cfg.assemble_margin_ms,
        admission_rate=admission_rate,
        admission_burst=(admission_rate * 0.05 if admission_rate else 0.0),
        ladder_high_backlog=1e9, ladder_low_backlog=1e8))
    tracer = None
    if tuning is not None:
        # the tuner reads the SLO burn through the job's tracer wiring
        tracer = Tracer(TracingSettings(
            enabled=True, ring_size=4096,
            slo_objective_ms=cfg.budget_ms,
            slo_fast_window_s=0.5, slo_slow_window_s=2.0,
            slo_bucket_s=0.05), clock=vclock)
    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=cfg.max_batch,
        max_delay_ms=(max_delay_ms if max_delay_ms is not None else 5.0),
        emit_features=False, emit_enriched=False,
        qos=plane, tracing=tracer, autotune=tuning))
    job.assembler = MicrobatchAssembler(
        job.consumer, max_batch=cfg.max_batch,
        max_delay_ms=(max_delay_ms if max_delay_ms is not None else 5.0),
        clock=vclock, budget=plane.budget, budget_clock=vclock,
        controller=job.tuning)

    latencies: List[float] = []
    max_wait_ms = 0.0
    next_i = 0
    step = cfg.step_s
    while True:
        while next_i < len(arrivals) and arrivals[next_i][0] <= clock[0]:
            ts, txn = arrivals[next_i]
            broker.produce(T.TRANSACTIONS, txn, key=txn["user_id"],
                           timestamp=ts)
            next_i += 1
        batch = job.assembler.next_batch(block=False)
        if not batch and next_i >= len(arrivals) \
                and job.consumer.lag() == 0:
            batch = job.assembler.flush()
        if batch:
            for r in batch:
                max_wait_ms = max(
                    max_wait_ms, (clock[0] - float(r.timestamp)) * 1e3)
            ctx = job.dispatch_batch(batch, now=clock[0])
            clock[0] += (scorer.last_cost_s
                         if ctx is not None and ctx.pending is not None
                         else step)
            if ctx is not None:
                job.complete_batch(ctx, now=clock[0])
                for r in ctx.fresh:
                    latencies.append(
                        (clock[0] - float(r.timestamp)) * 1e3)
            continue
        if next_i >= len(arrivals) and job.consumer.lag() == 0 \
                and not job.assembler._pending:
            break
        if job.assembler._pending:
            # a batch is open: advance in fine steps so deadline/budget/
            # JIT triggers fire at the same granularity for every config
            clock[0] += step
        else:
            clock[0] = (max(clock[0] + step, arrivals[next_i][0])
                        if next_i < len(arrivals) else clock[0] + step)

    lat = np.asarray(sorted(latencies)) if latencies else np.zeros(1)
    shed_high = sum(
        int(count) for key, count in plane.metrics.qos_shed._values.items()
        if dict(key).get("priority") == "high")

    def pct(q: float) -> float:
        from realtime_fraud_detection_tpu.obs.profiling import (
            interpolated_percentile,
        )

        return round(float(interpolated_percentile(lat, q)), 4)

    out = {
        "scored": job.counters["scored"],
        "shed": job.counters["shed"],
        "shed_high": shed_high,
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
        "max_ms": round(float(lat[-1]), 4),
        "mean_batch": round(job.counters["scored"]
                            / max(job.counters["batches"], 1), 2),
        "batches": job.counters["batches"],
        "max_wait_ms": round(max_wait_ms, 4),
        "close_reasons": dict(sorted(
            job.assembler.close_reasons.items())),
        "virtual_duration_s": round(clock[0], 4),
        "throughput_tps": round(
            job.counters["scored"] / max(clock[0], 1e-9), 1),
    }
    if job.tuning is not None:
        out["tuning"] = job.tuning.snapshot()
    return out


def _tuning_plane(cfg: AutotuneDrillConfig):
    from realtime_fraud_detection_tpu.tuning import TuningPlane

    settings = TuningSettings(
        enabled=True,
        deadline_min_ms=cfg.deadline_min_ms,
        deadline_max_ms=cfg.deadline_max_ms,
        patience_factor=cfg.patience_factor,
        tune_interval_batches=cfg.tune_interval_batches,
        # the drill's drive loop is serial (depth 1) — pin the in-flight
        # dimension so tuner trials spend epochs on knobs that act here
        inflight_min=1, inflight_max=1,
        forecast_bucket_s=0.02)
    settings.validate(qos=QosSettings(enabled=True, budget_ms=cfg.budget_ms,
                                      assemble_margin_ms=cfg
                                      .assemble_margin_ms))
    return TuningPlane(settings)


def run_autotune_drill(
        cfg: Optional[AutotuneDrillConfig] = None) -> Dict[str, Any]:
    cfg = cfg or AutotuneDrillConfig()
    arrivals = _arrivals(cfg)
    proc_summary = DiurnalBurstProcess(DiurnalBurstConfig(
        trough_tps=cfg.trough_tps, peak_tps=cfg.peak_tps,
        period_s=cfg.period_s), seed=cfg.seed).summary(
            [t for t, _ in arrivals])

    summary: Dict[str, Any] = {
        "config": dataclasses.asdict(cfg),
        "offered": proc_summary,
    }

    statics: Dict[str, Dict[str, Any]] = {}
    for d in cfg.static_grid:
        statics[f"deadline_{d}ms"] = _run_config(cfg, arrivals,
                                                 max_delay_ms=d)
    summary["static_grid"] = statics

    ctrl = _run_config(cfg, arrivals, tuning=_tuning_plane(cfg))
    summary["controller"] = ctrl
    # reproducibility: a fresh plane over the same timeline must make
    # bit-identical decisions (same p99, same close mix, same count)
    ctrl2 = _run_config(cfg, arrivals, tuning=_tuning_plane(cfg))
    reproducible = (
        ctrl["p99_ms"] == ctrl2["p99_ms"]
        and ctrl["scored"] == ctrl2["scored"]
        and ctrl["close_reasons"] == ctrl2["close_reasons"])
    summary["reproducible"] = reproducible

    # admission-limited guard phase: the high-value-shed check must be
    # FALSIFIABLE, so the same timeline is replayed under a token bucket
    # the bursts overrun — low-priority sheds genuinely occur (asserted),
    # and a controller that made admission shed high-value traffic a
    # static config would have admitted fails here, not silently passes
    guard_rate = cfg.peak_tps * 0.5
    guard: Dict[str, Dict[str, Any]] = {
        "controller": _run_config(cfg, arrivals, tuning=_tuning_plane(cfg),
                                  admission_rate=guard_rate)}
    for d in cfg.static_grid:
        guard[f"deadline_{d}ms"] = _run_config(
            cfg, arrivals, max_delay_ms=d, admission_rate=guard_rate)
    summary["admission_guard"] = {
        "admission_rate": guard_rate,
        "runs": {k: {x: v[x] for x in ("scored", "shed", "shed_high")}
                 for k, v in guard.items()},
    }

    static_p99 = {k: v["p99_ms"] for k, v in statics.items()}
    beats_p99 = all(ctrl["p99_ms"] < p for p in static_p99.values())
    tput_ok = all(ctrl["scored"] >= v["scored"] for v in statics.values())
    # never sheds high-value traffic a static would have admitted: high
    # never sheds on ANY run — main grid AND the admission-limited guard
    # (where sheds demonstrably happen, so the check can actually fail)
    no_high_sheds = (ctrl["shed_high"] == 0
                     and all(v["shed_high"] == 0 for v in statics.values())
                     and all(v["shed_high"] == 0 for v in guard.values()))
    admission_exercised = (guard["controller"]["shed"] > 0
                           and all(v["shed"] > 0 for v in guard.values()))
    tuned_wait = ctrl["tuning"]["controller"]["max_wait_ms"]
    budget_ok = (tuned_wait <= cfg.deadline_max_ms + 1e-9
                 and cfg.deadline_max_ms
                 <= cfg.budget_ms - cfg.assemble_margin_ms
                 and ctrl["p99_ms"] <= cfg.budget_ms)

    checks = {
        "beats_every_static_p99": beats_p99,
        "throughput_equal_or_better": tput_ok,
        "no_high_value_sheds": no_high_sheds,
        "admission_guard_exercised": admission_exercised,
        "qos_budget_respected": budget_ok,
        "reproducible": reproducible,
        "jit_decisions_used": ctrl["close_reasons"].get("jit", 0) > 0,
    }
    summary["checks"] = checks
    summary["passed"] = all(checks.values())
    return summary


def compact_autotune_summary(summary: Dict[str, Any]) -> Dict[str, Any]:
    """The <2 KB final-stdout-line verdict (bench.py convention)."""
    ctrl = summary["controller"]
    return {
        "drill": "autotune",
        "passed": summary["passed"],
        "checks": summary["checks"],
        "controller": {
            "p99_ms": ctrl["p99_ms"],
            "p50_ms": ctrl["p50_ms"],
            "scored": ctrl["scored"],
            "mean_batch": ctrl["mean_batch"],
            "tuned_max_wait_ms":
                ctrl["tuning"]["controller"]["max_wait_ms"],
            "close_reasons": ctrl["close_reasons"],
        },
        "static_p99_ms": {
            k: v["p99_ms"] for k, v in summary["static_grid"].items()},
        "static_scored": {
            k: v["scored"] for k, v in summary["static_grid"].items()},
        "offered": {
            "n": summary["offered"].get("n"),
            "mean_tps": summary["offered"].get("mean_tps"),
        },
        "admission_guard": {
            "shed": summary["admission_guard"]["runs"]["controller"][
                "shed"],
            "shed_high": summary["admission_guard"]["runs"]["controller"][
                "shed_high"],
        },
    }

"""TPU-native real-time fraud scoring framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
system (AjayAlluri/realtime-fraud-detection): Kafka -> Flink -> 5-model ML
ensemble -> Redis/decision engine, rebuilt as a single TPU-first framework.

Layer map (mirrors SURVEY.md section 7):

- ``core``     device mesh / precision policy / batch bucketing / compile cache
- ``features`` the 64-wide feature contract (reference FeatureExtractor.java)
- ``models``   tensorized GBDT, isolation forest, LSTM, DistilBERT, GraphSAGE
- ``ensemble`` ensemble strategies + decision ladder (ensemble_predictor.py)
- ``ops``      Pallas TPU kernels (blockwise attention, tree traversal)
- ``parallel`` sharding layouts, collectives (the ICI "NCCL" equivalent)
- ``stream``   transport (in-memory + Kafka-gated) and microbatch assembler
- ``state``    windowed velocity / profile / history stores (Redis equivalent)
- ``serving``  asyncio scoring service with the reference REST surface
- ``sim``      load generator + fraud pattern library
- ``training`` GBDT / iforest / neural trainers (model_trainer.py equivalent)
- ``testing``  A/B experiment manager (ab_testing.py equivalent)
- ``obs``      metrics / structured logging / profiling

Typical use::

    import realtime_fraud_detection_tpu as rtfd
    cfg = rtfd.Config()
    scorer = rtfd.serving.Scorer(cfg)
"""

__version__ = "0.1.0"

from realtime_fraud_detection_tpu.utils.config import Config  # noqa: F401

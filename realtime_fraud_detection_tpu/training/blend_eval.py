"""Production blend selection: train every branch, admit by measurement.

The reference configures a 5-model ensemble with fixed weights
(config.py:126-199) but never trains 3 of the 5 branches and never measures
the blend at all (its 96.8% accuracy claim has no harness behind it,
README.md:203). This module is the missing protocol, run the way the
framework serves:

1. **Stream-matched data.** Train/validation/test segments are consecutive
   windows of one simulated stream pushed through the PRODUCTION assemble
   path (``FraudScorer.assemble`` — live velocity/history/graph/token state),
   so every branch trains and evaluates on exactly the tensors serving
   builds. Training on offline-encoded features instead costs ~2pp
   accuracy / ~0.04 AUC on-stream (round-4 measurement).
2. **Per-branch training.** Trees (histogram GBDT), isolation forest,
   class-weighted LSTM / text / GNN (fraud is ~5% of the stream; unweighted
   BCE under-fits the positives — the round-4 LSTM's 0.74 AUC was exactly
   this, fixed here to ~0.97). Each neural branch is then Platt-calibrated
   on validation, with (a, b) FOLDED INTO the head parameters
   (training/calibrate.py) — class weighting inflates probabilities, and
   the serving combine averages raw probabilities, so an uncalibrated
   branch drags every blend it joins regardless of its ranking quality.
3. **Serving-parity blending.** Candidate blends run through
   ``ensemble.combine.combine_predictions`` itself (weighted average over
   the validity-masked branch set, renormalized — the same math the fused
   device program executes), so an accepted blend IS a deployable
   ``model_valid`` + ``EnsembleParams.weights`` setting, zero recompiles
   (testing/ab.py serves such variants).
4. **A/B-gated admission.** Starting from the round-4 production pair
   (trees + isolation forest), each remaining branch is admitted only if
   validation blend AUC does not regress — candidate weight chosen on
   validation from {config, config/2, config/4} (re-weighting by validation
   instead of trusting the reference's static weights). The held-out test
   segment is scored ONCE, with a paired bootstrap CI on the AUC delta vs
   the baseline pair.
5. **Operating point.** The alert threshold is chosen on validation to
   maximize recall subject to a precision floor (default 0.94, the round-4
   production precision), then reported on test.

``run_blend_eval`` returns the full evidence dict (per-branch AUCs,
admission decisions, ablations, bootstrap CI, operating points);
``rtfd quality-eval`` writes it as the round's quality artifact.
"""

from __future__ import annotations

import dataclasses
import time
from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Tuple

import numpy as np

if TYPE_CHECKING:  # import-cheap module: jax/models load lazily at run time
    from realtime_fraud_detection_tpu.models.bert import BertConfig

# branch order must match scoring.MODEL_NAMES (the device program's layout)
_BASELINE = ("xgboost_primary", "isolation_forest")


def _default_bert() -> "BertConfig":
    """The artifact's text-branch architecture (small enough to train on
    CPU inside the protocol; the perf benchmarks separately cover the
    full DistilBERT-base dimensions)."""
    from realtime_fraud_detection_tpu.models.bert import BertConfig

    return BertConfig(hidden_size=128, num_layers=2, num_heads=4,
                      intermediate_size=512)


@dataclasses.dataclass
class BlendEvalConfig:
    """Protocol parameters. Defaults reproduce the committed artifact."""

    num_users: int = 2000
    num_merchants: int = 500
    seed: int = 3
    batch_size: int = 256
    train_batches: int = 96
    # validation sizes the admission decisions AND the Platt fits: 24
    # batches ≈ 6k txns / ~350 positives keeps the AUC noise floor near
    # the deltas being judged (12 batches was decided by noise)
    val_batches: int = 24
    test_batches: int = 48
    # branch training
    n_trees: int = 40
    tree_depth: int = 5
    iforest_trees: int = 100
    lstm_epochs: int = 6
    lstm_hidden: int = 128
    text_epochs: int = 2
    gnn_epochs: int = 3
    text_len: int = 32
    tokenizer: str = "wordpiece"
    bert: "BertConfig" = dataclasses.field(default_factory=_default_bert)
    # admission + operating point
    weight_scales: Tuple[float, ...] = (1.0, 0.5, 0.25, 0.125)
    precision_target: float = 0.94
    bootstrap: int = 1000
    # combine-strategy selection: after weight admission, the stacked
    # combiner (ensemble/combine.py STACKING — shipped in the device
    # program but never exercised by this protocol before) competes with
    # weighted_average on validation; the winner is recorded in
    # selected_blend.strategy and deployed by apply_quality_artifact
    try_stacking: bool = True
    # saving into a checkpoint_dir whose latest step records a DIFFERENT
    # text-encoder architecture is refused unless explicitly allowed —
    # mixing architectures across steps makes "restore latest + apply
    # artifact" quietly incoherent (VERDICT Weak #5)
    allow_arch_mismatch: bool = False


def _auc(y: np.ndarray, s: np.ndarray) -> float:
    """Mann-Whitney AUC with tie-averaged ranks (ties get the mean of the
    rank run they occupy — without this, tied scores would be credited in
    arbitrary argsort order and a constant scorer could report AUC 1.0)."""
    _, inv, counts = np.unique(s, return_inverse=True, return_counts=True)
    ends = np.cumsum(counts)
    rank = (ends - (counts - 1) / 2.0)[inv]
    pos = y > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    return float((rank[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def _prf(y: np.ndarray, flag: np.ndarray) -> Dict[str, float]:
    pos = y > 0.5
    tp = float((flag & pos).sum())
    return {
        "accuracy": round(float((flag == pos).mean()), 4),
        "precision": round(tp / max(float(flag.sum()), 1.0), 4),
        "recall": round(tp / max(float(pos.sum()), 1.0), 4),
    }


def _collect(scorer, gen, n_batches: int, batch_size: int) -> Dict[str, np.ndarray]:
    """One stream segment through the production assemble path."""
    cols: Dict[str, list] = {k: [] for k in (
        "features", "history", "hlen", "ids", "mask", "uf", "mf",
        "unf", "unm", "mnf", "mnm", "y")}
    for _ in range(n_batches):
        recs = gen.generate_batch(batch_size)
        b = scorer.assemble(recs)
        for key, val in (
            ("features", b.features), ("history", b.history),
            ("hlen", b.history_len), ("ids", b.token_ids),
            ("mask", b.token_mask), ("uf", b.user_feat),
            ("mf", b.merchant_feat), ("unf", b.user_neigh_feat),
            ("unm", b.user_neigh_mask), ("mnf", b.merch_neigh_feat),
            ("mnm", b.merch_neigh_mask),
        ):
            cols[key].append(np.asarray(val))
        cols["y"].append(np.asarray(
            [bool(r.get("is_fraud")) for r in recs], np.float32))
        # serving's post-score write-back, applied here so later segments
        # see the velocity state this segment created
        ts = time.time()
        for r in recs:
            scorer.velocity.update(str(r.get("user_id", "")),
                                   float(r.get("amount", 0.0)), ts)
    return {k: np.concatenate(v) for k, v in cols.items()}


def _train_branches(
    cfg: BlendEvalConfig, tr: Dict[str, np.ndarray],
    segments: Dict[str, Dict[str, np.ndarray]],
    log: Callable[[str], None],
) -> Tuple[Dict[str, Dict[str, np.ndarray]], Dict[str, Dict[str, float]],
           Dict[str, object]]:
    """Fit all five branches; return (scores[segment][branch], platt
    calibration constants per neural branch, trained+calibrated params)."""
    import jax
    import jax.numpy as jnp
    import optax

    from realtime_fraud_detection_tpu.models.bert import (
        bert_logits,
        init_bert_params,
    )
    from realtime_fraud_detection_tpu.models.gnn import (
        gnn_logits,
        init_gnn_params,
    )
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        IsolationForestTrainer,
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.lstm import (
        init_lstm_params,
        lstm_logits,
    )
    from realtime_fraud_detection_tpu.models.trees import tree_ensemble_predict
    from realtime_fraud_detection_tpu.training import GBDTTrainer
    from realtime_fraud_detection_tpu.training.neural import NeuralTrainer

    pos_w = float((1.0 - tr["y"].mean()) / max(tr["y"].mean(), 1e-6))
    scores: Dict[str, Dict[str, np.ndarray]] = {k: {} for k in segments}

    log("training trees + isolation forest")
    gtr = GBDTTrainer(n_estimators=cfg.n_trees, max_depth=cfg.tree_depth,
                      seed=2)
    trees = gtr.fit(tr["features"], tr["y"])
    ifo = IsolationForestTrainer(n_estimators=cfg.iforest_trees, seed=4).fit(
        tr["features"][tr["y"] < 0.5][:6000])
    tfn = jax.jit(tree_ensemble_predict)
    ifn = jax.jit(iforest_predict)
    for k, d in segments.items():
        scores[k]["xgboost_primary"] = np.asarray(tfn(trees, d["features"]))
        scores[k]["isolation_forest"] = np.asarray(ifn(ifo, d["features"]))

    log("training LSTM (class-weighted)")
    lp = init_lstm_params(jax.random.PRNGKey(0), tr["features"].shape[-1],
                          cfg.lstm_hidden)

    def lstm_loss(p, inputs, y):
        s, l = inputs
        per = optax.sigmoid_binary_cross_entropy(lstm_logits(p, s, l), y)
        return (per * jnp.where(y > 0.5, pos_w, 1.0)).mean()

    lp = NeuralTrainer(epochs=cfg.lstm_epochs, seed=0).train(
        lp, lstm_loss, (np.clip(tr["history"], -10, 10), tr["hlen"]),
        tr["y"])
    lfn = jax.jit(lstm_logits)
    lstm_z = {k: np.asarray(lfn(lp, np.clip(d["history"], -10, 10),
                                d["hlen"]))
              for k, d in segments.items()}

    log("training text branch (class-weighted)")
    bp = init_bert_params(jax.random.PRNGKey(1), cfg.bert)

    def text_loss(p, inputs, y):
        ids, mask = inputs
        lg = bert_logits(p, ids, mask, cfg.bert)
        per = optax.sigmoid_binary_cross_entropy(lg[:, 1] - lg[:, 0], y)
        return (per * jnp.where(y > 0.5, pos_w, 1.0)).mean()

    bp = NeuralTrainer(epochs=cfg.text_epochs, seed=1, batch_size=128,
                       optimizer=optax.adamw(5e-4)).train(
        bp, text_loss, (tr["ids"], tr["mask"]), tr["y"])
    bfn = jax.jit(lambda p, i, m: bert_logits(p, i, m, cfg.bert))
    text_z = {}
    for k, d in segments.items():
        lg = np.asarray(bfn(bp, d["ids"], d["mask"]))
        text_z[k] = lg[:, 1] - lg[:, 0]

    log("training GNN (class-weighted)")
    gp = init_gnn_params(jax.random.PRNGKey(2), tr["uf"].shape[-1],
                         tr["features"].shape[-1], 64)

    def gnn_loss(p, inputs, y):
        per = optax.sigmoid_binary_cross_entropy(gnn_logits(p, *inputs), y)
        return (per * jnp.where(y > 0.5, pos_w, 1.0)).mean()

    gp = NeuralTrainer(epochs=cfg.gnn_epochs, seed=2).train(
        gp, gnn_loss,
        (np.clip(tr["features"], -10, 10), tr["uf"], tr["mf"], tr["unf"],
         tr["unm"], tr["mnf"], tr["mnm"]), tr["y"])
    gfn = jax.jit(gnn_logits)
    gnn_z = {k: np.asarray(gfn(
        gp, np.clip(d["features"], -10, 10), d["uf"], d["mf"],
        d["unf"], d["unm"], d["mnf"], d["mnm"]))
        for k, d in segments.items()}

    # Platt-calibrate the class-weighted branches on VALIDATION, and FOLD
    # (a, b) into the head params (training/calibrate.py — the fold is
    # exact, so these probabilities ARE what the calibrated model serves,
    # and the returned params are the deployable calibrated branches)
    from realtime_fraud_detection_tpu.training.calibrate import (
        calibrate_bert_head,
        calibrate_gnn_head,
        calibrate_lstm_head,
        platt_apply,
        platt_fit,
    )

    y_val = segments["val"]["y"]
    calibration = {}
    folds = {"lstm_sequential": (lstm_z, lambda a, b: calibrate_lstm_head(lp, a, b)),
             "bert_text": (text_z, lambda a, b: calibrate_bert_head(bp, a, b)),
             "graph_neural": (gnn_z, lambda a, b: calibrate_gnn_head(gp, a, b))}
    calibrated_params = {}
    for name, (z, fold) in folds.items():
        a, b = platt_fit(z["val"], y_val)
        calibration[name] = {"a": round(a, 4), "b": round(b, 4)}
        calibrated_params[name] = fold(a, b)
        for k in segments:
            scores[k][name] = platt_apply(z[k], a, b).astype(np.float32)
    log(f"platt calibration (fit on val): {calibration}")
    trained = {
        "trees": trees,
        "iforest": ifo,
        "lstm": calibrated_params["lstm_sequential"],
        "bert": calibrated_params["bert_text"],
        "gnn": calibrated_params["graph_neural"],
    }
    return scores, calibration, trained


def _blend_fn(weights_by_name: Dict[str, float],
              strategy: str = "weighted_average"):
    """Serving-parity blend: the shared ``blend_branch_scores`` recipe
    (ensemble/combine.py — also the continuous-learning gate's combine),
    curried over this protocol's weights + strategy. Returns a callable
    scores_by_branch -> fraud probabilities running the SAME jitted
    combine the fused device program uses — weighted average or the
    stacked combiner."""
    from realtime_fraud_detection_tpu.ensemble.combine import (
        blend_branch_scores,
    )

    def blend(scores_by_branch: Dict[str, np.ndarray]) -> np.ndarray:
        return blend_branch_scores(scores_by_branch, weights_by_name,
                                   strategy)

    return blend


def run_blend_eval(cfg: Optional[BlendEvalConfig] = None,
                   log: Callable[[str], None] = lambda m: None,
                   checkpoint_dir: Optional[str] = None) -> Dict:
    """Execute the full protocol; returns the evidence dict (JSON-able).

    ``checkpoint_dir``: also save the trained + calibrated branches as a
    serving checkpoint (orbax, step 0) with the text-arch recorded in its
    metadata — the artifact + checkpoint pair is a complete deployment:
    ``rtfd serve --checkpoint-dir D --quality-artifact Q.json``."""
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import (
        TransactionGenerator,
    )
    from realtime_fraud_detection_tpu.utils.config import Config

    cfg = cfg or BlendEvalConfig()
    config_weights = Config().normalized_weights()

    gen = TransactionGenerator(num_users=cfg.num_users,
                               num_merchants=cfg.num_merchants,
                               seed=cfg.seed)
    scorer = FraudScorer(
        scorer_config=ScorerConfig(text_len=cfg.text_len,
                                   tokenizer=cfg.tokenizer),
        bert_config=cfg.bert)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

    log("collecting train/val/test stream segments (production assemble)")
    tr = _collect(scorer, gen, cfg.train_batches, cfg.batch_size)
    va = _collect(scorer, gen, cfg.val_batches, cfg.batch_size)
    te = _collect(scorer, gen, cfg.test_batches, cfg.batch_size)
    segments = {"val": va, "test": te}

    scores, calibration, trained = _train_branches(cfg, tr, segments, log)
    y_va, y_te = va["y"], te["y"]

    branch_auc = {
        name: {"val": round(_auc(y_va, scores["val"][name]), 4),
               "test": round(_auc(y_te, scores["test"][name]), 4)}
        for name in scores["val"]
    }
    log(f"per-branch AUC: {branch_auc}")

    # ---------------- A/B-gated admission (decisions on VALIDATION only)
    weights: Dict[str, float] = {n: config_weights[n] for n in _BASELINE}
    admission: List[Dict] = []
    cur_val_auc = _auc(y_va, _blend_fn(weights)(scores["val"]))
    candidates = sorted(
        (n for n in scores["val"] if n not in _BASELINE),
        key=lambda n: -branch_auc[n]["val"])
    for name in candidates:
        best = None
        for scale in cfg.weight_scales:
            trial = dict(weights)
            trial[name] = config_weights[name] * scale
            a = _auc(y_va, _blend_fn(trial)(scores["val"]))
            if best is None or a > best[0]:
                best = (a, scale, trial)
        a, scale, trial = best
        accepted = a >= cur_val_auc     # non-regression gate
        admission.append({
            "branch": name, "weight_scale": scale,
            "val_auc_before": round(cur_val_auc, 4),
            "val_auc_with": round(a, 4),
            "accepted": bool(accepted),
        })
        log(f"  {'ACCEPT' if accepted else 'reject'} {name} "
            f"(scale {scale}): {cur_val_auc:.4f} -> {a:.4f}")
        if accepted:
            weights, cur_val_auc = trial, a

    # ------------- combine-strategy selection (decided on VALIDATION):
    # the stacked combiner competes with weighted_average over the
    # admitted branch set — same weights, same jitted device combine
    strategy = "weighted_average"
    strategy_selection = {
        "weighted_average": round(cur_val_auc, 4),
    }
    if cfg.try_stacking:
        stack_val = _auc(y_va, _blend_fn(weights, "stacking")(scores["val"]))
        strategy_selection["stacking"] = round(stack_val, 4)
        if not np.isnan(stack_val) and stack_val > cur_val_auc:
            strategy, cur_val_auc = "stacking", stack_val
    strategy_selection["selected"] = strategy
    log(f"combine strategy (val): {strategy_selection}")

    blend = _blend_fn(weights, strategy)
    blend_te = blend(scores["test"])
    blend_va = blend(scores["val"])
    baseline_te = _blend_fn(
        {n: config_weights[n] for n in _BASELINE})(scores["test"])
    test_auc = _auc(y_te, blend_te)
    base_auc = _auc(y_te, baseline_te)

    # paired bootstrap CI on the AUC delta vs the round-4 baseline pair
    rng = np.random.default_rng(7)
    deltas = np.empty(cfg.bootstrap)
    n_te = len(y_te)
    for i in range(cfg.bootstrap):
        idx = rng.integers(0, n_te, n_te)
        deltas[i] = _auc(y_te[idx], blend_te[idx]) - _auc(
            y_te[idx], baseline_te[idx])
    ci = (float(np.percentile(deltas, 2.5)),
          float(np.percentile(deltas, 97.5)))

    # drop-one ablation of the selected blend (test segment)
    ablation = {}
    for name in list(weights):
        if len(weights) <= 1:
            break
        rest = {k: v for k, v in weights.items() if k != name}
        ablation[name] = round(
            test_auc - _auc(y_te, _blend_fn(rest, strategy)(
                scores["test"])), 4)

    # ---------------- operating points (threshold chosen on VALIDATION)
    pos_va = y_va > 0.5
    best_t, best_rec = 0.5, -1.0
    for t in np.linspace(0.05, 0.95, 181):
        flag = blend_va >= t
        tp = float((flag & pos_va).sum())
        prec = tp / max(float(flag.sum()), 1.0)
        rec = tp / max(float(pos_va.sum()), 1.0)
        if prec >= cfg.precision_target and rec > best_rec:
            best_t, best_rec = float(t), rec
    operating = {
        "at_0.5": _prf(y_te, blend_te >= 0.5),
        f"at_precision>={cfg.precision_target}": {
            "threshold": round(best_t, 3),
            **_prf(y_te, blend_te >= best_t),
        },
    }

    checkpoint_info = None
    if checkpoint_dir:
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
        from realtime_fraud_detection_tpu.scoring import ScoringModels

        mgr = CheckpointManager(checkpoint_dir)
        latest = mgr.latest_step()
        if latest is not None and not cfg.allow_arch_mismatch:
            prev_tm = (mgr.manifest(latest).get("metadata")
                       or {}).get("text_model")
            this_tm = dataclasses.asdict(cfg.bert)
            if prev_tm is not None and dict(prev_tm) != this_tm:
                # a dir mixing text architectures across steps makes
                # "restore latest" + "apply artifact" quietly incoherent —
                # refuse unless the caller explicitly allows it
                raise ValueError(
                    f"checkpoint dir {checkpoint_dir} step {latest} records "
                    f"text_model {prev_tm}, but this protocol runs "
                    f"{this_tm}; use a fresh directory or set "
                    f"allow_arch_mismatch")

        models = ScoringModels(
            trees=trained["trees"], iforest=trained["iforest"],
            lstm=trained["lstm"], gnn=trained["gnn"], bert=trained["bert"])
        step = 0 if latest is None else latest + 1
        mgr.save(
            step, params=models,
            metadata={
                "source": "blend_eval",
                "text_model": dataclasses.asdict(cfg.bert),
                "text_len": cfg.text_len,
                "tokenizer": cfg.tokenizer,
                "selected_blend": sorted(weights),
                "selected_strategy": strategy,
            })
        checkpoint_info = {"dir": str(checkpoint_dir), "step": step}
        log(f"saved trained+calibrated branches to {checkpoint_dir}")

    return {
        "protocol": {
            "stream": {"users": cfg.num_users,
                       "merchants": cfg.num_merchants, "seed": cfg.seed},
            "segments_txns": {"train": len(tr["y"]), "val": len(y_va),
                              "test": len(y_te)},
            "fraud_rate": {"train": round(float(tr["y"].mean()), 4),
                           "test": round(float(y_te.mean()), 4)},
            "assemble_path": "FraudScorer.assemble (live state)",
            "blend_math": "ensemble.combine.combine_predictions "
                          "(serving parity)",
            "tokenizer": cfg.tokenizer,
            "text_model": dataclasses.asdict(cfg.bert),
            "text_len": cfg.text_len,
            "platt_calibration": calibration,
        },
        "checkpoint": checkpoint_info,
        "branch_auc": branch_auc,
        "admission": admission,
        "strategy_selection": strategy_selection,
        "selected_blend": {
            "branches": sorted(weights),
            "weights": {k: round(v, 4) for k, v in sorted(weights.items())},
            "n_branches": len(weights),
            "strategy": strategy,
        },
        "test": {
            "blend_auc": round(test_auc, 4),
            "baseline_pair_auc": round(base_auc, 4),
            "delta_auc": round(test_auc - base_auc, 4),
            "delta_auc_bootstrap_95ci": [round(ci[0], 4), round(ci[1], 4)],
        },
        "ablation_drop_one_delta_auc": ablation,
        "operating_points": operating,
        "reference_claim": "96.8% accuracy, unmeasured "
                           "(reference README.md:203)",
    }

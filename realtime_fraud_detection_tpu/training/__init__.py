from realtime_fraud_detection_tpu.training.gbdt import GBDTTrainer  # noqa: F401

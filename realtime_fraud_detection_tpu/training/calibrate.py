"""Post-training probability calibration, folded into model parameters.

Class-weighted training (the imbalance fix that lifted the LSTM branch from
0.74 to ~0.97 AUC) deliberately shifts each branch's operating point: a
pos_weight of ~16 inflates predicted probabilities by roughly that factor
in odds space. That is fine for a branch alone (ranking is unchanged) but
poisons the ENSEMBLE, whose serving combine is a weighted average of raw
probabilities (ensemble/combine.py:114-117): an uncalibrated branch's
inflated scores drag every blend they join. The fix is Platt scaling —
fit ``sigmoid(a * z + b)`` on held-out validation logits — and because
every neural branch ends in a plain affine head, (a, b) FOLDS INTO THE
EXISTING PARAMETERS: scale the final weight matrix by ``a`` and shift the
bias. No new serving op, no wrapper — the calibrated model is just a model,
and the fused device program runs it unchanged.

Used by training/blend_eval.py before blend admission; the fold functions
are pinned exact by tests/test_blend_eval.py.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import numpy as np

__all__ = ["platt_fit", "platt_apply", "calibrate_lstm_head",
           "calibrate_gnn_head", "calibrate_bert_head"]


def platt_fit(logits: np.ndarray, labels: np.ndarray,
              iters: int = 500, lr: float = 0.1) -> Tuple[float, float]:
    """Fit (a, b) of ``p = sigmoid(a*z + b)`` by BCE gradient descent on
    held-out logits. Deterministic, initialized at identity (a=1, b=0)."""
    z = np.asarray(logits, np.float64)
    y = np.asarray(labels, np.float64)
    a, b = 1.0, 0.0
    for _ in range(iters):
        p = 1.0 / (1.0 + np.exp(-(a * z + b)))
        g = p - y
        a -= lr * float((g * z).mean())
        b -= lr * float(g.mean())
    return float(a), float(b)


def platt_apply(logits: np.ndarray, a: float, b: float) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-(a * np.asarray(logits, np.float64) + b)))


def calibrate_lstm_head(params: Dict[str, jax.Array], a: float,
                        b: float) -> Dict[str, jax.Array]:
    """Fold (a, b) into the LSTM's final dense (models/lstm.py w_head2):
    z' = a*z + b exactly, so ``sigmoid(lstm_logits(calibrated, x))`` IS the
    Platt-calibrated probability."""
    return {**params,
            "w_head2": params["w_head2"] * a,
            "b_head2": params["b_head2"] * a + b}


def calibrate_gnn_head(params: Dict[str, jax.Array], a: float,
                       b: float) -> Dict[str, jax.Array]:
    """Same fold for the GraphSAGE head (models/gnn.py w_head2)."""
    return {**params,
            "w_head2": params["w_head2"] * a,
            "b_head2": params["b_head2"] * a + b}


def calibrate_bert_head(params: Dict, a: float, b: float) -> Dict:
    """Fold into the 2-logit classifier (models/bert.py): the branch score
    is ``z = logit[1] - logit[0]``; scaling both columns by ``a`` and
    adding ``b`` to class 1's bias gives z' = a*z + b exactly."""
    clf = params["classifier"]
    new_b = clf["b"] * a
    new_b = new_b.at[1].add(b)
    return {**params, "classifier": {"w": clf["w"] * a, "b": new_b}}

"""Post-training probability calibration, folded into model parameters.

Class-weighted training (the imbalance fix that lifted the LSTM branch from
0.74 to ~0.97 AUC) deliberately shifts each branch's operating point: a
pos_weight of ~16 inflates predicted probabilities by roughly that factor
in odds space. That is fine for a branch alone (ranking is unchanged) but
poisons the ENSEMBLE, whose serving combine is a weighted average of raw
probabilities (ensemble/combine.py:114-117): an uncalibrated branch's
inflated scores drag every blend they join. The fix is Platt scaling —
fit ``sigmoid(a * z + b)`` on held-out validation logits — and because
every neural branch ends in a plain affine head, (a, b) FOLDS INTO THE
EXISTING PARAMETERS: scale the final weight matrix by ``a`` and shift the
bias. No new serving op, no wrapper — the calibrated model is just a model,
and the fused device program runs it unchanged.

Used by training/blend_eval.py before blend admission; the fold functions
are pinned exact by tests/test_blend_eval.py.
"""

from __future__ import annotations

import logging
from typing import Dict, Tuple

import jax
import numpy as np

__all__ = ["platt_fit", "platt_apply", "calibrate_lstm_head",
           "calibrate_gnn_head", "calibrate_bert_head"]

logger = logging.getLogger(__name__)


def _bce(z: np.ndarray, y: np.ndarray, a: float, b: float) -> float:
    p = 1.0 / (1.0 + np.exp(-(a * z + b)))
    eps = 1e-12
    return float(-(y * np.log(p + eps)
                   + (1.0 - y) * np.log(1.0 - p + eps)).mean())


def platt_fit(logits: np.ndarray, labels: np.ndarray,
              iters: int = 2000, lr: float = 0.1,
              tol: float = 1e-7) -> Tuple[float, float]:
    """Fit (a, b) of ``p = sigmoid(a*z + b)`` by BCE gradient descent on
    held-out logits. Deterministic, initialized at identity (a=1, b=0).

    The fit runs on CENTERED/STANDARDIZED logits — class-weighted training
    shifts the raw logit mean far from 0 (pos_weight ~16 ≈ +2.8 nats), and
    on uncentered data the coupled (a, b) gradients crawl (the b step keeps
    fighting the a step), leaving the fit far from converged at the
    iteration cap; with a large shift the surface can even push ``a``
    NEGATIVE, i.e. a branch-inverting miscalibration (round-5 advisor).
    The standardized solution (a', b') folds back exactly:
    ``a = a'/sd, b = b' - a'*mu/sd``.

    Iterates to convergence (parameter step < ``tol``) and FALLS BACK TO
    IDENTITY with a warning when the fit is unusable: fitted ``a <= 0``
    (would invert the branch's ranking) or the BCE did not improve over
    identity (the fit diverged or the tail slice is degenerate). Identity
    folds are no-ops, so a bad calibration slice can never make a branch
    worse than uncalibrated.
    """
    z = np.asarray(logits, np.float64).ravel()
    y = np.asarray(labels, np.float64).ravel()
    if z.size == 0:
        logger.warning("platt_fit: empty calibration slice; "
                       "falling back to identity")
        return 1.0, 0.0
    with np.errstate(invalid="ignore", over="ignore"):
        mu = float(z.mean())
        sd = float(z.std())
    if not np.isfinite(mu) or not np.isfinite(sd):
        logger.warning("platt_fit: non-finite logits; "
                       "falling back to identity")
        return 1.0, 0.0
    if sd < 1e-12:
        sd = 1.0           # constant logits: only b is identifiable
    zs = (z - mu) / sd
    # identity in STANDARDIZED space maps back to the identity transform
    # of the raw logits: a'=sd, b'=mu  ->  a=1, b=0
    a_s, b_s = sd, mu
    for _ in range(iters):
        p = 1.0 / (1.0 + np.exp(-(a_s * zs + b_s)))
        g = p - y
        da = lr * float((g * zs).mean())
        db = lr * float(g.mean())
        a_s -= da
        b_s -= db
        if abs(da) < tol and abs(db) < tol:
            break
    # fold the standardization back into (a, b): a*z + b == a_s*zs + b_s
    a = a_s / sd
    b = b_s - a_s * mu / sd
    if a <= 0.0:
        logger.warning(
            "platt_fit: fitted a=%.4f <= 0 would invert the branch's "
            "ranking; falling back to identity", a)
        return 1.0, 0.0
    if _bce(z, y, a, b) > _bce(z, y, 1.0, 0.0):
        logger.warning(
            "platt_fit: fit did not improve BCE over identity "
            "(%.5f vs %.5f); falling back to identity",
            _bce(z, y, a, b), _bce(z, y, 1.0, 0.0))
        return 1.0, 0.0
    return float(a), float(b)


def platt_apply(logits: np.ndarray, a: float, b: float) -> np.ndarray:
    return 1.0 / (1.0 + np.exp(-(a * np.asarray(logits, np.float64) + b)))


def calibrate_lstm_head(params: Dict[str, jax.Array], a: float,
                        b: float) -> Dict[str, jax.Array]:
    """Fold (a, b) into the LSTM's final dense (models/lstm.py w_head2):
    z' = a*z + b exactly, so ``sigmoid(lstm_logits(calibrated, x))`` IS the
    Platt-calibrated probability."""
    return {**params,
            "w_head2": params["w_head2"] * a,
            "b_head2": params["b_head2"] * a + b}


def calibrate_gnn_head(params: Dict[str, jax.Array], a: float,
                       b: float) -> Dict[str, jax.Array]:
    """Same fold for the GraphSAGE head (models/gnn.py w_head2)."""
    return {**params,
            "w_head2": params["w_head2"] * a,
            "b_head2": params["b_head2"] * a + b}


def calibrate_bert_head(params: Dict, a: float, b: float) -> Dict:
    """Fold into the 2-logit classifier (models/bert.py): the branch score
    is ``z = logit[1] - logit[0]``; scaling both columns by ``a`` and
    adding ``b`` to class 1's bias gives z' = a*z + b exactly."""
    clf = params["classifier"]
    new_b = clf["b"] * a
    new_b = new_b.at[1].add(b)
    return {**params, "classifier": {"w": clf["w"] * a, "b": new_b}}

"""Text-branch training: fine-tune the BERT classifier on simulated text.

The reference never trains its text model (the transformers serving path
returns random numbers, model_manager.py:332-336). Here the generator's
merchant pool provides supervision: transaction text assembled the same way
serving assembles it, labeled with the stream's fraud labels. Suspicious
merchant names (crypto/gift-card/wire tokens) correlate with high-risk
categories and fraud, giving the encoder a learnable signal.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from realtime_fraud_detection_tpu.models.bert import (
    BertConfig,
    bert_logits,
    init_bert_params,
)
from realtime_fraud_detection_tpu.models.text import combined_text
from realtime_fraud_detection_tpu.models.tokenizer import FraudTokenizer


def build_text_dataset(
    generator, n_transactions: int, max_length: int = 64
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(input_ids, attention_mask, labels) from a simulated stream."""
    tok = FraudTokenizer(max_length=max_length)
    texts, labels = [], []
    _, lab = generator.generate_encoded(n_transactions)
    mp = generator.merchants
    for i in range(n_transactions):
        m = int(lab["merchant_index"][i])
        texts.append(combined_text({
            "merchant_name": str(mp.names[m]),
            "category": str(mp.category[m]),
        }))
        labels.append(float(lab["is_fraud"][i]))
    ids, mask = tok.encode_batch(texts)
    return ids, mask, np.asarray(labels, np.float32)


def train_bert(
    generator,
    config: BertConfig | None = None,
    n_transactions: int = 20_000,
    max_length: int = 64,
    batch_size: int = 64,
    epochs: int = 2,
    learning_rate: float = 5e-5,
    seed: int = 0,
    pos_weight: float | None = None,
    calibrate: bool = True,
) -> Dict:
    """Fine-tune (from random init) the classifier on stream text.
    ``pos_weight=None`` = auto class weighting (neg/pos ratio; fraud is ~5%
    of the stream); ``calibrate`` folds a tail-fitted Platt transform into
    the classifier head — see training/neural.py weighted_bce_loss and
    training/calibrate.py for why weighted branches must be calibrated
    before the serving ensemble averages their probabilities."""
    from realtime_fraud_detection_tpu.training.neural import (
        NeuralTrainer,
        _calibration_split,
        auto_pos_weight,
    )

    config = config or BertConfig()
    ids, mask, labels = build_text_dataset(generator, n_transactions, max_length)
    n_cal = _calibration_split(len(labels)) if calibrate else 0
    tr_sl = slice(0, len(labels) - n_cal)
    params = init_bert_params(jax.random.PRNGKey(seed), config)
    pw = (auto_pos_weight(labels[tr_sl]) if pos_weight is None
          else float(pos_weight))

    def loss_fn(p, inputs, by):
        bi, bm = inputs
        logits = bert_logits(p, bi, bm, config)
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits, by.astype(jnp.int32)
        )
        return (per * jnp.where(by > 0.5, pw, 1.0)).mean()

    trainer = NeuralTrainer(
        batch_size=batch_size, epochs=epochs, seed=seed,
        optimizer=optax.adamw(learning_rate),
    )
    params = trainer.train(params, loss_fn, (ids[tr_sl], mask[tr_sl]),
                           labels[tr_sl])
    if n_cal and 0 < labels[-n_cal:].sum() < n_cal:
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_bert_head,
            platt_fit,
        )

        lg = np.asarray(bert_logits(params, ids[-n_cal:], mask[-n_cal:],
                                    config))
        a, b = platt_fit(lg[:, 1] - lg[:, 0], labels[-n_cal:])
        params = calibrate_bert_head(params, a, b)
    return params

"""Histogram-based gradient boosting trainer.

The reference trains XGBoost with 100 trees / depth 6 / lr 0.1 /
subsample 0.8 / colsample 0.8 on synthetic data (model_trainer.py:71-121,
hyperparams from config.py:136-142). xgboost isn't in this image — and the
deployment target is a TPU tensor program anyway — so this trainer produces
``TreeEnsemble`` arrays directly: second-order (grad/hess) logistic boosting
with quantile-binned histogram splits, growing complete depth-D trees.

Unsplit nodes keep threshold=+inf (route left) with both leaves carrying the
parent value, which is exactly the padding convention the tensorized forward
pass expects (models/trees.py).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

from realtime_fraud_detection_tpu.models.trees import TreeEnsemble


@dataclasses.dataclass
class GBDTTrainer:
    n_estimators: int = 100
    max_depth: int = 6
    learning_rate: float = 0.1
    subsample: float = 0.8
    colsample_bytree: float = 0.8
    n_bins: int = 64
    reg_lambda: float = 1.0
    min_child_weight: float = 1.0
    min_gain: float = 1e-6
    seed: int = 42

    def fit(self, x: np.ndarray, y: np.ndarray) -> TreeEnsemble:
        """Fit on (N, F) features and {0,1} labels; returns device-ready trees.

        Also sets ``self.feature_importances_`` — per-feature total split
        gain, normalized to sum 1 (the xgboost "gain" importance the
        reference surfaces as top-10 feature importances in its prediction
        explanations, ensemble_predictor.py:371-435).
        """
        rng = np.random.default_rng(self.seed)
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.float32)
        n, f = x.shape
        depth = self.max_depth
        n_internal = 2**depth - 1
        n_leaf = 2**depth

        # quantile bin edges per feature (shared across trees)
        qs = np.linspace(0, 1, self.n_bins + 1)[1:-1]
        edges = np.quantile(x, qs, axis=0).astype(np.float32)  # [n_bins-1, F]
        binned = np.empty((n, f), np.int32)
        for j in range(f):
            binned[:, j] = np.searchsorted(edges[:, j], x[:, j], side="right")

        p0 = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
        base = float(np.log(p0 / (1 - p0)))
        logits = np.full(n, base, np.float64)

        feat_arr = np.zeros((self.n_estimators, n_internal), np.int32)
        thr_arr = np.full((self.n_estimators, n_internal), np.inf, np.float32)
        leaf_arr = np.zeros((self.n_estimators, n_leaf), np.float32)
        gain_by_feature = np.zeros(f, np.float64)

        for t in range(self.n_estimators):
            p = 1.0 / (1.0 + np.exp(-logits))
            grad = p - y
            hess = np.maximum(p * (1 - p), 1e-12)

            rows = rng.random(n) < self.subsample
            cols = rng.permutation(f)[: max(1, int(round(f * self.colsample_bytree)))]

            node_of = np.zeros(n, np.int32)  # complete-tree node id per sample
            node_of[~rows] = -1              # excluded from split finding
            for node in range(n_internal):
                mask = node_of == node
                if not mask.any():
                    continue
                g, h = grad[mask], hess[mask]
                split = self._best_split(binned[mask][:, cols], g, h)
                if split is None:
                    # leaf early: park samples in leftmost descendant leaf
                    node_of[mask] = _leftmost_leaf(node, depth)
                    continue
                ci, bin_id, gain = split
                j = cols[ci]
                gain_by_feature[j] += gain
                feat_arr[t, node] = j
                thr_arr[t, node] = (
                    edges[bin_id, j] if bin_id < edges.shape[0] else np.float32(np.inf)
                )
                right = mask & (binned[:, j] > bin_id)
                node_of[np.where(mask & ~right)[0]] = 2 * node + 1
                node_of[np.where(right)[0]] = 2 * node + 2

            # leaf values from full-tree positions (padding convention: parked
            # samples sit in the leftmost-descendant leaf)
            leaf_vals = np.zeros(n_leaf, np.float64)
            for leaf in range(n_leaf):
                mask = node_of == n_internal + leaf
                if mask.any():
                    gsum, hsum = grad[mask].sum(), hess[mask].sum()
                    leaf_vals[leaf] = -self.learning_rate * gsum / (hsum + self.reg_lambda)
            _fill_pruned_leaves(thr_arr[t], leaf_vals, depth)
            leaf_arr[t] = leaf_vals.astype(np.float32)

            # update logits for ALL rows via the tensor representation
            logits += _numpy_tree_forward(
                feat_arr[t], thr_arr[t], leaf_arr[t], x
            )

        total_gain = gain_by_feature.sum()
        self.feature_importances_ = (
            (gain_by_feature / total_gain).astype(np.float32)
            if total_gain > 0 else np.zeros(f, np.float32)
        )

        import jax.numpy as jnp

        return TreeEnsemble(
            feature=jnp.asarray(feat_arr),
            threshold=jnp.asarray(thr_arr),
            leaf=jnp.asarray(leaf_arr),
            base_score=jnp.asarray(base, jnp.float32),
        )

    def _best_split(
        self, binned: np.ndarray, grad: np.ndarray, hess: np.ndarray
    ) -> Tuple[int, int, float] | None:
        """Best (col_index, bin, gain) by second-order gain over histograms."""
        gtot, htot = grad.sum(), hess.sum()
        if htot < 2 * self.min_child_weight:
            return None
        parent = gtot * gtot / (htot + self.reg_lambda)
        best = None
        best_gain = self.min_gain
        for ci in range(binned.shape[1]):
            b = binned[:, ci]
            gh = np.zeros((self.n_bins, 2))
            np.add.at(gh, b, np.stack([grad, hess], axis=1))
            gl = np.cumsum(gh[:, 0])[:-1]
            hl = np.cumsum(gh[:, 1])[:-1]
            gr, hr = gtot - gl, htot - hl
            valid = (hl >= self.min_child_weight) & (hr >= self.min_child_weight)
            with np.errstate(divide="ignore", invalid="ignore"):
                gain = (
                    gl * gl / (hl + self.reg_lambda)
                    + gr * gr / (hr + self.reg_lambda)
                    - parent
                ) / 2.0
            gain = np.where(valid, gain, -np.inf)
            k = int(np.argmax(gain))
            if gain[k] > best_gain:
                best_gain = float(gain[k])
                best = (ci, k, best_gain)
        return best


def _leftmost_leaf(node: int, depth: int) -> int:
    """Leaf id (in complete-tree numbering) reached by always going left."""
    level = int(np.log2(node + 1))
    for _ in range(depth - level):
        node = 2 * node + 1
    return node


def _fill_pruned_leaves(thresholds: np.ndarray, leaf_vals: np.ndarray, depth: int) -> None:
    """Copy each unsplit subtree's left-leaf value across its whole leaf span.

    With threshold=+inf everything routes left at inference, so only the
    leftmost leaf of a pruned subtree is ever reached — but keeping the span
    consistent makes the arrays robust to any traversal convention.
    """
    n_internal = 2**depth - 1
    for node in range(n_internal):
        if np.isinf(thresholds[node]):
            level = int(np.log2(node + 1))
            span = 2 ** (depth - level)
            first = _leftmost_leaf(node, depth) - n_internal
            leaf_vals[first : first + span] = leaf_vals[first]


def _numpy_tree_forward(
    feature: np.ndarray, threshold: np.ndarray, leaf: np.ndarray, x: np.ndarray
) -> np.ndarray:
    """Single-tree forward in NumPy (training-side logit updates)."""
    n_internal = feature.shape[0]
    depth = int(np.log2(n_internal + 1))
    node = np.zeros(x.shape[0], np.int32)
    for _ in range(depth):
        f = feature[node]
        t = threshold[node]
        node = 2 * node + 1 + (x[np.arange(x.shape[0]), f] >= t).astype(np.int32)
    return leaf[node - n_internal]

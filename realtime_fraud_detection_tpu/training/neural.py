"""Neural branch training: LSTM and GraphSAGE on simulated streams.

The reference ships no trainer for its LSTM/BERT/GNN despite the docstring
claim (model_trainer.py:2-4 vs SURVEY.md 3.5), so this fills the gap: a
single optax BCE loop plus dataset builders that replay the simulator stream
through the state stores to produce real sequential/graph supervision —
per-user histories feed the LSTM exactly the way serving will
(state.UserHistoryStore), and the user-merchant graph grows edge-by-edge
(state.EntityGraphStore).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from realtime_fraud_detection_tpu.features.extract import extract_features
from realtime_fraud_detection_tpu.models.gnn import (
    build_node_features,
    gather_neighbor_features,
    gnn_logits,
    init_gnn_params,
)
from realtime_fraud_detection_tpu.models.lstm import init_lstm_params, lstm_logits
from realtime_fraud_detection_tpu.state.history import EntityGraphStore, UserHistoryStore


def bce_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


def weighted_bce_loss(logits: jax.Array, labels: jax.Array,
                      pos_weight: float) -> jax.Array:
    """BCE with the positive class up-weighted. At the stream's ~5% fraud
    rate, unweighted BCE under-fits the positives — the round-4 LSTM's
    0.74 AUC was exactly this (round-5 measurement: class weighting lifts
    it to ~0.97). NOTE: weighting inflates predicted probabilities; fold a
    Platt fit into the head before blending (training/calibrate.py)."""
    per = optax.sigmoid_binary_cross_entropy(logits, labels)
    return (per * jnp.where(labels > 0.5, pos_weight, 1.0)).mean()


def auto_pos_weight(labels: np.ndarray) -> float:
    """neg/pos ratio — the standard balanced weighting."""
    p = float(np.asarray(labels).mean())
    return (1.0 - p) / max(p, 1e-6)


@dataclasses.dataclass
class NeuralTrainer:
    """Minibatch training loop shared by the LSTM, GNN, and BERT branches."""

    learning_rate: float = 1e-3
    batch_size: int = 256
    epochs: int = 3
    seed: int = 0
    optimizer: optax.GradientTransformation | None = None

    def train(
        self,
        params: Dict[str, jax.Array],
        loss_fn: Callable[[Dict[str, jax.Array], Tuple, jax.Array], jax.Array],
        inputs: Tuple[np.ndarray, ...],
        labels: np.ndarray,
    ) -> Dict[str, jax.Array]:
        tx = self.optimizer if self.optimizer is not None else optax.adam(self.learning_rate)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, batch_inputs, batch_labels):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch_inputs, batch_labels)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        n = len(labels)
        rng = np.random.default_rng(self.seed)
        bs = min(self.batch_size, n)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            for start in range(0, n - bs + 1, bs):
                idx = order[start : start + bs]
                batch_inputs = tuple(a[idx] for a in inputs)
                batch_labels = jnp.asarray(labels[idx], jnp.float32)
                params, opt_state, _ = step(params, opt_state, batch_inputs, batch_labels)
        return params


# --------------------------------------------------------------------------
# dataset builders
# --------------------------------------------------------------------------

def build_sequence_dataset(
    generator,
    n_transactions: int,
    seq_len: int = 10,
    feature_dim: int = 64,
    chunk: int = 4096,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Replay a stream through UserHistoryStore -> (sequences, lengths, labels).

    The label of a sequence is the fraud label of its most recent step — the
    LSTM scores "is the txn that just arrived fraudulent given the user's
    recent history" (reference sequence_length 10, config.py:151-157).
    """
    store = UserHistoryStore(seq_len=seq_len, feature_dim=feature_dim)
    seqs, lens, labels = [], [], []
    remaining = n_transactions
    while remaining > 0:
        b = min(chunk, remaining)
        remaining -= b
        batch, lab = generator.generate_encoded(b)
        # the serving-side clip (ensemble_predictor.py:248) keeps neural
        # inputs in a trainable range; raw amounts/velocities reach 1e4
        feats = np.clip(np.asarray(extract_features(batch)), -10, 10)
        user_ids = [str(generator.users.ids[i]) for i in lab["user_index"]]
        s, l = store.append_and_gather(user_ids, feats)
        seqs.append(s)
        lens.append(l)
        labels.append(lab["is_fraud"])
    return (
        np.concatenate(seqs, axis=0),
        np.concatenate(lens, axis=0),
        np.concatenate(labels, axis=0).astype(np.float32),
    )


def build_graph_dataset(
    generator,
    n_transactions: int,
    fanout: int = 16,
    node_dim: int = 16,
    chunk: int = 512,
):
    """Replay a stream through EntityGraphStore -> GNN training tensors.

    Edges are committed per chunk, so a chunk's samples see only edges from
    earlier chunks (no label leakage through the current batch); the chunk
    is kept small so neighborhoods actually populate.
    """
    graph = EntityGraphStore(fanout=fanout)
    user_table, merchant_table = build_node_features(
        generator.users, generator.merchants, node_dim
    )
    txn_f, uf, mf, unf, unm, mnf, mnm, labels = [], [], [], [], [], [], [], []
    remaining = n_transactions
    while remaining > 0:
        b = min(chunk, remaining)
        remaining -= b
        batch, lab = generator.generate_encoded(b)
        feats = np.clip(np.asarray(extract_features(batch)), -10, 10)
        u_idx, m_idx = lab["user_index"], lab["merchant_index"]
        un, un_mask = graph.user_neighbors(u_idx)
        mn, mn_mask = graph.merchant_neighbors(m_idx)
        txn_f.append(feats)
        uf.append(user_table[u_idx])
        mf.append(merchant_table[m_idx])
        unf.append(gather_neighbor_features(merchant_table, un, un_mask))
        unm.append(un_mask)
        mnf.append(gather_neighbor_features(user_table, mn, mn_mask))
        mnm.append(mn_mask)
        labels.append(lab["is_fraud"])
        graph.add_edges(u_idx, m_idx)  # edges visible to FUTURE batches only
    cat = lambda xs: np.concatenate(xs, axis=0)  # noqa: E731
    return (
        (cat(txn_f), cat(uf), cat(mf), cat(unf), cat(unm), cat(mnf), cat(mnm)),
        cat(labels).astype(np.float32),
        (user_table, merchant_table, graph),
    )


# --------------------------------------------------------------------------
# convenience end-to-end trainers
# --------------------------------------------------------------------------

def _calibration_split(n: int, frac: float = 0.1,
                       min_rows: int = 200) -> int:
    """Rows reserved at the stream TAIL for the Platt fit (temporal split:
    calibrate on data later than anything trained on).

    Returns 0 (calibration DISABLED, with a warning) when the slice would
    consume half or more of the dataset — on a tiny dataset the old
    unconditional ``max(min_rows, ...)`` could swallow the whole training
    set, leaving zero training rows (NaN pos_weight from an empty label
    slice, a zero-row training loop). Calibration is an optional refinement;
    training data is not.
    """
    n_cal = max(min_rows, int(n * frac))
    if n_cal * 2 > n:
        import logging

        logging.getLogger(__name__).warning(
            "calibration disabled: the tail slice (%d rows, min %d) would "
            "consume >= half of the %d-row dataset; train on everything "
            "and skip the Platt fit", n_cal, min_rows, n)
        return 0
    return n_cal


def train_lstm(
    generator, n_transactions: int = 50_000, seq_len: int = 10,
    hidden: int = 128, epochs: int = 3, seed: int = 0,
    pos_weight: float | None = None, calibrate: bool = True,
) -> Dict[str, jax.Array]:
    """``pos_weight=None`` = auto (neg/pos ratio — the round-5 fix for the
    0.74-AUC unweighted recipe); pass 1.0 to reproduce unweighted BCE.

    ``calibrate`` (default ON) holds out the stream tail, fits Platt
    scaling there, and FOLDS it into the head (training/calibrate.py):
    class weighting inflates probabilities, and the serving ensemble
    averages raw probabilities, so an uncalibrated weighted branch would
    systematically shift every blend it joins."""
    seqs, lens, labels = build_sequence_dataset(generator, n_transactions, seq_len)
    n_cal = _calibration_split(len(labels)) if calibrate else 0
    tr_sl = slice(0, len(labels) - n_cal)
    params = init_lstm_params(jax.random.PRNGKey(seed), seqs.shape[-1], hidden)
    pw = (auto_pos_weight(labels[tr_sl]) if pos_weight is None
          else float(pos_weight))

    def loss_fn(p, inputs, y):
        s, l = inputs
        return weighted_bce_loss(lstm_logits(p, s, l), y, pw)

    params = NeuralTrainer(epochs=epochs, seed=seed).train(
        params, loss_fn, (seqs[tr_sl], lens[tr_sl]), labels[tr_sl]
    )
    if n_cal and 0 < labels[-n_cal:].sum() < n_cal:
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_lstm_head,
            platt_fit,
        )

        z = np.asarray(lstm_logits(params, seqs[-n_cal:], lens[-n_cal:]))
        a, b = platt_fit(z, labels[-n_cal:])
        params = calibrate_lstm_head(params, a, b)
    return params


def build_typed_graph_dataset(
    generator,
    n_transactions: int,
    fanout: int = 8,
    fanout2: int = 8,
    node_dim: int = 16,
    chunk: int = 256,
):
    """Replay a stream through the TYPED entity graph -> GNN tensors.

    The heterogeneous analog of :func:`build_graph_dataset`: edges
    (user↔device, user↔merchant, user↔IP) commit per chunk AFTER the
    chunk's samples are drawn (sample-then-insert, exactly the serving
    seam's order), and the sampling runs through the SAME
    ``graph.sampler.NeighborSampler`` serving uses — same interleave,
    same two-hop walk, same ``typed_entity_features`` rows — so the GNN
    trains on precisely the tensors it will be served. Works from the
    dict stream (``generate_batch``): the typed links live in the
    transaction dicts' ``device_id``/``ip_address`` fields, which the
    vectorized encoded path never materializes.

    Returns ``(inputs, labels, graph)`` where inputs matches
    ``gnn_logits``'s positional order (txn, user, merchant, u-neigh x2,
    m-neigh x2, u-2hop x2, m-2hop x2).
    """
    from realtime_fraud_detection_tpu.features.extract import (
        extract_features_host,
    )
    from realtime_fraud_detection_tpu.features.schema import (
        encode_transactions,
    )
    from realtime_fraud_detection_tpu.graph.sampler import NeighborSampler
    from realtime_fraud_detection_tpu.graph.store import TypedEntityGraph

    user_table, merchant_table = build_node_features(
        generator.users, generator.merchants, node_dim)
    uid_to_row = {str(u): i for i, u in enumerate(generator.users.ids)}
    mid_to_row = {str(m): i for i, m in enumerate(generator.merchants.ids)}
    # serving parity: a worker's entity index only carries users it has
    # actually SCORED (scorer._EntityIndex.peek_rows returns zeros for
    # the rest), so 2-hop cohort rows resolve to profile stats only for
    # users already seen as centers — train on the same visibility
    seen_users: set = set()

    def user_rows(ids):
        out = np.zeros((len(ids), node_dim), np.float32)
        for k, i in enumerate(ids):
            i = str(i)
            r = uid_to_row.get(i)
            if r is not None and i in seen_users:
                out[k] = user_table[r]
        return out

    def merchant_rows(ids):
        out = np.zeros((len(ids), node_dim), np.float32)
        for k, i in enumerate(ids):
            r = mid_to_row.get(str(i))
            if r is not None:
                out[k] = merchant_table[r]
        return out

    graph = TypedEntityGraph(fanout=fanout)
    sampler = NeighborSampler(graph, node_dim, fanout, fanout2,
                              user_rows=user_rows,
                              merchant_rows=merchant_rows)
    uprofs = generator.users.profiles()
    mprofs = generator.merchants.profiles()
    cols: Dict[str, list] = {k: [] for k in (
        "txn", "uf", "mf", "unf", "unm", "mnf", "mnm",
        "un2f", "un2m", "mn2f", "mn2m", "y")}
    remaining = n_transactions
    while remaining > 0:
        b = min(chunk, remaining)
        remaining -= b
        records = generator.generate_batch(b)
        user_ids = [str(r["user_id"]) for r in records]
        merchant_ids = [str(r["merchant_id"]) for r in records]
        seen_users.update(user_ids)     # centers are known within-batch,
        txn = encode_transactions(records, uprofs, mprofs, {})
        # RAW features, exactly what the fused program feeds gnn_logits
        # at serve time (the clipped-input recipe of the sequence builder
        # would train a model the serving path never shows that range)
        feats = np.asarray(extract_features_host(txn))
        s = sampler.sample(user_ids, merchant_ids)
        cols["txn"].append(feats)
        cols["uf"].append(user_rows(user_ids))
        cols["mf"].append(merchant_rows(merchant_ids))
        cols["unf"].append(s["user_neigh_feat"])
        cols["unm"].append(s["user_neigh_mask"])
        cols["mnf"].append(s["merch_neigh_feat"])
        cols["mnm"].append(s["merch_neigh_mask"])
        cols["un2f"].append(s["user_neigh2_feat"])
        cols["un2m"].append(s["user_neigh2_mask"])
        cols["mn2f"].append(s["merch_neigh2_feat"])
        cols["mn2m"].append(s["merch_neigh2_mask"])
        cols["y"].append(np.asarray(
            [bool(r.get("is_fraud")) for r in records], np.float32))
        # edges visible to FUTURE chunks only (no leakage through the
        # current batch); the sync drops sampler-cache entries the new
        # edges invalidate
        graph.add_batch(user_ids, merchant_ids,
                        [str(r.get("device_id") or "") for r in records],
                        [str(r.get("ip_address") or "") for r in records])
        sampler.sync()
    cat = lambda xs: np.concatenate(xs, axis=0)  # noqa: E731
    inputs = tuple(cat(cols[k]) for k in (
        "txn", "uf", "mf", "unf", "unm", "mnf", "mnm",
        "un2f", "un2m", "mn2f", "mn2m"))
    return inputs, cat(cols["y"]).astype(np.float32), graph


def train_typed_gnn(
    generator, n_transactions: int = 20_000, fanout: int = 8,
    fanout2: int = 8, node_dim: int = 16, hidden: int = 64,
    epochs: int = 3, seed: int = 0, pos_weight: float | None = None,
    calibrate: bool = True,
):
    """Train the heterogeneous (typed entity-graph) GNN branch.

    Same recipe as :func:`train_gnn` — auto class weighting, tail-split
    Platt calibration folded into the head — over the typed two-hop
    tensors. Returns the typed params dict (``is_typed_gnn`` True)."""
    inputs, labels, _graph = build_typed_graph_dataset(
        generator, n_transactions, fanout, fanout2, node_dim)
    n_cal = _calibration_split(len(labels)) if calibrate else 0
    tr_sl = slice(0, len(labels) - n_cal)
    params = init_gnn_params(
        jax.random.PRNGKey(seed), node_dim, inputs[0].shape[-1], hidden,
        typed=True)
    pw = (auto_pos_weight(labels[tr_sl]) if pos_weight is None
          else float(pos_weight))

    def loss_fn(p, batch_inputs, y):
        return weighted_bce_loss(gnn_logits(p, *batch_inputs), y, pw)

    params = NeuralTrainer(epochs=epochs, seed=seed).train(
        params, loss_fn, tuple(a[tr_sl] for a in inputs), labels[tr_sl]
    )
    if n_cal and 0 < labels[-n_cal:].sum() < n_cal:
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_gnn_head,
            platt_fit,
        )

        z = np.asarray(gnn_logits(params, *(a[-n_cal:] for a in inputs)))
        a, b = platt_fit(z, labels[-n_cal:])
        params = calibrate_gnn_head(params, a, b)
    return params


def train_gnn(
    generator, n_transactions: int = 50_000, fanout: int = 16,
    node_dim: int = 16, hidden: int = 64, epochs: int = 3, seed: int = 0,
    pos_weight: float | None = None, calibrate: bool = True,
):
    """``pos_weight=None`` = auto; ``calibrate`` folds a tail-fitted Platt
    transform into the head (see train_lstm)."""
    inputs, labels, (user_table, merchant_table, graph) = build_graph_dataset(
        generator, n_transactions, fanout, node_dim
    )
    n_cal = _calibration_split(len(labels)) if calibrate else 0
    tr_sl = slice(0, len(labels) - n_cal)
    params = init_gnn_params(
        jax.random.PRNGKey(seed), node_dim, inputs[0].shape[-1], hidden
    )
    pw = (auto_pos_weight(labels[tr_sl]) if pos_weight is None
          else float(pos_weight))

    def loss_fn(p, batch_inputs, y):
        return weighted_bce_loss(gnn_logits(p, *batch_inputs), y, pw)

    params = NeuralTrainer(epochs=epochs, seed=seed).train(
        params, loss_fn, tuple(a[tr_sl] for a in inputs), labels[tr_sl]
    )
    if n_cal and 0 < labels[-n_cal:].sum() < n_cal:
        from realtime_fraud_detection_tpu.training.calibrate import (
            calibrate_gnn_head,
            platt_fit,
        )

        z = np.asarray(gnn_logits(params, *(a[-n_cal:] for a in inputs)))
        a, b = platt_fit(z, labels[-n_cal:])
        params = calibrate_gnn_head(params, a, b)
    return params, user_table, merchant_table, graph

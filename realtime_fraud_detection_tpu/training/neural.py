"""Neural branch training: LSTM and GraphSAGE on simulated streams.

The reference ships no trainer for its LSTM/BERT/GNN despite the docstring
claim (model_trainer.py:2-4 vs SURVEY.md 3.5), so this fills the gap: a
single optax BCE loop plus dataset builders that replay the simulator stream
through the state stores to produce real sequential/graph supervision —
per-user histories feed the LSTM exactly the way serving will
(state.UserHistoryStore), and the user-merchant graph grows edge-by-edge
(state.EntityGraphStore).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from realtime_fraud_detection_tpu.features.extract import extract_features
from realtime_fraud_detection_tpu.models.gnn import (
    build_node_features,
    gather_neighbor_features,
    gnn_logits,
    init_gnn_params,
)
from realtime_fraud_detection_tpu.models.lstm import init_lstm_params, lstm_logits
from realtime_fraud_detection_tpu.state.history import EntityGraphStore, UserHistoryStore


def bce_loss(logits: jax.Array, labels: jax.Array) -> jax.Array:
    return optax.sigmoid_binary_cross_entropy(logits, labels).mean()


@dataclasses.dataclass
class NeuralTrainer:
    """Minibatch training loop shared by the LSTM, GNN, and BERT branches."""

    learning_rate: float = 1e-3
    batch_size: int = 256
    epochs: int = 3
    seed: int = 0
    optimizer: optax.GradientTransformation | None = None

    def train(
        self,
        params: Dict[str, jax.Array],
        loss_fn: Callable[[Dict[str, jax.Array], Tuple, jax.Array], jax.Array],
        inputs: Tuple[np.ndarray, ...],
        labels: np.ndarray,
    ) -> Dict[str, jax.Array]:
        tx = self.optimizer if self.optimizer is not None else optax.adam(self.learning_rate)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, batch_inputs, batch_labels):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch_inputs, batch_labels)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        n = len(labels)
        rng = np.random.default_rng(self.seed)
        bs = min(self.batch_size, n)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            for start in range(0, n - bs + 1, bs):
                idx = order[start : start + bs]
                batch_inputs = tuple(a[idx] for a in inputs)
                batch_labels = jnp.asarray(labels[idx], jnp.float32)
                params, opt_state, _ = step(params, opt_state, batch_inputs, batch_labels)
        return params


# --------------------------------------------------------------------------
# dataset builders
# --------------------------------------------------------------------------

def build_sequence_dataset(
    generator,
    n_transactions: int,
    seq_len: int = 10,
    feature_dim: int = 64,
    chunk: int = 4096,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Replay a stream through UserHistoryStore -> (sequences, lengths, labels).

    The label of a sequence is the fraud label of its most recent step — the
    LSTM scores "is the txn that just arrived fraudulent given the user's
    recent history" (reference sequence_length 10, config.py:151-157).
    """
    store = UserHistoryStore(seq_len=seq_len, feature_dim=feature_dim)
    seqs, lens, labels = [], [], []
    remaining = n_transactions
    while remaining > 0:
        b = min(chunk, remaining)
        remaining -= b
        batch, lab = generator.generate_encoded(b)
        # the serving-side clip (ensemble_predictor.py:248) keeps neural
        # inputs in a trainable range; raw amounts/velocities reach 1e4
        feats = np.clip(np.asarray(extract_features(batch)), -10, 10)
        user_ids = [str(generator.users.ids[i]) for i in lab["user_index"]]
        s, l = store.append_and_gather(user_ids, feats)
        seqs.append(s)
        lens.append(l)
        labels.append(lab["is_fraud"])
    return (
        np.concatenate(seqs, axis=0),
        np.concatenate(lens, axis=0),
        np.concatenate(labels, axis=0).astype(np.float32),
    )


def build_graph_dataset(
    generator,
    n_transactions: int,
    fanout: int = 16,
    node_dim: int = 16,
    chunk: int = 512,
):
    """Replay a stream through EntityGraphStore -> GNN training tensors.

    Edges are committed per chunk, so a chunk's samples see only edges from
    earlier chunks (no label leakage through the current batch); the chunk
    is kept small so neighborhoods actually populate.
    """
    graph = EntityGraphStore(fanout=fanout)
    user_table, merchant_table = build_node_features(
        generator.users, generator.merchants, node_dim
    )
    txn_f, uf, mf, unf, unm, mnf, mnm, labels = [], [], [], [], [], [], [], []
    remaining = n_transactions
    while remaining > 0:
        b = min(chunk, remaining)
        remaining -= b
        batch, lab = generator.generate_encoded(b)
        feats = np.clip(np.asarray(extract_features(batch)), -10, 10)
        u_idx, m_idx = lab["user_index"], lab["merchant_index"]
        un, un_mask = graph.user_neighbors(u_idx)
        mn, mn_mask = graph.merchant_neighbors(m_idx)
        txn_f.append(feats)
        uf.append(user_table[u_idx])
        mf.append(merchant_table[m_idx])
        unf.append(gather_neighbor_features(merchant_table, un, un_mask))
        unm.append(un_mask)
        mnf.append(gather_neighbor_features(user_table, mn, mn_mask))
        mnm.append(mn_mask)
        labels.append(lab["is_fraud"])
        graph.add_edges(u_idx, m_idx)  # edges visible to FUTURE batches only
    cat = lambda xs: np.concatenate(xs, axis=0)  # noqa: E731
    return (
        (cat(txn_f), cat(uf), cat(mf), cat(unf), cat(unm), cat(mnf), cat(mnm)),
        cat(labels).astype(np.float32),
        (user_table, merchant_table, graph),
    )


# --------------------------------------------------------------------------
# convenience end-to-end trainers
# --------------------------------------------------------------------------

def train_lstm(
    generator, n_transactions: int = 50_000, seq_len: int = 10,
    hidden: int = 128, epochs: int = 3, seed: int = 0,
) -> Dict[str, jax.Array]:
    seqs, lens, labels = build_sequence_dataset(generator, n_transactions, seq_len)
    params = init_lstm_params(jax.random.PRNGKey(seed), seqs.shape[-1], hidden)

    def loss_fn(p, inputs, y):
        s, l = inputs
        return bce_loss(lstm_logits(p, s, l), y)

    return NeuralTrainer(epochs=epochs, seed=seed).train(
        params, loss_fn, (seqs, lens), labels
    )


def train_gnn(
    generator, n_transactions: int = 50_000, fanout: int = 16,
    node_dim: int = 16, hidden: int = 64, epochs: int = 3, seed: int = 0,
):
    inputs, labels, (user_table, merchant_table, graph) = build_graph_dataset(
        generator, n_transactions, fanout, node_dim
    )
    params = init_gnn_params(
        jax.random.PRNGKey(seed), node_dim, inputs[0].shape[-1], hidden
    )

    def loss_fn(p, batch_inputs, y):
        return bce_loss(gnn_logits(p, *batch_inputs), y)

    params = NeuralTrainer(epochs=epochs, seed=seed).train(
        params, loss_fn, inputs, labels
    )
    return params, user_table, merchant_table, graph

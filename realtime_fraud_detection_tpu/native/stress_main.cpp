// ThreadSanitizer stress harness for the microbatcher queue.
//
// The reference has known unguarded RMW races in its Redis sinks
// (SURVEY.md §5.2); our native data plane is instead validated under TSAN:
// build with -fsanitize=thread and run — any data race aborts with a report.
//
//   g++ -O1 -g -std=c++17 -fsanitize=thread -pthread \
//       stress_main.cpp -o stress_tsan && ./stress_tsan
//
// Exit code 0 + "OK <count>" on stdout means every record produced by the 8
// producer threads was consumed exactly once with no races detected.

#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "microbatcher.cpp"

int main() {
  const int n_threads = 8, per_thread = 2000;
  void *q = mb_create(1024, 64, 128, 1.0);

  std::vector<std::thread> producers;
  for (int t = 0; t < n_threads; ++t) {
    producers.emplace_back([q, t] {
      char buf[64];
      for (int i = 0; i < per_thread; ++i) {
        int len = std::snprintf(buf, sizeof buf, "%d:%d", t, i);
        while (mb_push(q, buf, (uint32_t)len) != 0) std::this_thread::yield();
      }
    });
  }

  std::vector<char> seen(n_threads * per_thread, 0);
  char out[64 * 128];
  uint32_t lens[128];
  long consumed = 0, dups = 0;
  while (consumed < (long)n_threads * per_thread) {
    int n = mb_next_batch(q, out, sizeof out, lens, 50);
    size_t off = 0;
    for (int i = 0; i < n; ++i) {
      std::string rec(out + off, lens[i]);
      off += lens[i];
      int tid, idx;
      std::sscanf(rec.c_str(), "%d:%d", &tid, &idx);
      int key = tid * per_thread + idx;
      if (seen[key]) ++dups;
      seen[key] = 1;
      ++consumed;
    }
  }
  for (auto &p : producers) p.join();
  mb_destroy(q);
  if (dups) {
    std::printf("FAIL dups=%ld\n", dups);
    return 1;
  }
  std::printf("OK %ld\n", consumed);
  return 0;
}

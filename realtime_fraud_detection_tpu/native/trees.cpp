// Boosted-tree ensemble inference kernel (C ABI, OpenMP-free, threadable).
//
// The CPU-baseline twin of models/trees.py's tensorized traversal
// (SURVEY.md §2.9 component 2): the same complete-binary-tree layout
// (feature i32[T, 2^D-1], threshold f32[T, 2^D-1], leaf f32[T, 2^D], split
// rule x >= threshold goes RIGHT) traversed scalar-fashion per row. Gives
// the host a fast fallback scorer when no accelerator is attached (the
// reference served xgboost on CPU — model_manager.py:309-311) and an
// independent oracle for the JAX kernel's numerics.
//
// Exposed as a flat C ABI for ctypes (pybind11 is not in the image).

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Scores one batch: logits[b] = base + sum_t leaf[t][descend(t, x_b)].
// feature/threshold: [n_trees * n_internal]; leaf: [n_trees * n_leaf];
// x: [n_rows * n_features] row-major; out: [n_rows].
// depth = log2(n_leaf); n_internal = n_leaf - 1.
void trees_score(const int32_t* feature, const float* threshold,
                 const float* leaf, float base_score, int32_t n_trees,
                 int32_t depth, const float* x, int32_t n_rows,
                 int32_t n_features, float* out) {
  const int32_t n_internal = (1 << depth) - 1;
  const int32_t n_leaf = 1 << depth;
  for (int32_t r = 0; r < n_rows; ++r) {
    const float* row = x + static_cast<int64_t>(r) * n_features;
    float acc = base_score;
    for (int32_t t = 0; t < n_trees; ++t) {
      const int32_t* tf = feature + static_cast<int64_t>(t) * n_internal;
      const float* tt = threshold + static_cast<int64_t>(t) * n_internal;
      int32_t node = 0;
      for (int32_t d = 0; d < depth; ++d) {
        node = 2 * node + 1 + (row[tf[node]] >= tt[node] ? 1 : 0);
      }
      acc += leaf[static_cast<int64_t>(t) * n_leaf + (node - n_internal)];
    }
    out[r] = acc;
  }
}

// Multi-threaded variant: rows split across n_threads hardware threads.
void trees_score_mt(const int32_t* feature, const float* threshold,
                    const float* leaf, float base_score, int32_t n_trees,
                    int32_t depth, const float* x, int32_t n_rows,
                    int32_t n_features, float* out, int32_t n_threads) {
  if (n_threads <= 1 || n_rows < 2 * n_threads) {
    trees_score(feature, threshold, leaf, base_score, n_trees, depth, x,
                n_rows, n_features, out);
    return;
  }
  std::vector<std::thread> workers;
  const int32_t chunk = (n_rows + n_threads - 1) / n_threads;
  for (int32_t i = 0; i < n_threads; ++i) {
    const int32_t lo = i * chunk;
    const int32_t hi = std::min(n_rows, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([=] {
      trees_score(feature, threshold, leaf, base_score, n_trees, depth,
                  x + static_cast<int64_t>(lo) * n_features, hi - lo,
                  n_features, out + lo);
    });
  }
  for (auto& w : workers) w.join();
}

}  // extern "C"

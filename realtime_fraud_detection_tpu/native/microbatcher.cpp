// Native microbatcher: lock-free MPMC ring buffer + deadline batch assembler.
//
// TPU-native equivalent of the reference's latency-critical data plane (the
// Flink netty shuffle + the TF-Serving batching config that was never wired,
// reference k8s/manifests/ml-models-deployment.yaml:270-290). Producers are
// ingest threads (transport consumers / HTTP handlers); the single logical
// consumer is the scoring loop, which drains fixed-deadline microbatches into
// pinned host buffers for device transfer.
//
// Queue algorithm: bounded MPMC with per-slot sequence counters (Vyukov).
// Each push/pop is one CAS + one release store; no locks anywhere on the
// hot path. Batch close condition mirrors stream/microbatch.py: size reached
// OR max_delay elapsed since the oldest pending record.
//
// C ABI only (consumed via ctypes; pybind11 is not in this image).

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <thread>

namespace {

using Clock = std::chrono::steady_clock;

struct Slot {
  std::atomic<uint64_t> seq;
  uint32_t len;
  double enq_time;  // seconds since queue creation
  char *payload;
};

struct Queue {
  Slot *slots;
  size_t capacity;       // power of two
  size_t slot_bytes;     // max payload per record
  size_t max_batch;
  double max_delay_s;
  Clock::time_point t0;
  alignas(64) std::atomic<uint64_t> head;  // next push ticket
  alignas(64) std::atomic<uint64_t> tail;  // next pop ticket
  alignas(64) std::atomic<uint64_t> batches;
  std::atomic<uint64_t> records;
  std::atomic<uint64_t> dropped;

  double now() const {
    return std::chrono::duration<double>(Clock::now() - t0).count();
  }
};

size_t round_pow2(size_t v) {
  size_t p = 1;
  while (p < v) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

void *mb_create(size_t capacity, size_t slot_bytes, size_t max_batch,
                double max_delay_ms) {
  auto *q = new Queue();
  q->capacity = round_pow2(capacity < 2 ? 2 : capacity);
  q->slot_bytes = slot_bytes;
  q->max_batch = max_batch;
  q->max_delay_s = max_delay_ms / 1000.0;
  q->t0 = Clock::now();
  q->slots = new Slot[q->capacity];
  for (size_t i = 0; i < q->capacity; ++i) {
    q->slots[i].seq.store(i, std::memory_order_relaxed);
    q->slots[i].payload = new char[slot_bytes];
    q->slots[i].len = 0;
  }
  q->head.store(0, std::memory_order_relaxed);
  q->tail.store(0, std::memory_order_relaxed);
  q->batches.store(0, std::memory_order_relaxed);
  q->records.store(0, std::memory_order_relaxed);
  q->dropped.store(0, std::memory_order_relaxed);
  return q;
}

void mb_destroy(void *handle) {
  auto *q = static_cast<Queue *>(handle);
  for (size_t i = 0; i < q->capacity; ++i) delete[] q->slots[i].payload;
  delete[] q->slots;
  delete q;
}

// 0 = ok, -1 = queue full, -2 = payload too large.
int mb_push(void *handle, const char *data, uint32_t len) {
  auto *q = static_cast<Queue *>(handle);
  if (len > q->slot_bytes) return -2;
  uint64_t pos = q->head.load(std::memory_order_relaxed);
  for (;;) {
    Slot &s = q->slots[pos & (q->capacity - 1)];
    uint64_t seq = s.seq.load(std::memory_order_acquire);
    intptr_t dif = (intptr_t)seq - (intptr_t)pos;
    if (dif == 0) {
      if (q->head.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
        std::memcpy(s.payload, data, len);
        s.len = len;
        s.enq_time = q->now();
        s.seq.store(pos + 1, std::memory_order_release);
        return 0;
      }
    } else if (dif < 0) {
      q->dropped.fetch_add(1, std::memory_order_relaxed);
      return -1;  // full
    } else {
      pos = q->head.load(std::memory_order_relaxed);
    }
  }
}

// Pop exactly one record if available. Returns len, or -1 if empty.
static int pop_one(Queue *q, char *out, double *enq_time) {
  uint64_t pos = q->tail.load(std::memory_order_relaxed);
  for (;;) {
    Slot &s = q->slots[pos & (q->capacity - 1)];
    uint64_t seq = s.seq.load(std::memory_order_acquire);
    intptr_t dif = (intptr_t)seq - (intptr_t)(pos + 1);
    if (dif == 0) {
      if (q->tail.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
        uint32_t len = s.len;
        std::memcpy(out, s.payload, len);
        if (enq_time) *enq_time = s.enq_time;
        s.seq.store(pos + q->capacity, std::memory_order_release);
        return (int)len;
      }
    } else if (dif < 0) {
      return -1;  // empty
    } else {
      pos = q->tail.load(std::memory_order_relaxed);
    }
  }
}

size_t mb_pending(void *handle) {
  auto *q = static_cast<Queue *>(handle);
  uint64_t h = q->head.load(std::memory_order_acquire);
  uint64_t t = q->tail.load(std::memory_order_acquire);
  return h > t ? (size_t)(h - t) : 0;
}

// Peek the enqueue time of the oldest pending record. Single-consumer only.
// Returns false when the queue is empty (or the slot is mid-write).
static bool peek_oldest(Queue *q, double *enq_time) {
  uint64_t pos = q->tail.load(std::memory_order_relaxed);
  Slot &s = q->slots[pos & (q->capacity - 1)];
  if (s.seq.load(std::memory_order_acquire) != pos + 1) return false;
  *enq_time = s.enq_time;
  return true;
}

// Assemble the next microbatch into out_buf (concatenated payloads) +
// out_lens (per-record byte lengths). Returns the record count.
//
// Close conditions (same contract as stream/microbatch.py): the batch only
// opens once `max_batch` records are pending OR the oldest pending record is
// older than `max_delay`; with a block budget (block_ms > 0) an expiring
// budget flushes whatever is pending. block_ms=0 -> strict non-blocking:
// returns 0 until a close condition holds.
int mb_next_batch(void *handle, char *out_buf, size_t out_cap,
                  uint32_t *out_lens, int block_ms) {
  auto *q = static_cast<Queue *>(handle);
  double deadline_wall = q->now() + block_ms / 1000.0;
  bool flush = false;
  for (;;) {
    double oldest;
    bool have = peek_oldest(q, &oldest);
    bool size_ready = mb_pending(handle) >= q->max_batch;
    bool deadline_ready = have && (q->now() - oldest) >= q->max_delay_s;
    if (size_ready || deadline_ready || (flush && have)) break;
    if (q->now() >= deadline_wall) {
      if (block_ms <= 0 || !have) return 0;
      flush = true;  // budget exhausted: flush pending
    } else {
      std::this_thread::yield();
    }
  }
  size_t n = 0, used = 0;
  while (n < q->max_batch && used + q->slot_bytes <= out_cap) {
    double enq;
    int len = pop_one(q, out_buf + used, &enq);
    if (len < 0) break;
    out_lens[n++] = (uint32_t)len;
    used += (size_t)len;
  }
  if (n > 0) {
    q->batches.fetch_add(1, std::memory_order_relaxed);
    q->records.fetch_add(n, std::memory_order_relaxed);
  }
  return (int)n;
}

uint64_t mb_stat_batches(void *h) {
  return static_cast<Queue *>(h)->batches.load(std::memory_order_relaxed);
}
uint64_t mb_stat_records(void *h) {
  return static_cast<Queue *>(h)->records.load(std::memory_order_relaxed);
}
uint64_t mb_stat_dropped(void *h) {
  return static_cast<Queue *>(h)->dropped.load(std::memory_order_relaxed);
}

}  // extern "C"

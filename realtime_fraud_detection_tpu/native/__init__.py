"""ctypes bindings for the native (C++) microbatcher.

Builds ``microbatcher.cpp`` on demand with g++ (pybind11 is not in this
image; the C ABI + ctypes keeps the dependency surface at zero). The build
is cached next to the source keyed on its mtime; set
``RTFD_DISABLE_NATIVE=1`` to force the pure-Python assembler.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional

_DIR = Path(__file__).resolve().parent
_SRC = _DIR / "microbatcher.cpp"
_LIB = _DIR / "_microbatcher.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _compile_native(src: Path, lib_path: Path) -> tuple[Optional[ctypes.CDLL], Optional[str]]:
    """Shared on-demand g++ build: env-var gate, mtime cache, one compiler
    recipe for every native kernel in this package. Returns (lib, error)."""
    if os.environ.get("RTFD_DISABLE_NATIVE") == "1":
        return None, "disabled via RTFD_DISABLE_NATIVE"
    try:
        if not lib_path.exists() or lib_path.stat().st_mtime < src.stat().st_mtime:
            cmd = [
                "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
                str(src), "-o", str(lib_path),
            ]
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return ctypes.CDLL(str(lib_path)), None
    except (OSError, subprocess.SubprocessError) as e:
        return None, str(e)


def _build() -> Optional[ctypes.CDLL]:
    global _build_error
    lib, _build_error = _compile_native(_SRC, _LIB)
    if lib is None:
        return None

    lib.mb_create.restype = ctypes.c_void_p
    lib.mb_create.argtypes = [ctypes.c_size_t, ctypes.c_size_t,
                              ctypes.c_size_t, ctypes.c_double]
    lib.mb_destroy.argtypes = [ctypes.c_void_p]
    lib.mb_push.restype = ctypes.c_int
    lib.mb_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.mb_pending.restype = ctypes.c_size_t
    lib.mb_pending.argtypes = [ctypes.c_void_p]
    lib.mb_next_batch.restype = ctypes.c_int
    lib.mb_next_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int,
    ]
    for name in ("mb_stat_batches", "mb_stat_records", "mb_stat_dropped"):
        fn = getattr(lib, name)
        fn.restype = ctypes.c_uint64
        fn.argtypes = [ctypes.c_void_p]
    return lib


def native_available() -> bool:
    return _get_lib() is not None


def native_build_error() -> Optional[str]:
    _get_lib()
    return _build_error


def _get_lib() -> Optional[ctypes.CDLL]:
    global _lib
    with _lock:
        if _lib is None and _build_error is None:
            _lib = _build()
        return _lib


class NativeMicrobatchQueue:
    """Lock-free MPMC ingest queue + deadline microbatcher (C++ backed).

    Same close-condition contract as stream.microbatch.MicrobatchAssembler:
    a batch closes when it reaches ``max_batch`` or when ``max_delay_ms`` has
    passed since its oldest record was enqueued.
    """

    def __init__(self, capacity: int = 4096, slot_bytes: int = 4096,
                 max_batch: int = 256, max_delay_ms: float = 5.0):
        lib = _get_lib()
        if lib is None:
            raise RuntimeError(f"native microbatcher unavailable: {_build_error}")
        self._lib = lib
        self.slot_bytes = slot_bytes
        self.max_batch = max_batch
        self._q = ctypes.c_void_p(lib.mb_create(
            capacity, slot_bytes, max_batch, max_delay_ms
        ))
        self._out_buf = ctypes.create_string_buffer(slot_bytes * max_batch)
        self._out_lens = (ctypes.c_uint32 * max_batch)()

    def _handle(self) -> ctypes.c_void_p:
        if not self._q:
            raise ValueError("queue is closed")
        return self._q

    def push(self, payload: bytes) -> bool:
        """Enqueue one record; False when the ring is full (backpressure)."""
        rc = self._lib.mb_push(self._handle(), payload, len(payload))
        if rc == -2:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds slot size {self.slot_bytes}"
            )
        return rc == 0

    def next_batch(self, block_ms: int = 0) -> List[bytes]:
        n = self._lib.mb_next_batch(
            self._handle(), self._out_buf, len(self._out_buf), self._out_lens,
            block_ms,
        )
        if n <= 0:
            return []
        used = sum(self._out_lens[i] for i in range(n))
        raw = ctypes.string_at(self._out_buf, used)  # copy used prefix only
        out: List[bytes] = []
        off = 0
        for i in range(n):
            ln = self._out_lens[i]
            out.append(raw[off:off + ln])
            off += ln
        return out

    def pending(self) -> int:
        return int(self._lib.mb_pending(self._handle()))

    def stats(self) -> dict:
        h = self._handle()
        return {
            "batches": int(self._lib.mb_stat_batches(h)),
            "records": int(self._lib.mb_stat_records(h)),
            "dropped": int(self._lib.mb_stat_dropped(h)),
        }

    def close(self) -> None:
        if self._q:
            self._lib.mb_destroy(self._q)
            self._q = ctypes.c_void_p(None)

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


# ------------------------------------------------------------------- trees
_TREES_SRC = _DIR / "trees.cpp"
_TREES_LIB = _DIR / "_trees.so"
_trees_lib: Optional[ctypes.CDLL] = None
_trees_error: Optional[str] = None


def _build_trees() -> Optional[ctypes.CDLL]:
    global _trees_error
    lib, _trees_error = _compile_native(_TREES_SRC, _TREES_LIB)
    if lib is None:
        return None
    import numpy as np
    from numpy.ctypeslib import ndpointer

    lib.trees_score_mt.restype = None
    lib.trees_score_mt.argtypes = [
        ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_float, ctypes.c_int32, ctypes.c_int32,
        ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int32, ctypes.c_int32,
        ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_int32,
    ]
    return lib


def _get_trees_lib() -> Optional[ctypes.CDLL]:
    global _trees_lib
    with _lock:
        if _trees_lib is None and _trees_error is None:
            _trees_lib = _build_trees()
        return _trees_lib


def native_trees_available() -> bool:
    return _get_trees_lib() is not None


class NativeTreeScorer:
    """C++ boosted-tree inference over the framework's complete-binary-tree
    layout (models/trees.py TreeEnsemble) — the CPU-baseline scorer twin of
    the TPU tensorized traversal (SURVEY.md §2.9 component 2) and an
    independent numerics oracle for it.
    """

    def __init__(self, ensemble, n_threads: int = 0):
        import numpy as np

        lib = _get_trees_lib()
        if lib is None:
            raise RuntimeError(f"native tree scorer unavailable: {_trees_error}")
        self._lib = lib
        self.feature = np.ascontiguousarray(
            np.asarray(ensemble.feature), np.int32)
        self.threshold = np.ascontiguousarray(
            np.asarray(ensemble.threshold), np.float32)
        self.leaf = np.ascontiguousarray(np.asarray(ensemble.leaf), np.float32)
        self.base_score = float(np.asarray(ensemble.base_score))
        self.n_trees = self.feature.shape[0]
        self.depth = int(self.leaf.shape[1]).bit_length() - 1
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)
        # widest feature index any split touches: inputs narrower than this
        # would make the C++ kernel read out of bounds
        self.min_features = int(self.feature.max()) + 1 if self.n_trees else 0

    def logits(self, x):
        import numpy as np

        x = np.ascontiguousarray(np.asarray(x), np.float32)
        if x.ndim != 2 or x.shape[1] < self.min_features:
            raise ValueError(
                f"need f32[B, >= {self.min_features}] features, got {x.shape}")
        out = np.empty((x.shape[0],), np.float32)
        self._lib.trees_score_mt(
            self.feature, self.threshold, self.leaf, self.base_score,
            self.n_trees, self.depth, x, x.shape[0], x.shape[1], out,
            self.n_threads)
        return out

    def predict(self, x):
        """Fraud probability: sigmoid(logits), matching
        models.trees.tree_ensemble_predict."""
        import numpy as np

        return 1.0 / (1.0 + np.exp(-self.logits(x)))

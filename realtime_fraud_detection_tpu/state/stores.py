"""Windowed state stores — the Redis data plane, in-process.

Mirrors the reference's Redis key schema (RedisService.java:36-49):
``user:{id}`` / ``merchant:{id}`` profile hashes, ``transaction:{id}`` cache
(TTL 24h), ``user_transactions:{id}`` last-100 list, ``velocity:{user}:
{5min|1hour|24hour}`` counters, ``agg:{key}`` aggregations — plus the sink's
update logic (RedisTransactionSink.java:87-262).

Two defects of the reference are fixed by design:

1. **RMW races** (SURVEY.md 5.2): the reference GET-then-SETs velocity and
   aggregation values from 12 parallel Flink subtasks. Here every store
   mutation happens on the single ingest thread that owns the key range
   (single-writer-per-key); stores are plain dicts with no locks to contend.
2. **Velocity TTL bug**: the reference gives all three windows a 1-hour key
   TTL (RedisService.java:178-207), so its "24hour" window silently resets
   after an hour of inactivity. Here each window resets on its own period.

A Redis-backed implementation can slot behind ``StateBackend`` when the
``redis`` client is available; this process-local backend is the default and
the one the TPU scorer uses (state lives with the microbatcher, not across a
network hop in the hot loop).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Protocol, Tuple

VELOCITY_WINDOWS: dict[str, float] = {"5min": 300.0, "1hour": 3600.0, "24hour": 86400.0}


def _event_time_ms(txn: Mapping[str, Any], now: float | None) -> float:
    """Event time in ms: explicit timestamp_ms, else the simulator's ISO
    'timestamp' string, else wall clock / ``now``."""
    if "timestamp_ms" in txn:
        return float(txn["timestamp_ms"])
    ts = txn.get("timestamp")
    if isinstance(ts, str) and ts:
        from datetime import datetime

        try:
            return datetime.fromisoformat(ts).timestamp() * 1000.0
        except ValueError:
            pass
    return (now if now is not None else time.time()) * 1000.0


class StateBackend(Protocol):
    """Minimal protocol all state stores are built over."""

    def get(self, key: str) -> Any: ...
    def put(self, key: str, value: Any, ttl_s: float | None = None) -> None: ...
    def delete(self, key: str) -> None: ...


class _MemoryBackend:
    """Dict backend with lazy TTL expiry (single-writer discipline)."""

    def __init__(self) -> None:
        self._data: Dict[str, Tuple[Any, float | None]] = {}

    def get(self, key: str, now: float | None = None) -> Any:
        item = self._data.get(key)
        if item is None:
            return None
        value, expires = item
        if expires is not None and (now if now is not None else time.time()) >= expires:
            del self._data[key]
            return None
        return value

    def put(self, key: str, value: Any, ttl_s: float | None = None,
            now: float | None = None) -> None:
        expires = None
        if ttl_s is not None:
            expires = (now if now is not None else time.time()) + ttl_s
        self._data[key] = (value, expires)

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def __len__(self) -> int:
        return len(self._data)


class VelocityStore:
    """Per-user transaction velocity over 5min/1hour/24hour windows.

    Update semantics follow RedisTransactionSink.updateVelocityWindow
    (:116-135): read current (count, amount), add, store — except each window
    resets when its own period has elapsed since the window started.
    """

    def __init__(self) -> None:
        # (user_id, window) -> [count, amount, window_start]
        self._state: Dict[Tuple[str, str], List[float]] = {}
        # stream time: the latest `now` any update has seen; reads that omit
        # `now` expire against this clock (keeps virtual/sim clocks coherent)
        self._clock: float = 0.0

    def update(self, user_id: str, amount: float, now: float) -> None:
        self._clock = max(self._clock, now)
        for window, period in VELOCITY_WINDOWS.items():
            key = (user_id, window)
            cur = self._state.get(key)
            if cur is None or now - cur[2] >= period:
                self._state[key] = [1, amount, now]
            else:
                cur[0] += 1
                cur[1] += amount

    def update_batch(self, user_ids: Iterable[str], amounts: Iterable[float],
                     now: float) -> None:
        for uid, amt in zip(user_ids, amounts):
            self.update(uid, float(amt), now)

    def get(self, user_id: str, window: str, now: float | None = None) -> Dict[str, float]:
        """Velocity metrics dict (RedisService.getVelocityMetrics shape).

        Expiry always applies: against ``now`` when given, else against the
        stream clock (latest update time seen).
        """
        cur = self._state.get((user_id, window))
        if cur is None:
            return {}
        if (now if now is not None else self._clock) - cur[2] >= VELOCITY_WINDOWS[window]:
            return {}
        return {"count": cur[0], "amount": cur[1], "timestamp": cur[2]}

    def get_all(self, user_id: str, now: float | None = None) -> Dict[str, Dict[str, float]]:
        return {w: self.get(user_id, w, now) for w in VELOCITY_WINDOWS}

    def entries(self) -> List[Tuple[str, str, float, float, float]]:
        """Sorted raw window rows ``(user_id, window, count, amount,
        window_start)`` — the public content accessor the partition plane
        (cluster/partition.py) digests for state-equality checks, so
        nothing outside this module reaches into ``_state``."""
        return sorted((uid, w, float(v[0]), float(v[1]), float(v[2]))
                      for (uid, w), v in self._state.items())

    def __len__(self) -> int:
        return len(self._state)


class ProfileStore:
    """User + merchant profile store (``user:{id}`` / ``merchant:{id}``).

    ``generation`` stamps every write: derived per-entity caches (the
    columnar encoder's join-row cache, features/schema.EntityRowCache)
    compare their stamp against it and drop stale rows instead of serving
    a profile that has since been rewritten. The shared RESP-backed store
    (state/shared.SharedProfileStore) deliberately has NO generation —
    remote writers are invisible to this process, so caching over it
    would be wrong and callers must check for the attribute.
    """

    def __init__(self) -> None:
        self.users: Dict[str, Mapping[str, Any]] = {}
        self.merchants: Dict[str, Mapping[str, Any]] = {}
        self.generation: int = 0

    def __setstate__(self, state) -> None:
        # checkpoint migration: host state is pickled object instances
        # (checkpoint.py), and pre-host-plane snapshots lack ``generation``
        self.__dict__.update(state)
        if "generation" not in state:
            self.generation = 0

    def seed(self, users: Mapping[str, Mapping[str, Any]] | None = None,
             merchants: Mapping[str, Mapping[str, Any]] | None = None) -> None:
        """Bulk-load profiles (the simulator's Redis seeding path,
        simulator.py:243-294)."""
        if users:
            self.users.update(users)
        if merchants:
            self.merchants.update(merchants)
        if users or merchants:
            self.generation += 1

    def get_user(self, user_id: str) -> Optional[Mapping[str, Any]]:
        return self.users.get(user_id)

    def get_merchant(self, merchant_id: str) -> Optional[Mapping[str, Any]]:
        return self.merchants.get(merchant_id)

    def put_user(self, user_id: str, profile: Mapping[str, Any]) -> None:
        self.users[user_id] = profile
        self.generation += 1

    def put_merchant(self, merchant_id: str, profile: Mapping[str, Any]) -> None:
        self.merchants[merchant_id] = profile
        self.generation += 1


class TransactionCache:
    """Recent transactions + per-entity id lists (RedisService.java:127-171,
    296-321): ``transaction:{id}`` TTL 24h, ``user_transactions`` last-100,
    ``merchant_transactions`` last-500, ``features:{id}`` TTL 2h.
    """

    def __init__(self, txn_ttl_s: float = 24 * 3600, features_ttl_s: float = 2 * 3600,
                 user_list_len: int = 100, merchant_list_len: int = 500) -> None:
        self._backend = _MemoryBackend()
        self.txn_ttl_s = txn_ttl_s
        self.features_ttl_s = features_ttl_s
        self.user_list_len = user_list_len
        self.merchant_list_len = merchant_list_len
        self._user_lists: Dict[str, List[str]] = {}
        self._merchant_lists: Dict[str, List[str]] = {}

    def cache_transaction(self, txn: Mapping[str, Any], now: float | None = None) -> None:
        tid = str(txn.get("transaction_id"))
        self._backend.put(f"transaction:{tid}", dict(txn), self.txn_ttl_s, now)
        uid, mid = str(txn.get("user_id")), str(txn.get("merchant_id"))
        ul = self._user_lists.setdefault(uid, [])
        ul.insert(0, tid)
        del ul[self.user_list_len:]
        ml = self._merchant_lists.setdefault(mid, [])
        ml.insert(0, tid)
        del ml[self.merchant_list_len:]

    def get_transaction(self, txn_id: str, now: float | None = None) -> Any:
        return self._backend.get(f"transaction:{txn_id}", now)

    def store_features(self, txn_id: str, features: Any, now: float | None = None) -> None:
        self._backend.put(f"features:{txn_id}", features, self.features_ttl_s, now)

    def get_features(self, txn_id: str, now: float | None = None) -> Any:
        return self._backend.get(f"features:{txn_id}", now)

    def entries(self, now: float | None = None) -> List[Tuple[str, Any]]:
        """Sorted live ``(transaction_id, cached_txn)`` pairs (expired
        entries excluded against ``now`` when given). Content accessor
        for the partition plane's state digests — the cache's dedupe
        semantics stay behind get/cache_transaction."""
        out = []
        for key in sorted(self._backend._data):
            if not key.startswith("transaction:"):
                continue
            value = self._backend.get(key, now)
            if value is not None:
                out.append((key[len("transaction:"):], value))
        return out

    def get_user_transactions(self, user_id: str, limit: int = 100) -> List[str]:
        return self._user_lists.get(user_id, [])[:limit]

    def get_merchant_transactions(self, merchant_id: str, limit: int = 500) -> List[str]:
        return self._merchant_lists.get(merchant_id, [])[:limit]


class AggregationStore:
    """Hourly / daily / per-merchant rolling aggregations
    (RedisTransactionSink.java:140-262): total_count, total_amount,
    fraud_count, high_risk_count, fraud_rate, avg_amount per bucket.
    """

    def __init__(self, ttl_s: float = 1800.0) -> None:
        self._backend = _MemoryBackend()
        self.ttl_s = ttl_s

    def record(self, txn: Mapping[str, Any], now: float | None = None) -> None:
        ts_ms = _event_time_ms(txn, now)
        hour_key = int(ts_ms // 3_600_000)
        day_key = int(ts_ms // 86_400_000)
        amount = float(txn.get("amount", 0.0))
        is_fraud = bool(txn.get("is_fraud", False))
        high_risk = float(txn.get("fraud_score", 0.0)) > 0.7
        for key in (f"hourly:{hour_key}", f"daily:{day_key}",
                    f"merchant:{txn.get('merchant_id')}:{hour_key}"):
            self._update(key, amount, is_fraud, high_risk, now)

    def _update(self, key: str, amount: float, is_fraud: bool, high_risk: bool,
                now: float | None) -> None:
        agg = self._backend.get(f"agg:{key}", now) or {
            "total_count": 0, "total_amount": 0.0, "fraud_count": 0,
            "high_risk_count": 0,
        }
        agg["total_count"] += 1
        agg["total_amount"] += amount
        agg["fraud_count"] += int(is_fraud)
        agg["high_risk_count"] += int(high_risk)
        agg["fraud_rate"] = agg["fraud_count"] / agg["total_count"]
        agg["avg_amount"] = agg["total_amount"] / agg["total_count"]
        self._backend.put(f"agg:{key}", agg, self.ttl_s, now)

    def get(self, key: str, now: float | None = None) -> Dict[str, Any]:
        return self._backend.get(f"agg:{key}", now) or {}

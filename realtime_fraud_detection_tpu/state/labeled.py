"""Bounded labeled-example buffer: the training-side state of the
continuous-learning plane (feedback/).

Holds (feature_row, label, served_score, per-branch predictions, optional
LSTM history) tuples produced by the label join (feedback/labels.py) so a
background retrain (feedback/policy.Retrainer) always has a recent,
bounded, class-aware corpus:

- **Bounded**: hard capacity; memory never grows with stream length.
- **Class-aware retention**: fraud labels are ~5% of the stream and the
  whole point of retraining, so positives and negatives evict on separate
  FIFO rings (positives get ``capacity // 5`` slots — at a 5% fraud rate
  that retains positives ~5x longer than a single shared ring would).
- **Chronological reads**: ``arrays()`` returns time-ordered views so the
  retrain/gate split ("train on the past, gate on the most recent") is a
  simple index cut.

Single-writer discipline, same as the other stores in this package.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

__all__ = ["LabeledExampleBuffer"]


class LabeledExampleBuffer:
    """FIFO labeled-example store with per-class eviction rings."""

    def __init__(self, capacity: int = 50_000,
                 store_history: bool = False) -> None:
        if capacity < 10:
            raise ValueError(f"capacity must be >= 10, got {capacity}")
        self.capacity = int(capacity)
        self.store_history = bool(store_history)
        pos_cap = max(self.capacity // 5, 5)
        self._pos: deque = deque(maxlen=pos_cap)
        self._neg: deque = deque(maxlen=self.capacity - pos_cap)
        self.appended = 0
        self.evicted = 0

    def append(self, features: np.ndarray, label: bool, score: float,
               ts: float,
               branch_preds: Optional[Mapping[str, float]] = None,
               history: Optional[np.ndarray] = None,
               history_len: Optional[int] = None) -> None:
        ring = self._pos if label else self._neg
        if len(ring) == ring.maxlen:
            self.evicted += 1
        item = {
            "features": np.asarray(features, np.float32),
            "label": bool(label),
            "score": float(score),
            "ts": float(ts),
            "branch_preds": dict(branch_preds or {}),
        }
        if self.store_history and history is not None:
            item["history"] = np.asarray(history, np.float32)
            item["history_len"] = int(history_len or 0)
        ring.append(item)
        self.appended += 1

    # ------------------------------------------------------------------ reads
    def __len__(self) -> int:
        return len(self._pos) + len(self._neg)

    @property
    def positives(self) -> int:
        return len(self._pos)

    @property
    def negatives(self) -> int:
        return len(self._neg)

    def snapshot_rows(self) -> List[Dict[str, Any]]:
        """Shallow O(n) copy of the live rows — the ONLY part a concurrent
        writer's lock needs to cover. Hand the result to ``arrays_from``
        outside the lock for the expensive sort + stack (the serving app's
        retrain thread does exactly this so a 50k-row snapshot never
        stalls scoring)."""
        return list(self._pos) + list(self._neg)

    def _items_by_time(self) -> List[Dict[str, Any]]:
        return sorted(self.snapshot_rows(), key=lambda it: it["ts"])

    def arrays(self) -> Dict[str, np.ndarray]:
        """Time-ordered columns: ``x`` f32[N, F], ``y`` f32[N], ``score``
        f32[N], ``ts`` f64[N] (+ ``history``/``history_len`` when stored).
        Empty buffer returns zero-length arrays. Single-writer callers
        only — for cross-thread use take ``snapshot_rows`` under the
        writer's lock and build with ``arrays_from``."""
        return self.arrays_from(self.snapshot_rows(), self.store_history)

    @staticmethod
    def arrays_from(rows: List[Dict[str, Any]],
                    store_history: bool = False) -> Dict[str, np.ndarray]:
        items = sorted(rows, key=lambda it: it["ts"])
        if not items:
            out = {"x": np.zeros((0, 0), np.float32),
                   "y": np.zeros((0,), np.float32),
                   "score": np.zeros((0,), np.float32),
                   "ts": np.zeros((0,), np.float64)}
            if store_history:
                out["history"] = np.zeros((0, 0, 0), np.float32)
                out["history_len"] = np.zeros((0,), np.int32)
            return out
        out = {
            "x": np.stack([it["features"] for it in items]),
            "y": np.asarray([it["label"] for it in items], np.float32),
            "score": np.asarray([it["score"] for it in items], np.float32),
            "ts": np.asarray([it["ts"] for it in items], np.float64),
        }
        if store_history and "history" in items[0]:
            out["history"] = np.stack([it["history"] for it in items])
            out["history_len"] = np.asarray(
                [it["history_len"] for it in items], np.int32)
        return out

    def branch_preds(self) -> List[Dict[str, float]]:
        """Per-example branch predictions, time-ordered (same order as
        ``arrays()``)."""
        return [it["branch_preds"] for it in self._items_by_time()]

    def stats(self) -> Dict[str, Any]:
        return {
            "size": len(self),
            "positives": self.positives,
            "negatives": self.negatives,
            "capacity": self.capacity,
            "appended": self.appended,
            "evicted": self.evicted,
        }

"""Durable metadata store (SQLite): jobs, checkpoints, features, profiles.

The reference ships a three-database Postgres schema — flink_metadata
(jobs/checkpoints/savepoints), feature_store (groups/features/values with
JSONB + TTL), user_profiles (users/merchants/...) — that NOTHING in its code
ever reads or writes (docker/postgres/init.sql; JDBC configured in
JobConfig.java:27-31 but never exercised — SURVEY.md §2.5 "schema-as-
intent"). Here the same intent is implemented: a single-file SQLite store
(stdlib, no service dependency) that the job/checkpoint layer actually
records into, and that persists feature values and profiles durably.

Schema mirrors init.sql's tables, renamed for this framework:

    jobs(job_id, job_name, status, start/end, parallelism)     init.sql:22-32
    checkpoints(step, job_id, path, size, duration, status)    init.sql:34-45
    feature_groups / features / feature_values (JSON + TTL)    init.sql:59-91
    user_profiles / merchant_profiles (JSON documents)         init.sql:100-150

Timestamps are float epoch seconds. JSON columns hold ``json.dumps`` text.
Thread-safety: one connection per store, guarded by a lock (SQLite's own
serialization plus a Python-side mutex for multi-statement operations).
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Mapping, Optional

__all__ = ["MetadataStore"]

_SCHEMA = """
CREATE TABLE IF NOT EXISTS jobs (
    job_id TEXT PRIMARY KEY,
    job_name TEXT NOT NULL,
    status TEXT NOT NULL,
    start_time REAL,
    end_time REAL,
    parallelism INTEGER,
    checkpoints_enabled INTEGER DEFAULT 1,
    created_at REAL,
    updated_at REAL
);
CREATE TABLE IF NOT EXISTS checkpoints (
    step INTEGER,
    job_id TEXT,
    path TEXT,
    size_bytes INTEGER,
    duration_ms REAL,
    status TEXT,
    trigger_time REAL,
    completion_time REAL,
    PRIMARY KEY (job_id, step)
);
CREATE TABLE IF NOT EXISTS feature_groups (
    name TEXT PRIMARY KEY,
    description TEXT,
    version TEXT,
    schema_json TEXT,
    created_at REAL,
    updated_at REAL
);
CREATE TABLE IF NOT EXISTS features (
    name TEXT PRIMARY KEY,
    feature_group TEXT,
    data_type TEXT,
    description TEXT,
    created_at REAL
);
CREATE TABLE IF NOT EXISTS feature_values (
    entity_type TEXT,
    entity_id TEXT,
    values_json TEXT,
    event_time REAL,
    ingestion_time REAL,
    ttl_time REAL,
    PRIMARY KEY (entity_type, entity_id)
);
CREATE INDEX IF NOT EXISTS idx_feature_values_ttl
    ON feature_values(ttl_time);
CREATE TABLE IF NOT EXISTS user_profiles (
    user_id TEXT PRIMARY KEY,
    profile_json TEXT,
    updated_at REAL
);
CREATE TABLE IF NOT EXISTS merchant_profiles (
    merchant_id TEXT PRIMARY KEY,
    profile_json TEXT,
    updated_at REAL
);
"""


class MetadataStore:
    """One SQLite file holding all durable framework metadata."""

    def __init__(self, path: str | Path = ":memory:"):
        self.path = str(path)
        if self.path != ":memory:":
            Path(self.path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.row_factory = sqlite3.Row
        self._lock = threading.Lock()
        with self._lock, self._conn:
            self._conn.executescript(_SCHEMA)

    def close(self) -> None:
        self._conn.close()

    # ------------------------------------------------------------------ jobs
    def register_job(self, job_id: str, job_name: str, parallelism: int = 1,
                     now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT INTO jobs (job_id, job_name, status, start_time,"
                " parallelism, created_at, updated_at)"
                " VALUES (?, ?, 'RUNNING', ?, ?, ?, ?)"
                " ON CONFLICT(job_id) DO UPDATE SET status='RUNNING',"
                " start_time=excluded.start_time, updated_at=excluded.updated_at",
                (job_id, job_name, ts, parallelism, ts, ts))

    def set_job_status(self, job_id: str, status: str,
                       now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        end = ts if status in ("FINISHED", "FAILED", "CANCELED") else None
        with self._lock, self._conn:
            self._conn.execute(
                "UPDATE jobs SET status=?, end_time=COALESCE(?, end_time),"
                " updated_at=? WHERE job_id=?",
                (status, end, ts, job_id))

    def get_job(self, job_id: str) -> Optional[Dict[str, Any]]:
        row = self._conn.execute(
            "SELECT * FROM jobs WHERE job_id=?", (job_id,)).fetchone()
        return dict(row) if row else None

    # ----------------------------------------------------------- checkpoints
    def record_checkpoint(self, job_id: str, step: int, path: str,
                          size_bytes: int = 0, duration_ms: float = 0.0,
                          status: str = "COMPLETED",
                          now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT INTO checkpoints (step, job_id, path, size_bytes,"
                " duration_ms, status, trigger_time, completion_time)"
                " VALUES (?, ?, ?, ?, ?, ?, ?, ?)"
                " ON CONFLICT(job_id, step) DO UPDATE SET path=excluded.path,"
                " size_bytes=excluded.size_bytes, status=excluded.status,"
                " completion_time=excluded.completion_time",
                (step, job_id, path, size_bytes, duration_ms, status, ts, ts))

    def checkpoints(self, job_id: str) -> List[Dict[str, Any]]:
        rows = self._conn.execute(
            "SELECT * FROM checkpoints WHERE job_id=? ORDER BY step",
            (job_id,)).fetchall()
        return [dict(r) for r in rows]

    def latest_checkpoint(self, job_id: str) -> Optional[Dict[str, Any]]:
        row = self._conn.execute(
            "SELECT * FROM checkpoints WHERE job_id=? AND status='COMPLETED'"
            " ORDER BY step DESC LIMIT 1", (job_id,)).fetchone()
        return dict(row) if row else None

    # -------------------------------------------------------------- features
    def register_feature_group(self, name: str, description: str = "",
                               version: str = "1.0",
                               schema: Optional[Mapping[str, Any]] = None,
                               now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT INTO feature_groups (name, description, version,"
                " schema_json, created_at, updated_at) VALUES (?, ?, ?, ?, ?, ?)"
                " ON CONFLICT(name) DO UPDATE SET description=excluded.description,"
                " version=excluded.version, schema_json=excluded.schema_json,"
                " updated_at=excluded.updated_at",
                (name, description, version,
                 json.dumps(dict(schema or {})), ts, ts))

    def register_feature(self, name: str, group: str = "default",
                         data_type: str = "NUMERICAL", description: str = "",
                         now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT OR REPLACE INTO features (name, feature_group,"
                " data_type, description, created_at) VALUES (?, ?, ?, ?, ?)",
                (name, group, data_type, description, ts))

    def feature_names(self, group: Optional[str] = None) -> List[str]:
        if group is None:
            rows = self._conn.execute("SELECT name FROM features").fetchall()
        else:
            rows = self._conn.execute(
                "SELECT name FROM features WHERE feature_group=?",
                (group,)).fetchall()
        return [r["name"] for r in rows]

    def put_feature_values(self, entity_type: str, entity_id: str,
                           values: Mapping[str, Any],
                           event_time: Optional[float] = None,
                           ttl_s: float = 7_200.0,
                           now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            self._conn.execute(
                "INSERT OR REPLACE INTO feature_values (entity_type,"
                " entity_id, values_json, event_time, ingestion_time,"
                " ttl_time) VALUES (?, ?, ?, ?, ?, ?)",
                (entity_type, entity_id, json.dumps(dict(values)),
                 event_time if event_time is not None else ts, ts, ts + ttl_s))

    def get_feature_values(self, entity_type: str, entity_id: str,
                           now: Optional[float] = None) -> Dict[str, Any]:
        ts = now if now is not None else time.time()
        row = self._conn.execute(
            "SELECT values_json, ttl_time FROM feature_values"
            " WHERE entity_type=? AND entity_id=?",
            (entity_type, entity_id)).fetchone()
        if row is None or (row["ttl_time"] is not None and ts >= row["ttl_time"]):
            return {}
        return json.loads(row["values_json"])

    def expire_feature_values(self, now: Optional[float] = None) -> int:
        """Drop expired rows (the reference's ttl_timestamp index intent)."""
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            cur = self._conn.execute(
                "DELETE FROM feature_values WHERE ttl_time < ?", (ts,))
            return cur.rowcount

    # -------------------------------------------------------------- profiles
    def put_profiles(self, users: Mapping[str, Mapping[str, Any]] = (),
                     merchants: Mapping[str, Mapping[str, Any]] = (),
                     now: Optional[float] = None) -> None:
        ts = now if now is not None else time.time()
        with self._lock, self._conn:
            if users:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO user_profiles VALUES (?, ?, ?)",
                    [(uid, json.dumps(dict(p)), ts) for uid, p in users.items()])
            if merchants:
                self._conn.executemany(
                    "INSERT OR REPLACE INTO merchant_profiles VALUES (?, ?, ?)",
                    [(mid, json.dumps(dict(p)), ts)
                     for mid, p in merchants.items()])

    def get_user_profile(self, user_id: str) -> Optional[Dict[str, Any]]:
        row = self._conn.execute(
            "SELECT profile_json FROM user_profiles WHERE user_id=?",
            (user_id,)).fetchone()
        return json.loads(row["profile_json"]) if row else None

    def get_merchant_profile(self, merchant_id: str) -> Optional[Dict[str, Any]]:
        row = self._conn.execute(
            "SELECT profile_json FROM merchant_profiles WHERE merchant_id=?",
            (merchant_id,)).fetchone()
        return json.loads(row["profile_json"]) if row else None

    def load_all_profiles(self) -> Dict[str, Dict[str, Dict[str, Any]]]:
        """Bulk restore (scorer warm-start after restart)."""
        users = {r["user_id"]: json.loads(r["profile_json"])
                 for r in self._conn.execute(
                     "SELECT * FROM user_profiles").fetchall()}
        merchants = {r["merchant_id"]: json.loads(r["profile_json"])
                     for r in self._conn.execute(
                         "SELECT * FROM merchant_profiles").fetchall()}
        return {"users": users, "merchants": merchants}

    # ---------------------------------------------------------------- health
    def stats(self) -> Dict[str, int]:
        out = {}
        for table in ("jobs", "checkpoints", "feature_groups", "features",
                      "feature_values", "user_profiles", "merchant_profiles"):
            out[table] = self._conn.execute(
                f"SELECT COUNT(*) AS n FROM {table}").fetchone()["n"]
        return out

"""RESP (REdis Serialization Protocol) client + mini server, stdlib-only.

The reference keeps all shared online state in Redis — profiles, txn cache,
velocity hashes, feature JSON, aggregations (RedisService.java:36-49,
config/redis/redis-master.conf). This framework's default stores are
in-process (state/stores.py keeps the hot loop off the network), but a
multi-replica serving tier needs a *shared* plane: ``RespClient`` speaks
RESP2 to any Redis-compatible server, and ``MiniRedisServer`` is a
Redis-protocol-compatible in-process server (strings, hashes, lists, TTLs)
so shared-state deployments and tests work in this image, where no Redis
binary exists.

Command subset (what the §2.5 key schema needs): PING, GET, SET [EX], SETEX,
SETNX, DEL, EXISTS, EXPIRE, TTL, INCR, INCRBYFLOAT, HSET, HSETNX, HGET,
HGETALL, HINCRBY, HINCRBYFLOAT, HDEL, LPUSH, LTRIM, LRANGE, LLEN, KEYS,
FLUSHDB, DBSIZE. Hash-field increments are atomic server-side — that is the
fix for the reference's GET-then-SET velocity races
(RedisTransactionSink.java:116-135) when replicas share a user.
"""

from __future__ import annotations

import fnmatch
import socket
import socketserver
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["RespClient", "MiniRedisServer", "RespError"]


class RespError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def encode_command(args: Tuple[Any, ...]) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, str):
            b = a.encode()
        elif isinstance(a, float):
            b = repr(a).encode()
        else:
            b = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _SockReader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()

    def read_line(self) -> bytes:
        while True:
            i = self._buf.find(b"\r\n")
            if i >= 0:
                line = bytes(self._buf[:i])
                del self._buf[: i + 2]
                return line
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self._buf.extend(chunk)

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self._buf.extend(chunk)
        data = bytes(self._buf[:n])
        del self._buf[: n + 2]          # strip trailing \r\n
        return data

    def read_value(self) -> Any:
        line = self.read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self.read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self.read_value() for _ in range(n)]
        raise RespError(f"bad RESP type byte {kind!r}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RespClient:
    """One-connection Redis client. Thread-safe (requests serialized)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _SockReader(self._sock)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def execute(self, *args: Any) -> Any:
        with self._lock:
            self._sock.sendall(encode_command(args))
            return self._reader.read_value()

    # ------------------------------------------------------------- strings
    def ping(self) -> bool:
        return self.execute("PING") == "PONG"

    def get(self, key: str) -> Optional[bytes]:
        return self.execute("GET", key)

    def set(self, key: str, value: Any, ex: Optional[float] = None) -> None:
        if ex is not None:
            self.execute("SET", key, value, "PX", int(ex * 1000))
        else:
            self.execute("SET", key, value)

    def setnx(self, key: str, value: Any) -> bool:
        return self.execute("SETNX", key, value) == 1

    def delete(self, *keys: str) -> int:
        return self.execute("DEL", *keys)

    def exists(self, key: str) -> bool:
        return self.execute("EXISTS", key) == 1

    def expire(self, key: str, seconds: float) -> bool:
        return self.execute("PEXPIRE", key, int(seconds * 1000)) == 1

    def incr(self, key: str) -> int:
        return self.execute("INCR", key)

    def incrbyfloat(self, key: str, amount: float) -> float:
        return float(self.execute("INCRBYFLOAT", key, amount))

    # -------------------------------------------------------------- hashes
    def hset(self, key: str, *pairs: Any) -> int:
        return self.execute("HSET", key, *pairs)

    def hsetnx(self, key: str, field: str, value: Any) -> bool:
        return self.execute("HSETNX", key, field, value) == 1

    def hget(self, key: str, field: str) -> Optional[bytes]:
        return self.execute("HGET", key, field)

    def hgetall(self, key: str) -> Dict[str, bytes]:
        flat = self.execute("HGETALL", key) or []
        return {flat[i].decode(): flat[i + 1] for i in range(0, len(flat), 2)}

    def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        return self.execute("HINCRBY", key, field, amount)

    def hincrbyfloat(self, key: str, field: str, amount: float) -> float:
        return float(self.execute("HINCRBYFLOAT", key, field, amount))

    # --------------------------------------------------------------- lists
    def lpush(self, key: str, *values: Any) -> int:
        return self.execute("LPUSH", key, *values)

    def ltrim(self, key: str, start: int, stop: int) -> None:
        self.execute("LTRIM", key, start, stop)

    def lrange(self, key: str, start: int, stop: int) -> List[bytes]:
        return self.execute("LRANGE", key, start, stop) or []

    def llen(self, key: str) -> int:
        return self.execute("LLEN", key)

    # --------------------------------------------------------------- admin
    def keys(self, pattern: str = "*") -> List[bytes]:
        return self.execute("KEYS", pattern) or []

    def flushdb(self) -> None:
        self.execute("FLUSHDB")

    def dbsize(self) -> int:
        return self.execute("DBSIZE")


# ---------------------------------------------------------------------------
# mini server
# ---------------------------------------------------------------------------


class _Store:
    """The keyspace: key -> (value, expires_at_ms|None). Values are bytes
    (strings), dict (hashes), or list (lists). One lock — command atomicity
    is the contract that matters (HINCRBY etc.), not parallelism."""

    def __init__(self) -> None:
        self.data: Dict[bytes, Tuple[Any, Optional[float]]] = {}
        self.lock = threading.Lock()

    def now_ms(self) -> float:
        return time.time() * 1000.0

    def live(self, key: bytes) -> Optional[Any]:
        item = self.data.get(key)
        if item is None:
            return None
        value, exp = item
        if exp is not None and self.now_ms() >= exp:
            del self.data[key]
            return None
        return value

    def put(self, key: bytes, value: Any,
            expires_at_ms: Optional[float] = None) -> None:
        self.data[key] = (value, expires_at_ms)

    def keep_ttl_put(self, key: bytes, value: Any) -> None:
        old = self.data.get(key)
        self.data[key] = (value, old[1] if old else None)


def _num(b: bytes) -> float:
    return float(b)


def _fmt_float(v: float) -> bytes:
    s = f"{v:.17g}"
    return s.encode()


class _RespHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: MiniRedisServer = self.server.outer  # type: ignore[attr-defined]
        reader = _SockReader(self.request)
        while True:
            try:
                cmd = reader.read_value()
            except (ConnectionError, RespError):
                return
            if not isinstance(cmd, list) or not cmd:
                return
            try:
                resp = server.run_command([bytes(c) for c in cmd])
            except RespError as e:
                resp = e
            except Exception as e:  # noqa: BLE001
                resp = RespError(f"ERR {type(e).__name__}: {e}")
            try:
                self.request.sendall(_encode_reply(resp))
            except OSError:
                return


def _encode_reply(v: Any) -> bytes:
    if isinstance(v, RespError):
        return b"-%s\r\n" % str(v).encode()
    if v is True:
        return b"+OK\r\n"
    if isinstance(v, str):
        return b"+%s\r\n" % v.encode()
    if isinstance(v, bool):
        return b":%d\r\n" % int(v)
    if isinstance(v, int):
        return b":%d\r\n" % v
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, bytes):
        return b"$%d\r\n%s\r\n" % (len(v), v)
    if isinstance(v, list):
        return b"*%d\r\n" % len(v) + b"".join(_encode_reply(x) for x in v)
    raise TypeError(f"cannot encode {type(v)}")


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class MiniRedisServer:
    """Redis-protocol-compatible server over an in-process keyspace."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._store = _Store()
        self._tcp = _TCPServer((host, port), _RespHandler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="mini-redis", daemon=True)

    def start(self) -> "MiniRedisServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ------------------------------------------------------------- commands
    def run_command(self, parts: List[bytes]) -> Any:
        name = parts[0].upper().decode()
        args = parts[1:]
        s = self._store
        with s.lock:
            handler = getattr(self, f"_cmd_{name.lower()}", None)
            if handler is None:
                raise RespError(f"ERR unknown command '{name}'")
            return handler(s, args)

    # strings ---------------------------------------------------------------
    @staticmethod
    def _cmd_ping(s: _Store, args) -> str:
        return args[0].decode() if args else "PONG"

    @staticmethod
    def _cmd_get(s: _Store, args):
        v = s.live(args[0])
        if v is not None and not isinstance(v, bytes):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v

    @staticmethod
    def _cmd_set(s: _Store, args) -> Any:
        key, value, rest = args[0], args[1], args[2:]
        expires = None
        i = 0
        nx = xx = False
        while i < len(rest):
            opt = rest[i].upper()
            if opt == b"EX":
                expires = s.now_ms() + float(rest[i + 1]) * 1000.0
                i += 2
            elif opt == b"PX":
                expires = s.now_ms() + float(rest[i + 1])
                i += 2
            elif opt == b"NX":
                nx = True
                i += 1
            elif opt == b"XX":
                xx = True
                i += 1
            else:
                raise RespError(f"ERR syntax error near {opt!r}")
        exists = s.live(key) is not None
        if (nx and exists) or (xx and not exists):
            return None
        s.put(key, value, expires)
        return True

    @staticmethod
    def _cmd_setex(s: _Store, args) -> Any:
        key, seconds, value = args
        s.put(key, value, s.now_ms() + float(seconds) * 1000.0)
        return True

    @staticmethod
    def _cmd_setnx(s: _Store, args) -> int:
        if s.live(args[0]) is not None:
            return 0
        s.put(args[0], args[1])
        return 1

    @staticmethod
    def _cmd_del(s: _Store, args) -> int:
        n = 0
        for key in args:
            if s.live(key) is not None:
                del s.data[key]
                n += 1
        return n

    @staticmethod
    def _cmd_exists(s: _Store, args) -> int:
        return sum(1 for key in args if s.live(key) is not None)

    @staticmethod
    def _cmd_expire(s: _Store, args) -> int:
        if s.live(args[0]) is None:
            return 0
        value, _ = s.data[args[0]]
        s.put(args[0], value, s.now_ms() + float(args[1]) * 1000.0)
        return 1

    @staticmethod
    def _cmd_pexpire(s: _Store, args) -> int:
        if s.live(args[0]) is None:
            return 0
        value, _ = s.data[args[0]]
        s.put(args[0], value, s.now_ms() + float(args[1]))
        return 1

    @staticmethod
    def _cmd_ttl(s: _Store, args) -> int:
        if s.live(args[0]) is None:
            return -2
        _, exp = s.data[args[0]]
        if exp is None:
            return -1
        return max(0, int((exp - s.now_ms()) / 1000.0))

    @staticmethod
    def _cmd_incr(s: _Store, args) -> int:
        v = s.live(args[0])
        cur = int(v) if v is not None else 0
        cur += 1
        s.keep_ttl_put(args[0], str(cur).encode())
        return cur

    @staticmethod
    def _cmd_incrbyfloat(s: _Store, args) -> bytes:
        v = s.live(args[0])
        cur = _num(v) if v is not None else 0.0
        cur += _num(args[1])
        out = _fmt_float(cur)
        s.keep_ttl_put(args[0], out)
        return out

    # hashes ----------------------------------------------------------------
    @staticmethod
    def _hash(s: _Store, key: bytes) -> Dict[bytes, bytes]:
        v = s.live(key)
        if v is None:
            v = {}
            s.put(key, v)
        elif not isinstance(v, dict):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v

    @classmethod
    def _cmd_hset(cls, s: _Store, args) -> int:
        h = cls._hash(s, args[0])
        added = 0
        for i in range(1, len(args), 2):
            if args[i] not in h:
                added += 1
            h[args[i]] = args[i + 1]
        return added

    @classmethod
    def _cmd_hsetnx(cls, s: _Store, args) -> int:
        h = cls._hash(s, args[0])
        if args[1] in h:
            return 0
        h[args[1]] = args[2]
        return 1

    @classmethod
    def _cmd_hget(cls, s: _Store, args):
        v = s.live(args[0])
        if v is None:
            return None
        if not isinstance(v, dict):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v.get(args[1])

    @classmethod
    def _cmd_hgetall(cls, s: _Store, args) -> list:
        v = s.live(args[0])
        if v is None:
            return []
        if not isinstance(v, dict):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        out = []
        for field, val in v.items():
            out.extend((field, val))
        return out

    @classmethod
    def _cmd_hincrby(cls, s: _Store, args) -> int:
        h = cls._hash(s, args[0])
        cur = int(h.get(args[1], b"0")) + int(args[2])
        h[args[1]] = str(cur).encode()
        return cur

    @classmethod
    def _cmd_hincrbyfloat(cls, s: _Store, args) -> bytes:
        h = cls._hash(s, args[0])
        cur = _num(h.get(args[1], b"0")) + _num(args[2])
        out = _fmt_float(cur)
        h[args[1]] = out
        return out

    @classmethod
    def _cmd_hdel(cls, s: _Store, args) -> int:
        v = s.live(args[0])
        if not isinstance(v, dict):
            return 0
        n = 0
        for field in args[1:]:
            if field in v:
                del v[field]
                n += 1
        return n

    # lists -----------------------------------------------------------------
    @staticmethod
    def _list(s: _Store, key: bytes) -> list:
        v = s.live(key)
        if v is None:
            v = []
            s.put(key, v)
        elif not isinstance(v, list):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v

    @classmethod
    def _cmd_lpush(cls, s: _Store, args) -> int:
        lst = cls._list(s, args[0])
        for v in args[1:]:
            lst.insert(0, v)
        return len(lst)

    @classmethod
    def _cmd_ltrim(cls, s: _Store, args) -> bool:
        lst = cls._list(s, args[0])
        start, stop = int(args[1]), int(args[2])
        n = len(lst)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        lst[:] = lst[max(0, start): stop + 1]
        return True

    @classmethod
    def _cmd_lrange(cls, s: _Store, args) -> list:
        v = s.live(args[0])
        if v is None:
            return []
        if not isinstance(v, list):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        start, stop = int(args[1]), int(args[2])
        n = len(v)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        return list(v[max(0, start): stop + 1])

    @classmethod
    def _cmd_llen(cls, s: _Store, args) -> int:
        v = s.live(args[0])
        return len(v) if isinstance(v, list) else 0

    # admin -----------------------------------------------------------------
    @staticmethod
    def _cmd_keys(s: _Store, args) -> list:
        pattern = (args[0] if args else b"*").decode()
        return [k for k in list(s.data)
                if s.live(k) is not None
                and fnmatch.fnmatchcase(k.decode(), pattern)]

    @staticmethod
    def _cmd_flushdb(s: _Store, args) -> bool:
        s.data.clear()
        return True

    @staticmethod
    def _cmd_dbsize(s: _Store, args) -> int:
        return sum(1 for k in list(s.data) if s.live(k) is not None)

"""RESP (REdis Serialization Protocol) client + mini server, stdlib-only.

The reference keeps all shared online state in Redis — profiles, txn cache,
velocity hashes, feature JSON, aggregations (RedisService.java:36-49,
config/redis/redis-master.conf). This framework's default stores are
in-process (state/stores.py keeps the hot loop off the network), but a
multi-replica serving tier needs a *shared* plane: ``RespClient`` speaks
RESP2 to any Redis-compatible server, and ``MiniRedisServer`` is a
Redis-protocol-compatible in-process server (strings, hashes, lists, TTLs)
so shared-state deployments and tests work in this image, where no Redis
binary exists.

Command subset (what the §2.5 key schema needs): PING, GET, SET [EX], SETEX,
SETNX, DEL, EXISTS, EXPIRE, TTL, INCR, INCRBYFLOAT, HSET, HSETNX, HGET,
HGETALL, HINCRBY, HINCRBYFLOAT, HDEL, LPUSH, RPUSH, LTRIM, LRANGE, LLEN,
KEYS, FLUSHDB, DBSIZE, INFO, SYNC, PEXPIREAT. Hash-field increments are
atomic server-side — that is the fix for the reference's GET-then-SET
velocity races (RedisTransactionSink.java:116-135) when replicas share a
user.

Production semantics (reference config/redis/redis-master.conf:17-18 and the
3-master + 3-replica compose topology):

- **maxmemory + allkeys-lru**: ``MiniRedisServer(maxmemory=...)`` tracks
  approximate per-key memory and evicts least-recently-accessed keys when a
  write pushes usage over the cap (exact LRU, not Redis's 5-key sampling —
  determinism beats fidelity at this scale). ``policy="noeviction"`` gives
  Redis's OOM-error mode instead.
- **Append-only persistence**: ``aof_path=`` logs every effective write
  (TTLs rewritten to absolute PEXPIREAT so replay is time-independent) and
  replays the log on start; a truncated tail (crash mid-write) is dropped,
  like ``aof-load-truncated yes``. ``rewrite_aof()`` compacts the log to a
  snapshot of the live keyspace.
- **Replication**: ``replica_of=(host, port)`` makes the server a read-only
  replica — it SYNCs a snapshot from the primary, then applies the
  primary's streamed write commands; ``promote()`` detaches it for
  failover. Replicas reject client writes with -READONLY, like Redis.
"""

from __future__ import annotations

import fnmatch
import os
import queue
import socket
import socketserver
import sys
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

__all__ = ["RespClient", "MiniRedisServer", "RespError"]


class RespError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


def encode_command(args: Tuple[Any, ...]) -> bytes:
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, bytes):
            b = a
        elif isinstance(a, str):
            b = a.encode()
        elif isinstance(a, float):
            b = repr(a).encode()
        else:
            b = str(a).encode()
        out.append(b"$%d\r\n%s\r\n" % (len(b), b))
    return b"".join(out)


class _SockReader:
    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()

    def read_line(self) -> bytes:
        while True:
            i = self._buf.find(b"\r\n")
            if i >= 0:
                line = bytes(self._buf[:i])
                del self._buf[: i + 2]
                return line
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self._buf.extend(chunk)

    def read_exact(self, n: int) -> bytes:
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self._buf.extend(chunk)
        data = bytes(self._buf[:n])
        del self._buf[: n + 2]          # strip trailing \r\n
        return data

    def read_value(self) -> Any:
        line = self.read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self.read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self.read_value() for _ in range(n)]
        raise RespError(f"bad RESP type byte {kind!r}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class RespClient:
    """One-connection Redis client. Thread-safe (requests serialized)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = _SockReader(self._sock)
        self._lock = threading.Lock()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def execute(self, *args: Any) -> Any:
        with self._lock:
            self._sock.sendall(encode_command(args))
            return self._reader.read_value()

    # ------------------------------------------------------------- strings
    def ping(self) -> bool:
        return self.execute("PING") == "PONG"

    def get(self, key: str) -> Optional[bytes]:
        return self.execute("GET", key)

    def set(self, key: str, value: Any, ex: Optional[float] = None) -> None:
        if ex is not None:
            self.execute("SET", key, value, "PX", int(ex * 1000))
        else:
            self.execute("SET", key, value)

    def setnx(self, key: str, value: Any) -> bool:
        return self.execute("SETNX", key, value) == 1

    def delete(self, *keys: str) -> int:
        return self.execute("DEL", *keys)

    def exists(self, key: str) -> bool:
        return self.execute("EXISTS", key) == 1

    def expire(self, key: str, seconds: float) -> bool:
        return self.execute("PEXPIRE", key, int(seconds * 1000)) == 1

    def incr(self, key: str) -> int:
        return self.execute("INCR", key)

    def incrbyfloat(self, key: str, amount: float) -> float:
        return float(self.execute("INCRBYFLOAT", key, amount))

    # -------------------------------------------------------------- hashes
    def hset(self, key: str, *pairs: Any) -> int:
        return self.execute("HSET", key, *pairs)

    def hsetnx(self, key: str, field: str, value: Any) -> bool:
        return self.execute("HSETNX", key, field, value) == 1

    def hget(self, key: str, field: str) -> Optional[bytes]:
        return self.execute("HGET", key, field)

    def hgetall(self, key: str) -> Dict[str, bytes]:
        flat = self.execute("HGETALL", key) or []
        return {flat[i].decode(): flat[i + 1] for i in range(0, len(flat), 2)}

    def hincrby(self, key: str, field: str, amount: int = 1) -> int:
        return self.execute("HINCRBY", key, field, amount)

    def hincrbyfloat(self, key: str, field: str, amount: float) -> float:
        return float(self.execute("HINCRBYFLOAT", key, field, amount))

    # --------------------------------------------------------------- lists
    def lpush(self, key: str, *values: Any) -> int:
        return self.execute("LPUSH", key, *values)

    def rpush(self, key: str, *values: Any) -> int:
        return self.execute("RPUSH", key, *values)

    def ltrim(self, key: str, start: int, stop: int) -> None:
        self.execute("LTRIM", key, start, stop)

    def lrange(self, key: str, start: int, stop: int) -> List[bytes]:
        return self.execute("LRANGE", key, start, stop) or []

    def llen(self, key: str) -> int:
        return self.execute("LLEN", key)

    # --------------------------------------------------------------- admin
    def keys(self, pattern: str = "*") -> List[bytes]:
        return self.execute("KEYS", pattern) or []

    def flushdb(self) -> None:
        self.execute("FLUSHDB")

    def dbsize(self) -> int:
        return self.execute("DBSIZE")

    def info(self) -> Dict[str, str]:
        raw = self.execute("INFO")
        out: Dict[str, str] = {}
        for line in (raw or b"").decode().splitlines():
            if line and not line.startswith("#") and ":" in line:
                k, v = line.split(":", 1)
                out[k] = v
        return out


# ---------------------------------------------------------------------------
# mini server
# ---------------------------------------------------------------------------


def _approx_size(key: bytes, value: Any) -> int:
    """Approximate resident bytes for a key (Redis-style accounting: payload
    plus fixed per-object overheads; exactness doesn't matter, monotonicity
    with real usage does)."""
    n = len(key) + 48
    if isinstance(value, bytes):
        return n + len(value) + 16
    if isinstance(value, dict):
        return n + 64 + sum(len(f) + len(v) + 64 for f, v in value.items())
    if isinstance(value, list):
        return n + 64 + sum(len(v) + 16 for v in value)
    return n + 64


class _Store:
    """The keyspace: key -> (value, expires_at_ms|None). Values are bytes
    (strings), dict (hashes), or list (lists). One lock — command atomicity
    is the contract that matters (HINCRBY etc.), not parallelism.

    ``access``/``sizes``/``used_memory`` feed the LRU eviction: every command
    touch bumps a logical clock, every write recomputes the touched key's
    approximate size."""

    def __init__(self) -> None:
        self.data: Dict[bytes, Tuple[Any, Optional[float]]] = {}
        self.lock = threading.Lock()
        self.access: Dict[bytes, int] = {}
        self.sizes: Dict[bytes, int] = {}
        self.used_memory = 0
        self.clock = 0

    def now_ms(self) -> float:
        return time.time() * 1000.0

    def touch(self, key: bytes) -> None:
        """Move ``key`` to the recently-used end. ``access`` doubles as the
        LRU order (dict preserves insertion order; pop+reinsert = move-to-
        end), so eviction pops from the front in O(1) — no keyspace scan."""
        self.clock += 1
        if key in self.data:
            self.access.pop(key, None)
            self.access[key] = self.clock

    def lru_victim(self) -> Optional[bytes]:
        for key in self.access:
            return key
        for key in self.data:          # untouched keys (shouldn't happen)
            return key
        return None

    def drop(self, key: bytes) -> None:
        self.data.pop(key, None)
        self.access.pop(key, None)
        self.used_memory -= self.sizes.pop(key, 0)

    def resize(self, key: bytes) -> None:
        """Re-account ``key`` after a mutation (or removal)."""
        self.used_memory -= self.sizes.pop(key, 0)
        item = self.data.get(key)
        if item is None:
            self.access.pop(key, None)
            return
        size = _approx_size(key, item[0])
        self.sizes[key] = size
        self.used_memory += size

    def live(self, key: bytes) -> Optional[Any]:
        item = self.data.get(key)
        if item is None:
            return None
        value, exp = item
        if exp is not None and self.now_ms() >= exp:
            self.drop(key)
            return None
        return value

    def put(self, key: bytes, value: Any,
            expires_at_ms: Optional[float] = None) -> None:
        self.data[key] = (value, expires_at_ms)

    def keep_ttl_put(self, key: bytes, value: Any) -> None:
        old = self.data.get(key)
        self.data[key] = (value, old[1] if old else None)


def _num(b: bytes) -> float:
    return float(b)


def _fmt_float(v: float) -> bytes:
    s = f"{v:.17g}"
    return s.encode()


class _RespHandler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: MiniRedisServer = self.server.outer  # type: ignore[attr-defined]
        reader = _SockReader(self.request)
        while True:
            try:
                cmd = reader.read_value()
            except (ConnectionError, RespError):
                return
            if not isinstance(cmd, list) or not cmd:
                return
            if bytes(cmd[0]).upper() == b"SYNC":
                # replication handshake: snapshot + live write stream ride
                # this very connection from now on. The replica never sends
                # again; park this thread tolerating the 5 s send-timeout
                # (set by handle_sync) bleeding into our recv, and exit —
                # closing the socket — only once the primary has dropped
                # the replica from its propagation list.
                server.handle_sync(self.request)
                while server.is_replica_socket(self.request):
                    try:
                        reader.read_value()
                    except socket.timeout:
                        continue
                    except (ConnectionError, RespError, OSError):
                        break
                return
            try:
                resp = server.run_command([bytes(c) for c in cmd],
                                          from_client=True)
            except RespError as e:
                resp = e
            except Exception as e:  # noqa: BLE001
                resp = RespError(f"ERR {type(e).__name__}: {e}")
            try:
                self.request.sendall(_encode_reply(resp))
            except OSError:
                return


def _encode_reply(v: Any) -> bytes:
    if isinstance(v, RespError):
        return b"-%s\r\n" % str(v).encode()
    if v is True:
        return b"+OK\r\n"
    if isinstance(v, str):
        return b"+%s\r\n" % v.encode()
    if isinstance(v, bool):
        return b":%d\r\n" % int(v)
    if isinstance(v, int):
        return b":%d\r\n" % v
    if v is None:
        return b"$-1\r\n"
    if isinstance(v, bytes):
        return b"$%d\r\n%s\r\n" % (len(v), v)
    if isinstance(v, list):
        return b"*%d\r\n" % len(v) + b"".join(_encode_reply(x) for x in v)
    raise TypeError(f"cannot encode {type(v)}")


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


_WRITE_CMDS = frozenset({
    "SET", "SETEX", "SETNX", "DEL", "EXPIRE", "PEXPIRE", "PEXPIREAT",
    "INCR", "INCRBYFLOAT", "HSET", "HSETNX", "HINCRBY", "HINCRBYFLOAT",
    "HDEL", "LPUSH", "RPUSH", "LTRIM", "FLUSHDB",
})


def _iter_aof(buf: bytes) -> Iterator[List[bytes]]:
    """Parse an append-only file of RESP command arrays. Stops silently at
    a truncated/corrupt tail (aof-load-truncated yes)."""
    i, n = 0, len(buf)
    while i < n:
        try:
            if buf[i:i + 1] != b"*":
                return
            j = buf.index(b"\r\n", i)
            argc = int(buf[i + 1:j])
            i = j + 2
            parts: List[bytes] = []
            for _ in range(argc):
                if buf[i:i + 1] != b"$":
                    return
                j = buf.index(b"\r\n", i)
                ln = int(buf[i + 1:j])
                i = j + 2
                if i + ln + 2 > n:
                    return
                parts.append(buf[i:i + ln])
                i += ln + 2
        except ValueError:
            return
        yield parts


class _ReplicaLink:
    """Per-replica output buffer + sender thread (Redis's client output
    buffer): the primary's write path only ENQUEUES under the store lock —
    a slow or drip-feeding replica can never stall client commands. A full
    buffer (replica hopelessly behind) drops the link; the replica
    reconnects and re-SYNCs."""

    def __init__(self, sock: socket.socket, maxlen: int = 10_000):
        self.sock = sock
        self.q: "queue.Queue[Optional[bytes]]" = queue.Queue(maxsize=maxlen)
        self.alive = True
        self.thread = threading.Thread(
            target=self._drain, name="mini-redis-repl-out", daemon=True)
        self.thread.start()

    def send(self, payload: bytes) -> bool:
        """Non-blocking enqueue; False = buffer overrun, drop this link."""
        if not self.alive:
            return False
        try:
            self.q.put_nowait(payload)
            return True
        except queue.Full:
            self.close()
            return False

    def _drain(self) -> None:
        while True:
            payload = self.q.get()
            if payload is None or not self.alive:
                return
            try:
                self.sock.sendall(payload)
            except OSError:
                self.alive = False
                return

    def close(self) -> None:
        self.alive = False
        try:
            self.q.put_nowait(None)
        except queue.Full:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class MiniRedisServer:
    """Redis-protocol-compatible server over an in-process keyspace, with
    maxmemory/LRU eviction, append-only persistence and primary→replica
    replication (see module docstring)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 maxmemory: int = 0, policy: str = "allkeys-lru",
                 aof_path: Optional[str] = None,
                 replica_of: Optional[Tuple[str, int]] = None):
        if policy not in ("allkeys-lru", "noeviction"):
            raise ValueError(f"unsupported eviction policy {policy!r}")
        self._store = _Store()
        self._maxmemory = int(maxmemory)
        self._policy = policy
        self._evicted = 0
        self._aof_path = aof_path
        self._aof_file = None
        self._loading = False
        self._aof_skipped = 0
        self._replicas: List[_ReplicaLink] = []
        self._replica_of = replica_of
        self._repl_stop = threading.Event()
        self._repl_sock: Optional[socket.socket] = None
        self._repl_thread: Optional[threading.Thread] = None
        if aof_path:
            self._load_aof(aof_path)
            self._aof_file = open(aof_path, "ab")
        self._tcp = _TCPServer((host, port), _RespHandler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="mini-redis", daemon=True)

    def start(self) -> "MiniRedisServer":
        self._thread.start()
        if self._replica_of is not None:
            self._repl_thread = threading.Thread(
                target=self._replicate_from, args=self._replica_of,
                name="mini-redis-replica", daemon=True)
            self._repl_thread.start()
        return self

    def stop(self) -> None:
        self._repl_stop.set()
        if self._repl_sock is not None:
            try:
                self._repl_sock.close()
            except OSError:
                pass
        self._tcp.shutdown()
        self._tcp.server_close()
        for link in self._replicas:
            link.close()
        with self._store.lock:
            if self._aof_file is not None:
                self._aof_file.close()
                self._aof_file = None

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    @property
    def is_replica(self) -> bool:
        return self._replica_of is not None

    @property
    def used_memory(self) -> int:
        return self._store.used_memory

    @property
    def evicted_keys(self) -> int:
        return self._evicted

    # ------------------------------------------------------------- commands
    def run_command(self, parts: List[bytes],
                    from_client: bool = False) -> Any:
        name = parts[0].upper().decode()
        args = parts[1:]
        s = self._store
        is_write = name in _WRITE_CMDS
        if is_write and from_client and self.is_replica:
            raise RespError(
                "READONLY You can't write against a read only replica.")
        with s.lock:
            handler = getattr(self, f"_cmd_{name.lower()}", None)
            if handler is None:
                raise RespError(f"ERR unknown command '{name}'")
            if (is_write and self._maxmemory
                    and self._policy == "noeviction"
                    and s.used_memory > self._maxmemory
                    and name not in ("DEL", "FLUSHDB")
                    and not self._loading):
                # never OOM-reject during AOF replay — Redis loads the full
                # log and only then enforces maxmemory on new writes
                raise RespError("OOM command not allowed when used memory "
                                "> 'maxmemory'.")
            result = handler(s, args)
            if args:
                s.touch(args[0])
            if is_write:
                self._after_write(name, args, result)
            return result

    # ------------------------------------------------- write-path machinery
    def _after_write(self, name: str, args: List[bytes], result: Any) -> None:
        """Re-account sizes, persist/propagate the effective command, evict.
        Called with the store lock held."""
        s = self._store
        if name == "FLUSHDB":
            s.access.clear()
            s.sizes.clear()
            s.used_memory = 0
        elif name == "DEL":
            for key in args:
                s.resize(key)
        else:
            s.resize(args[0])
        for entry in self._effective_entries(name, args, result):
            self._persist(entry)
        if self._maxmemory and self._policy == "allkeys-lru":
            while s.used_memory > self._maxmemory and s.data:
                victim = s.lru_victim()
                if victim is None:
                    break
                s.drop(victim)
                self._evicted += 1
                # evictions are state changes: AOF + replicas must see them
                self._persist((b"DEL", victim))

    def _effective_entries(self, name: str, args: List[bytes],
                           result: Any) -> List[Tuple[bytes, ...]]:
        """Translate a write command into replay-safe AOF/replication entries.

        Relative TTLs become absolute PEXPIREAT (replay later must not
        extend them); conditional writes that didn't fire log nothing."""
        s = self._store
        if name in ("SET", "SETEX", "SETNX"):
            if result is None or result == 0:
                return []
            key = args[0]
            value, exp = s.data[key]
            out = [(b"SET", key, value)]
            if exp is not None:
                out.append((b"PEXPIREAT", key, str(int(exp)).encode()))
            return out
        if name in ("EXPIRE", "PEXPIRE", "PEXPIREAT"):
            if result != 1:
                return []
            exp = s.data[args[0]][1]
            return [(b"PEXPIREAT", args[0], str(int(exp)).encode())]
        return [tuple([name.encode(), *args])]

    def _persist(self, entry: Tuple[bytes, ...]) -> None:
        payload = encode_command(entry)
        if self._aof_file is not None:
            self._aof_file.write(payload)
            self._aof_file.flush()
        for link in list(self._replicas):
            # enqueue only — the per-replica sender thread does the socket
            # I/O, so a slow replica can never stall commands on the primary
            if not link.send(payload):
                self._replicas.remove(link)

    # ----------------------------------------------------------- AOF replay
    def _load_aof(self, path: str) -> None:
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            buf = f.read()
        self._loading = True
        try:
            for parts in _iter_aof(buf):
                try:
                    self.run_command(parts)
                except RespError as e:
                    # replay of a well-formed log shouldn't error; count and
                    # surface rather than silently dropping data
                    self._aof_skipped += 1
                    print(f"mini-redis: AOF entry skipped during replay: {e}",
                          file=sys.stderr)
        finally:
            self._loading = False

    def _snapshot_entries(self) -> List[Tuple[bytes, ...]]:
        """The live keyspace as replay commands (lock must be held)."""
        s = self._store
        out: List[Tuple[bytes, ...]] = []
        for key in list(s.data):
            value = s.live(key)
            if value is None:
                continue
            _, exp = s.data[key]
            if isinstance(value, bytes):
                out.append((b"SET", key, value))
            elif isinstance(value, dict):
                flat: List[bytes] = []
                for f, v in value.items():
                    flat.extend((f, v))
                if flat:
                    out.append((b"HSET", key, *flat))
            elif isinstance(value, list):
                if value:
                    out.append((b"RPUSH", key, *value))
            if exp is not None:
                out.append((b"PEXPIREAT", key, str(int(exp)).encode()))
        return out

    def rewrite_aof(self) -> None:
        """Compact the append-only file to a snapshot of the live keyspace
        (BGREWRITEAOF analog, synchronous)."""
        if not self._aof_path:
            return
        with self._store.lock:
            tmp = self._aof_path + ".rewrite"
            with open(tmp, "wb") as f:
                for entry in self._snapshot_entries():
                    f.write(encode_command(entry))
            if self._aof_file is not None:
                self._aof_file.close()
            os.replace(tmp, self._aof_path)
            self._aof_file = open(self._aof_path, "ab")

    # ---------------------------------------------------------- replication
    def handle_sync(self, sock: socket.socket) -> None:
        """Primary side of SYNC: send a snapshot array, then register the
        connection for the live write stream (atomically, so no write is
        lost between snapshot and subscription)."""
        with self._store.lock:
            entries = self._snapshot_entries()
            payload = (b"*%d\r\n" % len(entries)
                       + b"".join(encode_command(e) for e in entries))
            link = _ReplicaLink(sock)
            if not link.send(payload):
                return
            self._replicas.append(link)

    def is_replica_socket(self, sock: socket.socket) -> bool:
        return any(link.sock is sock and link.alive
                   for link in self._replicas)

    def _replicate_from(self, host: str, port: int) -> None:
        """Replica side: SYNC snapshot, then apply the primary's stream.
        Reconnects (fresh SYNC) until stopped/promoted."""
        while not self._repl_stop.is_set():
            sock = None
            try:
                sock = socket.create_connection((host, port), timeout=10.0)
                self._repl_sock = sock
                sock.sendall(encode_command(("SYNC",)))
                reader = _SockReader(sock)
                snapshot = reader.read_value()
                self.run_command([b"FLUSHDB"])
                for parts in snapshot or []:
                    self.run_command([bytes(p) for p in parts])
                sock.settimeout(None)
                while not self._repl_stop.is_set():
                    parts = reader.read_value()
                    if not isinstance(parts, list) or not parts:
                        break
                    self.run_command([bytes(p) for p in parts])
            except (OSError, ConnectionError, RespError):
                pass
            finally:
                self._repl_sock = None
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
            if not self._repl_stop.is_set():
                time.sleep(0.2)

    def promote(self) -> None:
        """Detach from the primary and accept writes (failover: REPLICAOF
        NO ONE analog)."""
        self._repl_stop.set()
        if self._repl_sock is not None:
            try:
                self._repl_sock.close()
            except OSError:
                pass
        self._replica_of = None

    # strings ---------------------------------------------------------------
    @staticmethod
    def _cmd_ping(s: _Store, args) -> str:
        return args[0].decode() if args else "PONG"

    @staticmethod
    def _cmd_get(s: _Store, args):
        v = s.live(args[0])
        if v is not None and not isinstance(v, bytes):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v

    @staticmethod
    def _cmd_set(s: _Store, args) -> Any:
        key, value, rest = args[0], args[1], args[2:]
        expires = None
        i = 0
        nx = xx = False
        while i < len(rest):
            opt = rest[i].upper()
            if opt == b"EX":
                expires = s.now_ms() + float(rest[i + 1]) * 1000.0
                i += 2
            elif opt == b"PX":
                expires = s.now_ms() + float(rest[i + 1])
                i += 2
            elif opt == b"NX":
                nx = True
                i += 1
            elif opt == b"XX":
                xx = True
                i += 1
            else:
                raise RespError(f"ERR syntax error near {opt!r}")
        exists = s.live(key) is not None
        if (nx and exists) or (xx and not exists):
            return None
        s.put(key, value, expires)
        return True

    @staticmethod
    def _cmd_setex(s: _Store, args) -> Any:
        key, seconds, value = args
        s.put(key, value, s.now_ms() + float(seconds) * 1000.0)
        return True

    @staticmethod
    def _cmd_setnx(s: _Store, args) -> int:
        if s.live(args[0]) is not None:
            return 0
        s.put(args[0], args[1])
        return 1

    @staticmethod
    def _cmd_del(s: _Store, args) -> int:
        n = 0
        for key in args:
            if s.live(key) is not None:
                del s.data[key]
                n += 1
        return n

    @staticmethod
    def _cmd_exists(s: _Store, args) -> int:
        return sum(1 for key in args if s.live(key) is not None)

    @staticmethod
    def _cmd_expire(s: _Store, args) -> int:
        if s.live(args[0]) is None:
            return 0
        value, _ = s.data[args[0]]
        s.put(args[0], value, s.now_ms() + float(args[1]) * 1000.0)
        return 1

    @staticmethod
    def _cmd_pexpire(s: _Store, args) -> int:
        if s.live(args[0]) is None:
            return 0
        value, _ = s.data[args[0]]
        s.put(args[0], value, s.now_ms() + float(args[1]))
        return 1

    @staticmethod
    def _cmd_pexpireat(s: _Store, args) -> int:
        """Absolute-deadline expiry — the replay-safe TTL form the AOF and
        replication stream use (relative EXPIREs are rewritten to this)."""
        if s.live(args[0]) is None:
            return 0
        value, _ = s.data[args[0]]
        s.put(args[0], value, float(args[1]))
        return 1

    @staticmethod
    def _cmd_ttl(s: _Store, args) -> int:
        if s.live(args[0]) is None:
            return -2
        _, exp = s.data[args[0]]
        if exp is None:
            return -1
        return max(0, int((exp - s.now_ms()) / 1000.0))

    @staticmethod
    def _cmd_incr(s: _Store, args) -> int:
        v = s.live(args[0])
        cur = int(v) if v is not None else 0
        cur += 1
        s.keep_ttl_put(args[0], str(cur).encode())
        return cur

    @staticmethod
    def _cmd_incrbyfloat(s: _Store, args) -> bytes:
        v = s.live(args[0])
        cur = _num(v) if v is not None else 0.0
        cur += _num(args[1])
        out = _fmt_float(cur)
        s.keep_ttl_put(args[0], out)
        return out

    # hashes ----------------------------------------------------------------
    @staticmethod
    def _hash(s: _Store, key: bytes) -> Dict[bytes, bytes]:
        v = s.live(key)
        if v is None:
            v = {}
            s.put(key, v)
        elif not isinstance(v, dict):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v

    @classmethod
    def _cmd_hset(cls, s: _Store, args) -> int:
        h = cls._hash(s, args[0])
        added = 0
        for i in range(1, len(args), 2):
            if args[i] not in h:
                added += 1
            h[args[i]] = args[i + 1]
        return added

    @classmethod
    def _cmd_hsetnx(cls, s: _Store, args) -> int:
        h = cls._hash(s, args[0])
        if args[1] in h:
            return 0
        h[args[1]] = args[2]
        return 1

    @classmethod
    def _cmd_hget(cls, s: _Store, args):
        v = s.live(args[0])
        if v is None:
            return None
        if not isinstance(v, dict):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v.get(args[1])

    @classmethod
    def _cmd_hgetall(cls, s: _Store, args) -> list:
        v = s.live(args[0])
        if v is None:
            return []
        if not isinstance(v, dict):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        out = []
        for field, val in v.items():
            out.extend((field, val))
        return out

    @classmethod
    def _cmd_hincrby(cls, s: _Store, args) -> int:
        h = cls._hash(s, args[0])
        cur = int(h.get(args[1], b"0")) + int(args[2])
        h[args[1]] = str(cur).encode()
        return cur

    @classmethod
    def _cmd_hincrbyfloat(cls, s: _Store, args) -> bytes:
        h = cls._hash(s, args[0])
        cur = _num(h.get(args[1], b"0")) + _num(args[2])
        out = _fmt_float(cur)
        h[args[1]] = out
        return out

    @classmethod
    def _cmd_hdel(cls, s: _Store, args) -> int:
        v = s.live(args[0])
        if not isinstance(v, dict):
            return 0
        n = 0
        for field in args[1:]:
            if field in v:
                del v[field]
                n += 1
        return n

    # lists -----------------------------------------------------------------
    @staticmethod
    def _list(s: _Store, key: bytes) -> list:
        v = s.live(key)
        if v is None:
            v = []
            s.put(key, v)
        elif not isinstance(v, list):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        return v

    @classmethod
    def _cmd_lpush(cls, s: _Store, args) -> int:
        lst = cls._list(s, args[0])
        for v in args[1:]:
            lst.insert(0, v)
        return len(lst)

    @classmethod
    def _cmd_rpush(cls, s: _Store, args) -> int:
        lst = cls._list(s, args[0])
        lst.extend(args[1:])
        return len(lst)

    @classmethod
    def _cmd_ltrim(cls, s: _Store, args) -> bool:
        lst = cls._list(s, args[0])
        start, stop = int(args[1]), int(args[2])
        n = len(lst)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        lst[:] = lst[max(0, start): stop + 1]
        return True

    @classmethod
    def _cmd_lrange(cls, s: _Store, args) -> list:
        v = s.live(args[0])
        if v is None:
            return []
        if not isinstance(v, list):
            raise RespError("WRONGTYPE Operation against a key holding the "
                            "wrong kind of value")
        start, stop = int(args[1]), int(args[2])
        n = len(v)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        return list(v[max(0, start): stop + 1])

    @classmethod
    def _cmd_llen(cls, s: _Store, args) -> int:
        v = s.live(args[0])
        return len(v) if isinstance(v, list) else 0

    # admin -----------------------------------------------------------------
    @staticmethod
    def _cmd_keys(s: _Store, args) -> list:
        pattern = (args[0] if args else b"*").decode()
        return [k for k in list(s.data)
                if s.live(k) is not None
                and fnmatch.fnmatchcase(k.decode(), pattern)]

    @staticmethod
    def _cmd_flushdb(s: _Store, args) -> bool:
        s.data.clear()
        return True

    def _cmd_info(self, s: _Store, args) -> bytes:
        lines = [
            "# Server",
            f"role:{'slave' if self.is_replica else 'master'}",
            "# Memory",
            f"used_memory:{s.used_memory}",
            f"maxmemory:{self._maxmemory}",
            f"maxmemory_policy:{self._policy}",
            "# Stats",
            f"evicted_keys:{self._evicted}",
            f"db0_keys:{sum(1 for k in list(s.data) if s.live(k) is not None)}",
            f"connected_replicas:{sum(r.alive for r in self._replicas)}",
            f"aof_enabled:{int(self._aof_path is not None)}",
            f"aof_entries_skipped_on_load:{self._aof_skipped}",
        ]
        return ("\r\n".join(lines) + "\r\n").encode()

    @staticmethod
    def _cmd_dbsize(s: _Store, args) -> int:
        return sum(1 for k in list(s.data) if s.live(k) is not None)

from realtime_fraud_detection_tpu.state.stores import (  # noqa: F401
    VelocityStore,
    ProfileStore,
    TransactionCache,
    AggregationStore,
    StateBackend,
)
from realtime_fraud_detection_tpu.state.history import (  # noqa: F401
    UserHistoryStore,
    EntityGraphStore,
)

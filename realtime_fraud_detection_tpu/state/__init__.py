from realtime_fraud_detection_tpu.state.stores import (  # noqa: F401
    VelocityStore,
    ProfileStore,
    TransactionCache,
    AggregationStore,
    StateBackend,
)
from realtime_fraud_detection_tpu.state.resp import (  # noqa: F401
    MiniRedisServer,
    RespClient,
)
from realtime_fraud_detection_tpu.state.shared import (  # noqa: F401
    SharedAggregationStore,
    SharedProfileStore,
    SharedTransactionCache,
    SharedVelocityStore,
)
from realtime_fraud_detection_tpu.state.labeled import (  # noqa: F401
    LabeledExampleBuffer,
)
from realtime_fraud_detection_tpu.state.history import (  # noqa: F401
    UserHistoryStore,
    EntityGraphStore,
)
from realtime_fraud_detection_tpu.state.feature_store import (  # noqa: F401
    FeatureStats,
    FeatureStore,
)
from realtime_fraud_detection_tpu.state.metadata import (  # noqa: F401
    MetadataStore,
)

"""Per-entity history: sequence ring buffers and the user-merchant graph.

The reference keeps a last-100 transaction list per user in Redis
(RedisService.java:296-306) and rebuilds an entity graph from it per request
(graph_neural_network.py:244-315). Here the histories live host-side in
pre-allocated NumPy rings so a whole microbatch gathers into dense
``(B, T, F)`` / neighbor tensors with zero Python-per-row work on the
device path:

- ``UserHistoryStore``: fixed (T, F) float ring per user -> LSTM input
  (sequence_length 10, config.py:151-157).
- ``EntityGraphStore``: bounded neighbor rings user<->merchant -> GraphSAGE
  neighbor sampling (fan-out K per hop).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np


class UserHistoryStore:
    """Ring buffer of recent feature vectors per user."""

    def __init__(self, seq_len: int = 10, feature_dim: int = 64):
        self.seq_len = seq_len
        self.feature_dim = feature_dim
        self._rings: Dict[str, np.ndarray] = {}
        self._count: Dict[str, int] = {}

    def append_batch(self, user_ids: Sequence[str], features: np.ndarray) -> None:
        """Append one feature row per user (features: [B, F])."""
        for i, uid in enumerate(user_ids):
            ring = self._rings.get(uid)
            if ring is None:
                ring = np.zeros((self.seq_len, self.feature_dim), np.float32)
                self._rings[uid] = ring
                self._count[uid] = 0
            pos = self._count[uid] % self.seq_len
            ring[pos] = features[i]
            self._count[uid] += 1

    def append_and_gather(
        self, user_ids: Sequence[str], features: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Per row, in order: append the row, then gather that user's state.

        This is the scoring-time semantic — each transaction is scored
        against a history that ends with itself. A plain append_batch +
        gather would pair earlier rows with sequences containing later
        transactions of the same user (training-label leakage / mismatch).
        """
        b = len(user_ids)
        out = np.zeros((b, self.seq_len, self.feature_dim), np.float32)
        lengths = np.zeros((b,), np.int32)
        for i, uid in enumerate(user_ids):
            self.append_batch([uid], features[i : i + 1])
            seq, ln = self.gather([uid])
            out[i] = seq[0]
            lengths[i] = ln[0]
        return out, lengths

    def gather(self, user_ids: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Dense (B, T, F) history batch, oldest-first, plus lengths (B,).

        Users with fewer than T events are zero-padded at the FRONT so the
        most recent event is always the last step (what an LSTM reads out).
        """
        b = len(user_ids)
        out = np.zeros((b, self.seq_len, self.feature_dim), np.float32)
        lengths = np.zeros((b,), np.int32)
        for i, uid in enumerate(user_ids):
            ring = self._rings.get(uid)
            if ring is None:
                continue
            count = self._count[uid]
            k = min(count, self.seq_len)
            pos = count % self.seq_len
            # ring unrolled oldest->newest
            ordered = np.concatenate([ring[pos:], ring[:pos]], axis=0) if count >= self.seq_len \
                else ring[:k]
            out[i, self.seq_len - k:] = ordered[-k:]
            lengths[i] = k
        return out, lengths

    def __len__(self) -> int:
        return len(self._rings)


class EntityGraphStore:
    """Bounded bipartite adjacency between users and merchants.

    Node ids are the integer pool indices (sim.UserPool / sim.MerchantPool
    order or any stable external mapping). Each side keeps a ring of its K
    most recent counterparties; sampling pads with -1 and returns a mask.
    """

    def __init__(self, fanout: int = 16):
        self.fanout = fanout
        self._user_adj: Dict[int, List[int]] = {}
        self._merchant_adj: Dict[int, List[int]] = {}

    def add_edges(self, user_idx: Iterable[int], merchant_idx: Iterable[int]) -> None:
        for u, m in zip(user_idx, merchant_idx):
            u, m = int(u), int(m)
            ua = self._user_adj.setdefault(u, [])
            ua.append(m)
            del ua[:-self.fanout]
            ma = self._merchant_adj.setdefault(m, [])
            ma.append(u)
            del ma[:-self.fanout]

    def _sample(self, adj: Dict[int, List[int]], ids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        b, k = len(ids), self.fanout
        out = np.full((b, k), -1, np.int32)
        for i, n in enumerate(ids):
            neigh = adj.get(int(n))
            if neigh:
                out[i, : len(neigh)] = neigh[-k:]
        return out, out >= 0

    def user_neighbors(self, user_idx: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Merchant neighbors of users -> (idx [B,K], mask [B,K])."""
        return self._sample(self._user_adj, user_idx)

    def merchant_neighbors(self, merchant_idx: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """User neighbors of merchants -> (idx [B,K], mask [B,K])."""
        return self._sample(self._merchant_adj, merchant_idx)

    def _two_hop(self, first_adj, second_adj, ids):
        hop1, mask1 = self._sample(first_adj, ids)
        b, k = hop1.shape
        flat_idx = np.where(mask1, hop1, 0).reshape(-1)
        hop2, mask2 = self._sample(second_adj, flat_idx)
        hop2 = hop2.reshape(b, k, k)
        mask2 = mask2.reshape(b, k, k) & mask1[:, :, None]
        return hop1, mask1, hop2, mask2

    def user_two_hop(self, user_idx: Sequence[int]):
        """1-hop merchants + their 2-hop users:
        (hop1 [B,K], mask1, hop2 [B,K,K], mask2) for the GNN's 2-hop path."""
        return self._two_hop(self._user_adj, self._merchant_adj, user_idx)

    def merchant_two_hop(self, merchant_idx: Sequence[int]):
        """1-hop users + their 2-hop merchants."""
        return self._two_hop(self._merchant_adj, self._user_adj, merchant_idx)

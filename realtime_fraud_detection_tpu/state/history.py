"""Per-entity history: sequence ring buffers and the user-merchant graph.

The reference keeps a last-100 transaction list per user in Redis
(RedisService.java:296-306) and rebuilds an entity graph from it per request
(graph_neural_network.py:244-315). Here the histories live host-side in
pre-allocated NumPy rings so a whole microbatch gathers into dense
``(B, T, F)`` / neighbor tensors with zero Python-per-row work on the
device path:

- ``UserHistoryStore``: fixed (T, F) float ring per user -> LSTM input
  (sequence_length 10, config.py:151-157).
- ``EntityGraphStore``: bounded neighbor rings user<->merchant -> GraphSAGE
  neighbor sampling (fan-out K per hop).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np


def _occurrence_index(slots: np.ndarray) -> np.ndarray:
    """occ[i] = number of earlier rows in this batch with the same slot."""
    occ = np.zeros((len(slots),), np.int64)
    seen: Dict[int, int] = {}
    get = seen.get
    for i, s in enumerate(slots.tolist()):
        k = get(s, 0)
        occ[i] = k
        seen[s] = k + 1
    return occ


class UserHistoryStore:
    """Ring buffer of recent feature vectors per user.

    Storage is one dense (capacity, T, F) slot table plus a uid->slot map
    (not a dict of per-user rings): a whole microbatch appends with one
    fancy-index scatter and gathers with one ``take``-style read, so the
    host-assembly hot path does no per-record Python ring arithmetic.
    """

    def __init__(self, seq_len: int = 10, feature_dim: int = 64):
        self.seq_len = seq_len
        self.feature_dim = feature_dim
        self._slots: Dict[str, int] = {}
        cap = 1024
        self._table = np.zeros((cap, seq_len, feature_dim), np.float32)
        self._counts = np.zeros((cap,), np.int64)

    def __getstate__(self) -> Dict:
        """Pickle only the USED slot rows: host-state checkpoints and the
        partition plane's handoff snapshots (cluster/partition.py) pickle
        this store per partition, and shipping the pre-allocated capacity
        would make every handoff blob capacity-sized regardless of
        occupancy. ``_grow`` doubles from the trimmed size on restore.

        A legacy-layout instance (pre-slot-table ``_rings``/``_count``,
        re-pickled before ``__setstate__`` ever migrated it) has no slot
        table to trim — pickle it as-is and let restore migrate."""
        if "_slots" not in self.__dict__:
            return dict(self.__dict__)
        used = max(len(self._slots), 1)
        state = dict(self.__dict__)
        state["_table"] = self._table[:used].copy()
        state["_counts"] = self._counts[:used].copy()
        return state

    def __setstate__(self, state) -> None:
        """Checkpoint migration: pre-slot-table snapshots pickled a dict of
        per-user rings (``_rings``/``_count``). The ring layout is
        position-identical (raw modular positions), so legacy rings copy
        straight into slot-table rows."""
        if "_rings" not in state:
            self.__dict__.update(state)
            return
        self.seq_len = state["seq_len"]
        self.feature_dim = state["feature_dim"]
        self._slots = {}
        cap = 1024
        while cap < max(len(state["_rings"]), 1):
            cap *= 2
        self._table = np.zeros((cap, self.seq_len, self.feature_dim),
                               np.float32)
        self._counts = np.zeros((cap,), np.int64)
        counts = state.get("_count", {})
        for uid, ring in state["_rings"].items():
            s = len(self._slots)
            self._slots[uid] = s
            self._table[s] = ring
            self._counts[s] = int(counts.get(uid, 0))

    def _grow(self, need: int) -> None:
        cap = self._table.shape[0]
        if need <= cap:
            return
        new_cap = cap
        while new_cap < need:
            new_cap *= 2
        table = np.zeros((new_cap, self.seq_len, self.feature_dim), np.float32)
        table[:cap] = self._table
        counts = np.zeros((new_cap,), np.int64)
        counts[:cap] = self._counts
        self._table, self._counts = table, counts

    def _slot_ids(self, user_ids: Sequence[str], create: bool) -> np.ndarray:
        """uid -> slot indices; unknown uids get fresh slots (``create``)
        or the sentinel -1, which ``_gather_slots`` masks to zero rows."""
        slots = np.empty((len(user_ids),), np.int64)
        get = self._slots.get
        for i, uid in enumerate(user_ids):
            s = get(uid)
            if s is None:
                if not create:
                    s = -1
                else:
                    s = len(self._slots)
                    self._slots[uid] = s
            slots[i] = s
        if create and self._slots:
            self._grow(len(self._slots))
        return slots

    def _scatter_append(self, slots: np.ndarray, features: np.ndarray,
                        occ: np.ndarray) -> None:
        """Ring-write one row per (slot, occurrence); duplicate (slot, pos)
        targets resolve last-write-wins in index order — exactly the
        sequential ring semantics."""
        pos = (self._counts[slots] + occ) % self.seq_len
        self._table[slots, pos] = features
        np.add.at(self._counts, slots, 1)

    def append_batch(self, user_ids: Sequence[str], features: np.ndarray) -> None:
        """Append one feature row per user (features: [B, F])."""
        if not len(user_ids):
            return
        slots = self._slot_ids(user_ids, create=True)
        occ = _occurrence_index(slots)
        self._scatter_append(slots, np.asarray(features, np.float32), occ)

    def _gather_slots(self, slots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Dense (B, T, F) oldest-first readout for resolved slots
        (slot -1 = never seen -> zero rows, length 0)."""
        t = self.seq_len
        safe = np.maximum(slots, 0)
        counts = np.where(slots >= 0, self._counts[safe], 0)
        k = np.minimum(counts, t)
        # output position j holds ring[(count - k + (j - (T - k))) % T]
        # for j >= T - k, zero-pad in front of that
        jj = np.arange(t)[None, :] - (t - k[:, None])
        src = (counts[:, None] - k[:, None] + np.maximum(jj, 0)) % t
        vals = self._table[safe[:, None], src]
        out = np.where((jj >= 0)[:, :, None], vals, np.float32(0.0))
        return out, k.astype(np.int32)

    def append_and_gather(
        self, user_ids: Sequence[str], features: np.ndarray
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Per row, in order: append the row, then gather that user's state.

        This is the scoring-time semantic — each transaction is scored
        against a history that ends with itself. A plain append_batch +
        gather would pair earlier rows with sequences containing later
        transactions of the same user (training-label leakage / mismatch).

        Vectorized in occurrence rounds: round r appends + gathers every
        row that is its user's (r+1)-th appearance in this batch, so a
        user's later rows see its earlier rows' appends (identical to the
        sequential per-row semantics) while the common all-unique batch
        runs in exactly one vectorized round.
        """
        b = len(user_ids)
        out = np.zeros((b, self.seq_len, self.feature_dim), np.float32)
        lengths = np.zeros((b,), np.int32)
        if not b:
            return out, lengths
        features = np.asarray(features, np.float32)
        slots = self._slot_ids(user_ids, create=True)
        occ = _occurrence_index(slots)
        for r in range(int(occ.max()) + 1):
            rows = np.nonzero(occ == r)[0]
            rs = slots[rows]
            self._scatter_append(rs, features[rows],
                                 np.zeros((len(rows),), np.int64))
            out[rows], lengths[rows] = self._gather_slots(rs)
        return out, lengths

    def gather(self, user_ids: Sequence[str]) -> Tuple[np.ndarray, np.ndarray]:
        """Dense (B, T, F) history batch, oldest-first, plus lengths (B,).

        Users with fewer than T events are zero-padded at the FRONT so the
        most recent event is always the last step (what an LSTM reads out).
        """
        if not len(user_ids):
            return (np.zeros((0, self.seq_len, self.feature_dim), np.float32),
                    np.zeros((0,), np.int32))
        return self._gather_slots(self._slot_ids(user_ids, create=False))

    def user_ids(self) -> List[str]:
        """Users with any history, in first-seen order — the public
        iteration seam for state digests (``gather(sorted(user_ids()))``
        reads every ring without touching the slot internals)."""
        return list(self._slots)

    def __len__(self) -> int:
        return len(self._slots)


class EntityGraphStore:
    """Bounded bipartite adjacency between users and merchants.

    Node ids are the integer pool indices (sim.UserPool / sim.MerchantPool
    order or any stable external mapping). Each side keeps a ring of its K
    most recent counterparties; sampling pads with -1 and returns a mask.
    """

    def __init__(self, fanout: int = 16):
        self.fanout = fanout
        self._user_adj: Dict[int, List[int]] = {}
        self._merchant_adj: Dict[int, List[int]] = {}

    def add_edges(self, user_idx: Iterable[int], merchant_idx: Iterable[int]) -> None:
        for u, m in zip(user_idx, merchant_idx):
            u, m = int(u), int(m)
            ua = self._user_adj.setdefault(u, [])
            ua.append(m)
            del ua[:-self.fanout]
            ma = self._merchant_adj.setdefault(m, [])
            ma.append(u)
            del ma[:-self.fanout]

    def _sample(self, adj: Dict[int, List[int]], ids: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        b, k = len(ids), self.fanout
        out = np.full((b, k), -1, np.int32)
        for i, n in enumerate(ids):
            neigh = adj.get(int(n))
            if neigh:
                out[i, : len(neigh)] = neigh[-k:]
        return out, out >= 0

    def user_neighbors(self, user_idx: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """Merchant neighbors of users -> (idx [B,K], mask [B,K])."""
        return self._sample(self._user_adj, user_idx)

    def merchant_neighbors(self, merchant_idx: Sequence[int]) -> Tuple[np.ndarray, np.ndarray]:
        """User neighbors of merchants -> (idx [B,K], mask [B,K])."""
        return self._sample(self._merchant_adj, merchant_idx)

    def _two_hop(self, first_adj, second_adj, ids):
        hop1, mask1 = self._sample(first_adj, ids)
        b, k = hop1.shape
        flat_idx = np.where(mask1, hop1, 0).reshape(-1)
        hop2, mask2 = self._sample(second_adj, flat_idx)
        hop2 = hop2.reshape(b, k, k)
        mask2 = mask2.reshape(b, k, k) & mask1[:, :, None]
        return hop1, mask1, hop2, mask2

    def user_two_hop(self, user_idx: Sequence[int]):
        """1-hop merchants + their 2-hop users:
        (hop1 [B,K], mask1, hop2 [B,K,K], mask2) for the GNN's 2-hop path."""
        return self._two_hop(self._user_adj, self._merchant_adj, user_idx)

    def merchant_two_hop(self, merchant_idx: Sequence[int]):
        """1-hop users + their 2-hop merchants."""
        return self._two_hop(self._merchant_adj, self._user_adj, merchant_idx)

"""Feature registry + online feature statistics: the FeatureStore.

Capability mirror of the reference's FeatureStore (FeatureStore.java:21-398):
feature registration with typed metadata, per-entity feature values with TTL
(2 h), single/batch/selected retrieval, and online per-feature statistics for
data-quality monitoring — with two reference defects fixed:

1. **storeFeatureValues never stores** — the reference builds the enriched
   JSON then calls ``redisService.incrementCounter(key, ttl)`` instead of
   storing it (FeatureStore.java:122-146, noted in SURVEY.md §5.2). Here the
   values are actually persisted and retrievable.
2. **std-dev is never computed** — the reference's Welford update drops the
   M2 term ("For std calculation, we'd need to maintain sum of squares",
   FeatureStore.java:268). Here full Welford (count, mean, M2) runs, so
   ``std`` is real.

Registration metadata mirrors FeatureMetadata (name/type/description/
version/created/updated/properties, :46-61); statistics mirror FeatureStats
(count/mean/std/min/max, categorical counts, null rate, :63-75).
Backed by the same in-process ``_MemoryBackend`` as the other stores
(single-writer discipline); ``state.metadata.MetadataStore`` adds the
durable (SQLite) tier the reference's Postgres feature_store schema
promised but never used (init.sql, SURVEY.md §2.5).
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, Iterable, List, Mapping, Optional, Set

from realtime_fraud_detection_tpu.features.extract import FEATURE_NAMES
from realtime_fraud_detection_tpu.state.stores import _MemoryBackend

__all__ = ["FeatureStore", "FeatureStats"]

FEATURE_TYPES = ("NUMERICAL", "CATEGORICAL", "BOOLEAN", "TEXT", "TIMESTAMP")

METADATA_TTL_S = 86_400.0     # FeatureStore.java:36
VALUES_TTL_S = 7_200.0        # :37
STATS_TTL_S = 3_600.0         # :38 (stats here don't expire; TTL kept for
                              # parity in health reporting)


class FeatureStats:
    """Online statistics for one feature (FeatureStats, :63-75) with a real
    Welford accumulator."""

    __slots__ = ("name", "count", "numeric_count", "mean", "m2", "min",
                 "max", "categorical_counts", "null_count", "last_updated")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.numeric_count = 0
        self.mean = 0.0
        self.m2 = 0.0
        self.min = math.inf
        self.max = -math.inf
        self.categorical_counts: Dict[str, int] = {}
        self.null_count = 0
        self.last_updated = 0.0

    def update(self, value: Any, now: float) -> None:
        self.count += 1
        self.last_updated = now
        if value is None:
            self.null_count += 1
        elif isinstance(value, bool):
            key = str(value).lower()
            self.categorical_counts[key] = self.categorical_counts.get(key, 0) + 1
        elif isinstance(value, (int, float)):
            v = float(value)
            self.numeric_count += 1
            delta = v - self.mean
            self.mean += delta / self.numeric_count
            self.m2 += delta * (v - self.mean)
            self.min = min(self.min, v)
            self.max = max(self.max, v)
        else:
            key = str(value)
            self.categorical_counts[key] = self.categorical_counts.get(key, 0) + 1

    @property
    def std(self) -> float:
        n = self.numeric_count
        return math.sqrt(self.m2 / n) if n >= 2 else 0.0

    @property
    def null_rate(self) -> float:
        return self.null_count / self.count if self.count else 0.0

    def to_dict(self) -> Dict[str, Any]:
        # min/max must stay JSON-safe (no Infinity tokens) when no numeric
        # sample has been seen — e.g. purely categorical features
        has_numeric = self.numeric_count > 0
        return {
            "feature_name": self.name,
            "count": self.count,
            "mean": self.mean,
            "std": self.std,
            "min": self.min if has_numeric else 0.0,
            "max": self.max if has_numeric else 0.0,
            "null_rate": self.null_rate,
            "categorical_counts": dict(self.categorical_counts),
            "last_updated": self.last_updated,
        }


class FeatureStore:
    """Registry + values + statistics, in one single-writer object."""

    def __init__(self):
        self._metadata: Dict[str, Dict[str, Any]] = {}
        self._values = _MemoryBackend()
        self._stats: Dict[str, FeatureStats] = {}
        self.counters = {"stored": 0, "retrieved": 0, "registered": 0}

    # ------------------------------------------------------------- registry
    def register_feature(self, name: str, feature_type: str = "NUMERICAL",
                         description: str = "",
                         properties: Optional[Mapping[str, Any]] = None,
                         now: Optional[float] = None) -> Dict[str, Any]:
        """registerFeature (:83-117). Re-registering bumps version and
        ``updated_at``."""
        if feature_type not in FEATURE_TYPES:
            raise ValueError(
                f"unknown feature type {feature_type!r}; one of {FEATURE_TYPES}")
        ts = now if now is not None else time.time()
        existing = self._metadata.get(name)
        if existing is None:
            meta = {
                "name": name, "type": feature_type,
                "description": description, "version": 1,
                "created_at": ts, "updated_at": ts,
                "properties": dict(properties or {}),
            }
        else:
            meta = dict(existing)
            meta.update(type=feature_type, description=description,
                        version=existing["version"] + 1, updated_at=ts)
            if properties:
                meta["properties"] = {**existing["properties"], **properties}
        self._metadata[name] = meta
        self.counters["registered"] += 1
        return meta

    def get_metadata(self, name: str) -> Optional[Dict[str, Any]]:
        return self._metadata.get(name)

    def registered_features(self) -> Set[str]:
        """getRegisteredFeatures (:325-365): explicit registrations plus the
        canonical 64-feature contract (features/extract.py FEATURE_NAMES)."""
        return set(self._metadata) | set(FEATURE_NAMES)

    # --------------------------------------------------------------- values
    @staticmethod
    def _key(entity_type: str, entity_id: str) -> str:
        return f"feature_values:{entity_type}:{entity_id}"

    def store_feature_values(self, entity_id: str, entity_type: str,
                             features: Mapping[str, Any],
                             now: Optional[float] = None) -> None:
        """storeFeatureValues (:122-146) — actually storing the values."""
        ts = now if now is not None else time.time()
        enriched = dict(features)
        enriched["_entity_id"] = entity_id
        enriched["_entity_type"] = entity_type
        enriched["_timestamp"] = ts * 1000.0
        enriched["_version"] = "1.0"
        self._values.put(self._key(entity_type, entity_id), enriched,
                         VALUES_TTL_S, now=ts)
        for name, value in features.items():
            stats = self._stats.get(name)
            if stats is None:
                stats = self._stats[name] = FeatureStats(name)
            stats.update(value, ts)
        self.counters["stored"] += 1

    def get_feature_values(self, entity_id: str, entity_type: str,
                           now: Optional[float] = None) -> Dict[str, Any]:
        """getFeatureValues (:152-174): internal ``_*`` fields stripped."""
        raw = self._values.get(self._key(entity_type, entity_id), now=now)
        self.counters["retrieved"] += 1
        if not raw:
            return {}
        return {k: v for k, v in raw.items() if not k.startswith("_")}

    def get_batch_feature_values(self, entity_ids: Iterable[str],
                                 entity_type: str,
                                 now: Optional[float] = None
                                 ) -> Dict[str, Dict[str, Any]]:
        """getBatchFeatureValues (:179-189)."""
        return {eid: self.get_feature_values(eid, entity_type, now=now)
                for eid in entity_ids}

    def get_selected_features(self, entity_id: str, entity_type: str,
                              feature_names: Iterable[str],
                              now: Optional[float] = None) -> Dict[str, Any]:
        """getSelectedFeatures (:194-201)."""
        wanted = set(feature_names)
        return {k: v
                for k, v in self.get_feature_values(
                    entity_id, entity_type, now=now).items()
                if k in wanted}

    # ----------------------------------------------------------- statistics
    def get_feature_statistics(self, name: str) -> Dict[str, Any]:
        """getFeatureStatistics (:305-322)."""
        stats = self._stats.get(name)
        return stats.to_dict() if stats else FeatureStats(name).to_dict()

    def all_statistics(self) -> List[Dict[str, Any]]:
        return [s.to_dict() for s in self._stats.values()]

    # --------------------------------------------------------------- health
    def health(self) -> Dict[str, Any]:
        """isHealthy/getStoreStatistics analog (:370-396)."""
        return {
            "healthy": True,
            "registered_features": len(self._metadata),
            "tracked_statistics": len(self._stats),
            "stored_value_sets": len(self._values),
            "counters": dict(self.counters),
        }

"""Shared online-state stores over the RESP client: the multi-replica tier.

Same APIs as the in-process stores (state/stores.py) over a Redis-protocol
server, with the reference's exact key schema (RedisService.java:36-49):

    user:{id} / merchant:{id}              profile hashes (JSON field values)
    transaction:{id}                       JSON, TTL 24 h
    user_transactions:{id}                 list, last 100 (LPUSH + LTRIM)
    merchant_transactions:{id}             list, last 500
    velocity:{user}:{5min|1hour|24hour}    hash {count, amount, timestamp}
    features:{txnId}                       JSON, TTL 2 h
    agg:{key}                              hash counters, TTL 30 min

Two scorer replicas pointed at one server share profiles/velocity/history —
the deployment story behind HPA scale-out (deploy/k8s). Differences from the
in-process stores, by design:

- **Atomicity**: velocity and aggregation updates are HINCRBY /
  HINCRBYFLOAT — atomic server-side, so concurrent replicas can't lose
  updates (the reference's GET-then-SET races,
  RedisTransactionSink.java:116-135, are structurally impossible).
- **Velocity TTL**: each window key gets its own TTL equal to its period
  (PEXPIRE at window creation), fixing the reference's all-windows-1h bug
  (RedisService.java:178-207). Expiry runs on the server's wall clock, so
  the ``now`` parameters accepted for sim-time compatibility are recorded
  but not used for expiry.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Mapping, Optional

from realtime_fraud_detection_tpu.state.resp import RespClient
from realtime_fraud_detection_tpu.state.stores import VELOCITY_WINDOWS

__all__ = [
    "SharedProfileStore",
    "SharedVelocityStore",
    "SharedTransactionCache",
    "SharedAggregationStore",
]


def _dumps(v: Any) -> str:
    return json.dumps(v, separators=(",", ":"))


def _loads(b: Optional[bytes]) -> Any:
    return None if b is None else json.loads(b)


class SharedProfileStore:
    """``user:{id}`` / ``merchant:{id}`` hashes, one JSON value per field."""

    def __init__(self, client: RespClient):
        self.c = client

    def seed(self, users: Optional[Mapping[str, Mapping[str, Any]]] = None,
             merchants: Optional[Mapping[str, Mapping[str, Any]]] = None) -> None:
        for uid, p in (users or {}).items():
            self.put_user(uid, p)
        for mid, p in (merchants or {}).items():
            self.put_merchant(mid, p)

    def _put(self, key: str, profile: Mapping[str, Any]) -> None:
        pairs: List[Any] = []
        for field, value in profile.items():
            pairs.extend((field, _dumps(value)))
        if pairs:
            self.c.hset(key, *pairs)

    def _get(self, key: str) -> Optional[Dict[str, Any]]:
        h = self.c.hgetall(key)
        if not h:
            return None
        return {field: json.loads(v) for field, v in h.items()}

    def put_user(self, user_id: str, profile: Mapping[str, Any]) -> None:
        self._put(f"user:{user_id}", profile)

    def put_merchant(self, merchant_id: str, profile: Mapping[str, Any]) -> None:
        self._put(f"merchant:{merchant_id}", profile)

    def get_user(self, user_id: str) -> Optional[Mapping[str, Any]]:
        return self._get(f"user:{user_id}")

    def get_merchant(self, merchant_id: str) -> Optional[Mapping[str, Any]]:
        return self._get(f"merchant:{merchant_id}")


class SharedVelocityStore:
    """``velocity:{user}:{window}`` hashes with atomic increments."""

    def __init__(self, client: RespClient):
        self.c = client

    def update(self, user_id: str, amount: float, now: float) -> None:
        for window, period in VELOCITY_WINDOWS.items():
            key = f"velocity:{user_id}:{window}"
            created = self.c.hsetnx(key, "timestamp", repr(now))
            self.c.hincrby(key, "count", 1)
            self.c.hincrbyfloat(key, "amount", float(amount))
            if created:
                # window TTL == its own period (fixes the reference's
                # uniform 1h TTL); set once at window creation
                self.c.expire(key, period)

    def update_batch(self, user_ids, amounts, now: float) -> None:
        for uid, amt in zip(user_ids, amounts):
            self.update(uid, float(amt), now)

    def get(self, user_id: str, window: str,
            now: Optional[float] = None) -> Dict[str, float]:
        h = self.c.hgetall(f"velocity:{user_id}:{window}")
        if not h:
            return {}
        return {
            "count": int(h.get("count", b"0")),
            "amount": float(h.get("amount", b"0")),
            "timestamp": float(h.get("timestamp", b"0")),
        }

    def get_all(self, user_id: str,
                now: Optional[float] = None) -> Dict[str, Dict[str, float]]:
        return {w: self.get(user_id, w, now) for w in VELOCITY_WINDOWS}


class SharedTransactionCache:
    """``transaction:{id}`` / ``features:{id}`` JSON + per-entity id lists."""

    def __init__(self, client: RespClient, txn_ttl_s: float = 24 * 3600,
                 features_ttl_s: float = 2 * 3600,
                 user_list_len: int = 100, merchant_list_len: int = 500):
        self.c = client
        self.txn_ttl_s = txn_ttl_s
        self.features_ttl_s = features_ttl_s
        self.user_list_len = user_list_len
        self.merchant_list_len = merchant_list_len

    def cache_transaction(self, txn: Mapping[str, Any],
                          now: Optional[float] = None) -> None:
        tid = str(txn.get("transaction_id"))
        self.c.set(f"transaction:{tid}", _dumps(dict(txn)), ex=self.txn_ttl_s)
        uid, mid = str(txn.get("user_id")), str(txn.get("merchant_id"))
        ukey, mkey = f"user_transactions:{uid}", f"merchant_transactions:{mid}"
        self.c.lpush(ukey, tid)
        self.c.ltrim(ukey, 0, self.user_list_len - 1)
        self.c.lpush(mkey, tid)
        self.c.ltrim(mkey, 0, self.merchant_list_len - 1)

    def get_transaction(self, txn_id: str,
                        now: Optional[float] = None) -> Any:
        return _loads(self.c.get(f"transaction:{txn_id}"))

    def store_features(self, txn_id: str, features: Any,
                       now: Optional[float] = None) -> None:
        self.c.set(f"features:{txn_id}", _dumps(features),
                   ex=self.features_ttl_s)

    def get_features(self, txn_id: str, now: Optional[float] = None) -> Any:
        return _loads(self.c.get(f"features:{txn_id}"))

    def get_user_transactions(self, user_id: str,
                              limit: int = 100) -> List[str]:
        return [b.decode() for b in
                self.c.lrange(f"user_transactions:{user_id}", 0, limit - 1)]

    def get_merchant_transactions(self, merchant_id: str,
                                  limit: int = 500) -> List[str]:
        return [b.decode() for b in
                self.c.lrange(f"merchant_transactions:{merchant_id}", 0,
                              limit - 1)]


class SharedAggregationStore:
    """``agg:{key}`` hash counters — concurrent-replica-safe by atomicity."""

    def __init__(self, client: RespClient, ttl_s: float = 1800.0):
        self.c = client
        self.ttl_s = ttl_s

    def record(self, txn: Mapping[str, Any],
               now: Optional[float] = None) -> None:
        from realtime_fraud_detection_tpu.state.stores import _event_time_ms

        ts_ms = _event_time_ms(txn, now)
        hour_key = int(ts_ms // 3_600_000)
        day_key = int(ts_ms // 86_400_000)
        amount = float(txn.get("amount", 0.0))
        is_fraud = bool(txn.get("is_fraud", False))
        high_risk = float(txn.get("fraud_score", 0.0)) > 0.7
        for key in (f"hourly:{hour_key}", f"daily:{day_key}",
                    f"merchant:{txn.get('merchant_id')}:{hour_key}"):
            full = f"agg:{key}"
            count = self.c.hincrby(full, "total_count", 1)
            self.c.hincrbyfloat(full, "total_amount", amount)
            if is_fraud:
                self.c.hincrby(full, "fraud_count", 1)
            if high_risk:
                self.c.hincrby(full, "high_risk_count", 1)
            if count == 1:
                self.c.expire(full, self.ttl_s)

    def get(self, key: str, now: Optional[float] = None) -> Dict[str, Any]:
        h = self.c.hgetall(f"agg:{key}")
        if not h:
            return {}
        count = int(h.get("total_count", b"0"))
        total = float(h.get("total_amount", b"0"))
        fraud = int(h.get("fraud_count", b"0"))
        return {
            "total_count": count,
            "total_amount": total,
            "fraud_count": fraud,
            "high_risk_count": int(h.get("high_risk_count", b"0")),
            "fraud_rate": fraud / count if count else 0.0,
            "avg_amount": total / count if count else 0.0,
        }

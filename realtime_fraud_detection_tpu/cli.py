"""Command-line entry points: simulate / run-job / serve / train / bench /
health-check / topics.

The reference's operational surface is a pile of shell scripts and service
mains (simulator.py:478-503 argparse, FraudDetectionJob.main + JobConfig
CLI flags JobConfig.java:69-146, uvicorn in main.py:343,
scripts/setup/{start-all,health-check,start-simulation}.sh). Here it is one
typed CLI over the framework:

    python -m realtime_fraud_detection_tpu simulate --count 1000
    python -m realtime_fraud_detection_tpu run-job --count 10000 --analytics
    python -m realtime_fraud_detection_tpu serve --port 8000
    python -m realtime_fraud_detection_tpu train --rows 20000 --out ./ckpt
    python -m realtime_fraud_detection_tpu bench
    python -m realtime_fraud_detection_tpu health-check --url http://...
    python -m realtime_fraud_detection_tpu topics
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Optional


def _add_sim_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--users", type=int, default=10_000,
                   help="user pool size (simulator.py:481)")
    p.add_argument("--merchants", type=int, default=5_000,
                   help="merchant pool size (:482)")
    p.add_argument("--tps", type=float, default=1000.0,
                   help="simulated event-time rate (:481)")
    p.add_argument("--seed", type=int, default=42)


def cmd_simulate(args: argparse.Namespace) -> int:
    """Generate transactions as JSON lines (simulator.py main() analog —
    minus the sleep(1/tps) pacing loop; event time is synthesized)."""
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    gen = TransactionGenerator(num_users=args.users,
                               num_merchants=args.merchants,
                               seed=args.seed, tps=args.tps)
    if getattr(args, "broker", ""):
        # produce into an external broker at ~tps (start-simulation.sh
        # role) through the ingress gateway: generation paces here, the
        # gateway's C++ lock-free queue + sender thread overlaps the
        # network produce with generation
        from realtime_fraud_detection_tpu.stream import IngressGateway
        from realtime_fraud_detection_tpu.stream import topics as T

        client = _broker_client(args.broker)
        gateway = IngressGateway(client, T.TRANSACTIONS)
        n_fraud = produced = 0
        try:
            while produced < args.count:
                chunk = min(1000, args.count - produced,
                            max(1, int(args.tps)))
                t0 = time.perf_counter()
                for txn in gen.generate_batch(chunk):
                    n_fraud += bool(txn.get("is_fraud"))
                    while not gateway.submit(txn):  # backpressure: spin
                        time.sleep(0.001)
                produced += chunk
                budget = chunk / args.tps - (time.perf_counter() - t0)
                if budget > 0:
                    time.sleep(budget)
        finally:
            gateway.close()
            client.close()
        print(f"produced {produced} txns ({n_fraud} fraud, "
              f"native_queue={gateway.native}, dropped={gateway.dropped}) "
              f"to {args.broker}", file=sys.stderr)
        return 0
    out = sys.stdout if args.output == "-" else open(args.output, "w")
    try:
        n_fraud = 0
        remaining = args.count
        while remaining > 0:
            for txn in gen.generate_batch(min(1000, remaining)):
                n_fraud += bool(txn.get("is_fraud"))
                out.write(json.dumps(txn) + "\n")
            remaining -= min(1000, remaining)
    finally:
        if out is not sys.stdout:
            out.close()
    print(f"generated {args.count} txns ({n_fraud} fraud)", file=sys.stderr)
    return 0


def _addr(spec: str, default_port: int) -> tuple[str, int]:
    host, _, port = spec.partition(":")
    return host or "127.0.0.1", int(port or default_port)


def _broker_client(spec: str, default_port: int = 9092):
    """Broker client from an address spec. A comma-separated list (the
    replicated-cluster deployment, primary first) returns an
    HaBrokerClient that rotates on connection loss or a not-yet-promoted
    replica's READONLY; a single address returns the plain client."""
    from realtime_fraud_detection_tpu.stream import (
        HaBrokerClient,
        NetBrokerClient,
    )

    addrs = [_addr(a, default_port) for a in spec.split(",") if a.strip()]
    if not addrs:
        raise ValueError(f"no broker address in {spec!r}")
    if len(addrs) > 1:
        return HaBrokerClient(addrs)
    return NetBrokerClient(host=addrs[0][0], port=addrs[0][1])


def cmd_run_job(args: argparse.Namespace) -> int:
    """End-to-end streaming job: simulator -> broker -> microbatched TPU
    scorer -> output topics, with checkpointing + durable job metadata."""
    from realtime_fraud_detection_tpu.checkpoint import (
        CheckpointManager,
        snapshot_scorer_host_state,
    )
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.state import MetadataStore
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T

    gen = TransactionGenerator(num_users=args.users,
                               num_merchants=args.merchants,
                               seed=args.seed, tps=args.tps)
    if args.broker:
        broker = _broker_client(args.broker)
    else:
        broker = InMemoryBroker()
    state_client = None
    if args.state:
        from realtime_fraud_detection_tpu.state import RespClient

        shost, sport = _addr(args.state, 6379)
        state_client = RespClient(host=shost, port=sport)
    job_config_obj = None
    if (getattr(args, "quant", False) or getattr(args, "kernels", False)
            or getattr(args, "mega", False)):
        from realtime_fraud_detection_tpu.utils.config import (
            Config,
            KernelSettings,
            QuantSettings,
        )

        job_config_obj = Config()
        if getattr(args, "quant", False):
            # quantized scoring plane (models/quant.py): int8 BERT weights
            # + GEMM-form tree kernels, the configuration rtfd quant-drill
            # gates
            job_config_obj.quant = QuantSettings.full()
        if getattr(args, "kernels", False) or getattr(args, "mega", False):
            # Pallas kernel plane (ops/): fused dequant-matmul + fused
            # score-and-blend epilogue + flash attention, the
            # configuration rtfd kernel-drill gates; --mega swaps in the
            # persistent megakernel (one program per microbatch, the
            # kernel-drill --mega gated configuration)
            job_config_obj.kernels = (
                KernelSettings.mega() if getattr(args, "mega", False)
                else KernelSettings.full())
    scorer = FraudScorer(job_config_obj, scorer_config=ScorerConfig(),
                         state_client=state_client)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    feedback_plane = None
    if getattr(args, "feedback", False):
        # continuous-learning plane: the job feeds emitted predictions into
        # the label join; this entry point also plays the label-producer
        # role (delayed ground truth from the simulator onto the labels
        # topic), so a self-generating run closes the loop end to end
        from realtime_fraud_detection_tpu.feedback import FeedbackPlane
        from realtime_fraud_detection_tpu.obs import (
            DriftConfig,
            FeatureDriftMonitor,
        )
        from realtime_fraud_detection_tpu.utils.config import (
            FeedbackSettings,
        )

        settings = FeedbackSettings(
            enabled=True,
            label_delay_scale=args.feedback_delay_scale)
        feedback_plane = FeedbackPlane(
            settings, scorer=scorer, config=scorer.config,
            drift_monitor=FeatureDriftMonitor(
                DriftConfig(num_features=scorer.sc.feature_dim)))
    qos_settings = None
    if getattr(args, "qos", False):
        from realtime_fraud_detection_tpu.utils.config import QosSettings

        qos_settings = QosSettings(
            enabled=True, budget_ms=args.qos_budget_ms,
            admission_rate=args.qos_rate)
    tracing_settings = None
    if getattr(args, "trace", False):
        from realtime_fraud_detection_tpu.utils.config import TracingSettings

        tracing_settings = TracingSettings(enabled=True)
    tuning_settings = None
    if getattr(args, "autotune", False):
        from realtime_fraud_detection_tpu.utils.config import TuningSettings

        tuning_settings = TuningSettings(enabled=True)
        # the hard QoS floor holds at the CLI seam too: with --qos, the
        # tuner's deadline search space is clamped to the budget's
        # assembly slice, then checked by the same validation
        # Config.validate applies
        tuning_settings.clamp_to_qos(qos_settings)
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=args.batch, enable_analytics=args.analytics,
        enable_enrichment=args.enrichment,
        pipeline_depth=args.pipeline_depth, qos=qos_settings,
        feedback=feedback_plane,
        overlap_assembly=getattr(args, "overlap_assembly", False),
        device_pool=getattr(args, "device_pool", False),
        inflight_depth=getattr(args, "inflight_depth", 2),
        tracing=tracing_settings, autotune=tuning_settings))

    metadata: Optional[MetadataStore] = None
    ckpt: Optional[CheckpointManager] = None
    job_id = f"job-{args.seed}"
    if args.metadata_db:
        metadata = MetadataStore(args.metadata_db)
        metadata.register_job(job_id, "fraud-detection-job", parallelism=1)
        metadata.put_profiles(gen.users.profiles(), gen.merchants.profiles())
    if args.checkpoint_dir:
        ckpt = CheckpointManager(args.checkpoint_dir)

    def _checkpoint_step(step: int) -> None:
        if ckpt is None:
            return
        t_ck = time.perf_counter()
        path = ckpt.save(
            step, params=scorer.models,
            host_state=snapshot_scorer_host_state(scorer),
            offsets=job.consumer.positions())
        if metadata is not None:
            metadata.record_checkpoint(
                job_id, step, str(path),
                duration_ms=(time.perf_counter() - t_ck) * 1e3)

    # graceful shutdown (robustness satellite, ISSUE 12): SIGTERM/SIGINT
    # drain the in-flight microbatches, commit their offsets, and write a
    # final checkpoint before exit — a terminated job loses NOTHING to
    # replay-on-restart; only SIGKILL (no handler possible) replays the
    # uncommitted tail
    import signal as _signal

    stop_sig: Dict[str, Any] = {"name": None}

    def _graceful(signum, frame):  # noqa: ANN001 - signal contract
        stop_sig["name"] = _signal.Signals(signum).name
        job.request_stop()

    try:
        _signal.signal(_signal.SIGTERM, _graceful)
        _signal.signal(_signal.SIGINT, _graceful)
    except ValueError:
        pass                      # not the main thread (embedded/test use)

    t0 = time.perf_counter()
    produced = scored = step = 0
    if ckpt is not None and ckpt.latest_step() is not None:
        # resume: models + host state + transport offsets from the latest
        # checkpoint (the Flink restore-from-checkpoint behavior); step
        # numbering continues so retention never collides
        # rtfd-lint: allow[lock-order] CLI startup: restore runs before any scoring thread exists
        ck = ckpt.restore_into_scorer(scorer)
        if ck.offsets:
            job.consumer.seek_to_positions(ck.offsets)
        step = ck.step
        print(f"resumed from checkpoint step {ck.step} "
              f"({args.checkpoint_dir})", file=sys.stderr)
    try:
        if args.count == 0:
            # consume-only: an external simulator feeds the broker; run in
            # checkpointed slices until --duration elapses (0 = forever)
            while (args.duration <= 0
                   or time.perf_counter() - t0 < args.duration) \
                    and not job.stop_requested:
                scored += job.run_for(
                    min(10.0, args.duration - (time.perf_counter() - t0))
                    if args.duration > 0 else 10.0)
                step += 1
                _checkpoint_step(step)
        while produced < args.count and not job.stop_requested:
            chunk = min(args.count - produced, 10_000)
            records = gen.generate_batch(chunk)
            broker.produce_batch(T.TRANSACTIONS, records,
                                 key_fn=lambda r: str(r["user_id"]))
            if feedback_plane is not None:
                # label-producer role: delayed ground truth for the chunk
                broker.produce_batch(
                    T.LABELS,
                    gen.label_events(records,
                                     delay_scale=args.feedback_delay_scale),
                    key_fn=lambda e: str(e["transaction_id"]))
            produced += chunk
            scored += job.run_until_drained()
            step += 1
            _checkpoint_step(step)
    except BaseException:
        if metadata is not None:
            metadata.set_job_status(job_id, "FAILED")
            metadata.close()
        raise
    if job.analytics is not None:
        job.analytics.flush()
    if stop_sig["name"] is not None:
        # the run loops drained + committed before returning; the final
        # checkpoint pins (state, offsets) at the drained point so resume
        # replays NOTHING (regression-pinned in tests/test_elastic.py)
        step += 1
        _checkpoint_step(step)
        print(f"graceful shutdown on {stop_sig['name']}: in-flight "
              f"drained, offsets committed"
              + (f", final checkpoint step {step}"
                 if ckpt is not None else ""), file=sys.stderr)
    dt = time.perf_counter() - t0
    if metadata is not None:
        metadata.set_job_status(job_id, "FINISHED")
        metadata.close()

    summary: Dict[str, Any] = {
        "scored": scored,
        "wall_s": round(dt, 3),
        "txn_per_s": round(scored / dt, 1),
        "counters": job.counters,
        **({"stopped_by": stop_sig["name"]}
           if stop_sig["name"] is not None else {}),
    }
    if feedback_plane is not None:
        snap = feedback_plane.snapshot()
        summary["feedback"] = {
            "prequential_sliding": snap["prequential"]["sliding"],
            "labels_matched": snap["label_join"]["matched"],
            "buffer": snap["buffer"]["size"],
            "policy": snap["policy"],
        }
    if job.tracer is not None:
        bd = job.tracer.breakdown()
        slo = job.tracer.slo.snapshot()
        summary["tracing"] = {
            "traces": bd["n"],
            "p99": bd["quantiles"].get("p99"),
            "slo_fast": slo["windows"]["fast"],
            "counters": dict(job.tracer.counters),
        }
    if job.tuning is not None:
        snap = job.tuning.snapshot()
        summary["autotune"] = {
            "decisions": snap["controller"]["decisions"],
            "max_wait_ms": snap["controller"]["max_wait_ms"],
            "tuner": snap["tuner"]["counters"],
            "close_reasons": dict(job.assembler.close_reasons),
        }
    if job.analytics is not None:
        summary["analytics"] = {
            k: v["fired"] for k, v in job.analytics.stats().items()}
    print(json.dumps(summary))
    return 0


def cmd_serve(args: argparse.Namespace) -> int:
    """Run the scoring service (reference main.py:343 uvicorn analog)."""
    from realtime_fraud_detection_tpu.serving.app import ServingApp
    from realtime_fraud_detection_tpu.utils.config import Config

    config = Config.from_file(args.config) if args.config else Config()
    if args.host:
        config.serving.host = args.host
    if args.port is not None:
        config.serving.port = args.port
    if getattr(args, "qos", False):
        config.qos.enabled = True
    if getattr(args, "qos_budget_ms", None):
        config.qos.budget_ms = args.qos_budget_ms
    if getattr(args, "qos_rate", None):
        config.qos.admission_rate = args.qos_rate
    if getattr(args, "trace", False):
        config.tracing.enabled = True
    if getattr(args, "quant", False):
        from realtime_fraud_detection_tpu.utils.config import QuantSettings

        config.quant = QuantSettings.full()
    if getattr(args, "kernels", False) or getattr(args, "mega", False):
        from realtime_fraud_detection_tpu.utils.config import KernelSettings

        config.kernels = (KernelSettings.mega()
                          if getattr(args, "mega", False)
                          else KernelSettings.full())
    if getattr(args, "autotune", False):
        config.tuning.enabled = True
        # clamp the tuner's deadline search space to the budget's
        # assembly slice (the validation floor), then re-check
        config.tuning.clamp_to_qos(config.qos)
    if getattr(args, "overlap_assembly", False):
        config.serving.overlap_assembly = True
    if getattr(args, "device_pool", False):
        config.serving.device_pool = True
    if getattr(args, "inflight_depth", None):
        config.serving.inflight_depth = args.inflight_depth
    scorer_kwargs: Dict[str, Any] = {}
    if getattr(args, "quality_artifact", ""):
        applied = config.apply_quality_artifact(args.quality_artifact)
        print(f"serving the measured blend from {args.quality_artifact}: "
              f"{applied}", file=sys.stderr)
        # the artifact records the text-branch architecture + tokenizer the
        # blend was measured (and its checkpoint trained) with — the scorer
        # must be built to match or a checkpoint restore would mismatch
        with open(args.quality_artifact) as f:
            proto = json.load(f).get("protocol", {})
        if proto.get("text_model"):
            from realtime_fraud_detection_tpu.models.bert import BertConfig

            scorer_kwargs["bert_config"] = BertConfig(**proto["text_model"])
    scorer = None
    state_addr = args.state or os.environ.get("RTFD_STATE_ADDR", "")
    if state_addr or scorer_kwargs:
        from realtime_fraud_detection_tpu.scoring import (
            FraudScorer,
            ScorerConfig,
        )

        sc = ScorerConfig()
        if getattr(args, "quality_artifact", "") and proto.get("text_model"):
            import dataclasses as _dc

            sc = _dc.replace(
                sc, text_len=int(proto.get("text_len", 32)),
                tokenizer=proto.get("tokenizer", "word"))
        if state_addr:
            from realtime_fraud_detection_tpu.state import RespClient

            shost, sport = _addr(state_addr, 6379)
            scorer_kwargs["state_client"] = RespClient(host=shost,
                                                       port=sport)
            print(f"using shared state tier at {state_addr}",
                  file=sys.stderr)
        scorer = FraudScorer(config, scorer_config=sc, **scorer_kwargs)
    app = ServingApp(config=config, scorer=scorer)
    if args.checkpoint_dir:
        from realtime_fraud_detection_tpu.checkpoint import CheckpointManager

        mgr = CheckpointManager(args.checkpoint_dir)
        if getattr(args, "quality_artifact", ""):
            # refuse to combine a checkpoint and an artifact recording
            # DIFFERENT text-encoder architectures (VERDICT Weak #5): the
            # blend was measured against one model, the restored params
            # are another. --allow-arch-mismatch overrides explicitly.
            art_tm = Config.load_artifact_text_model(args.quality_artifact)
            ck_tm = (mgr.manifest().get("metadata") or {}).get("text_model")
            if (art_tm is not None and ck_tm is not None
                    and dict(art_tm) != dict(ck_tm)
                    and not getattr(args, "allow_arch_mismatch", False)):
                print(f"text-encoder architecture mismatch: artifact "
                      f"{args.quality_artifact} records {art_tm}, "
                      f"checkpoint {args.checkpoint_dir} records {ck_tm}; "
                      f"pass --allow-arch-mismatch to combine anyway",
                      file=sys.stderr)
                return 2
        try:
            # rtfd-lint: allow[lock-order] CLI startup: restore runs before the serving loop starts
            ck = mgr.restore_into_scorer(
                app.scorer,
                allow_arch_mismatch=getattr(args, "allow_arch_mismatch",
                                            False))
        except ValueError as e:
            # quantization-mode / shape stamp refusal: exit loudly instead
            # of serving a silently cross-mode model
            print(str(e), file=sys.stderr)
            return 2
        print(f"restored checkpoint step {ck.step} from "
              f"{args.checkpoint_dir}", file=sys.stderr)
    print(f"serving on {config.serving.host}:{config.serving.port}",
          file=sys.stderr)
    app.run_forever()
    return 0


def cmd_train(args: argparse.Namespace) -> int:
    """Train the tree models on synthetic data and save a checkpoint
    (model_trainer.py analog: XGBoost + IsolationForest, AUC eval,
    artifact save — :41-295). The checkpoint holds a FULL ScoringModels
    set (trained trees + isolation forest, fresh neural branches) so
    ``serve --checkpoint-dir`` and ``POST /reload-models`` can load it
    directly."""
    import numpy as np

    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.features.extract import extract_features
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        IsolationForestTrainer,
    )
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.training import GBDTTrainer

    gen = TransactionGenerator(num_users=args.users,
                               num_merchants=args.merchants, seed=args.seed)
    batch, labels = gen.generate_encoded(args.rows)
    x = np.asarray(extract_features(batch))
    y = labels["is_fraud"].astype(np.float32)
    split = int(0.8 * len(y))

    gbdt_trainer = GBDTTrainer(n_estimators=args.trees, seed=args.seed)
    trees = gbdt_trainer.fit(x[:split], y[:split])
    from realtime_fraud_detection_tpu.models.trees import tree_ensemble_logits

    logits = np.asarray(tree_ensemble_logits(trees, x[split:]))
    auc = _auc(y[split:], logits)

    iforest = IsolationForestTrainer(seed=args.seed).fit(
        x[:split][y[:split] == 0])          # fit on normals only (:235-276)

    import jax

    from realtime_fraud_detection_tpu.scoring import init_scoring_models

    models = init_scoring_models(jax.random.PRNGKey(args.seed))
    models = models.replace(trees=trees, iforest=iforest)

    if args.neural:
        # train every neural branch too (the reference's ModelTrainer
        # docstring claims LSTM/BERT/GNN trainers that don't exist —
        # model_trainer.py:2-4, SURVEY.md §3.5)
        from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG
        from realtime_fraud_detection_tpu.training.neural import (
            train_gnn,
            train_lstm,
        )
        from realtime_fraud_detection_tpu.training.text import train_bert

        n = args.rows
        lstm = train_lstm(gen, n_transactions=n, hidden=128,
                          epochs=2, seed=args.seed)
        gnn, _, _, _ = train_gnn(gen, n_transactions=n, node_dim=16,
                                 hidden=64, epochs=2, seed=args.seed)
        bert = train_bert(gen, config=TINY_CONFIG,
                          n_transactions=min(n, 8000), epochs=1,
                          seed=args.seed)
        models = models.replace(lstm=lstm, gnn=gnn, bert=bert)

    mgr = CheckpointManager(args.out)
    # a FRESH step per run (never overwrite in place): a reader — the
    # serving hot-reload or the 3 AM validate CronJob — resolving "latest"
    # mid-save sees the previous complete step, not a torn rmtree'd dir.
    # The recorded sim_seed lets validate refuse a contaminated eval stream.
    latest = mgr.latest_step()
    step = 0 if latest is None else latest + 1
    path = mgr.save(step, params=models,
                    metadata={"rows": args.rows, "auc": auc,
                              "fraud_rate": float(y.mean()),
                              "sim_seed": args.seed,
                              "sim_users": args.users,
                              "sim_merchants": args.merchants,
                              # restored by restore_into_scorer so served
                              # explanations keep their importances
                              "feature_importances":
                                  [round(float(v), 6) for v in
                                   gbdt_trainer.feature_importances_]})
    from realtime_fraud_detection_tpu.features.extract import (
        top_feature_importances,
    )

    print(json.dumps({"auc": round(auc, 4),
                      "fraud_rate": round(float(y.mean()), 4),
                      "neural_trained": bool(args.neural),
                      "top_feature_importances": top_feature_importances(
                          gbdt_trainer.feature_importances_),
                      "checkpoint": str(path)}))
    return 0


def _auc(y: "Any", score: "Any") -> float:
    """Mann-Whitney AUC with average ranks for ties (tied logits are common
    with few trees; ordinal ranks would bias the estimate)."""
    import numpy as np

    score = np.asarray(score, float)
    order = np.argsort(score)
    rank = np.empty(len(score), float)
    sorted_scores = score[order]
    i = 0
    while i < len(score):
        j = i
        while j + 1 < len(score) and sorted_scores[j + 1] == sorted_scores[i]:
            j += 1
        rank[order[i:j + 1]] = (i + j) / 2.0 + 1.0   # average 1-based rank
        i = j + 1
    pos = np.asarray(y) > 0.5
    n_pos, n_neg = int(pos.sum()), int((~pos).sum())
    if not n_pos or not n_neg:
        return 0.5
    return float((rank[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def cmd_validate(args: argparse.Namespace) -> int:
    """Validate a trained checkpoint against a fresh labeled stream.

    The reference schedules this as its model-validation CronJob
    (ci-cd-pipeline.yaml:351-390: daily run, metrics pushed to a Prometheus
    gateway) but ships no implementation. Here: restore the checkpoint into
    a scorer, score a freshly simulated stream with known injected fraud,
    report AUC/accuracy/precision/recall, optionally write a Prometheus
    textfile, and FAIL (exit 1) below --min-auc so the CronJob's status is
    the quality gate.
    """
    import numpy as np

    from realtime_fraud_detection_tpu.checkpoint import CheckpointManager
    from realtime_fraud_detection_tpu.scoring import FraudScorer
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator

    scorer = FraudScorer()
    # rtfd-lint: allow[lock-order] CLI startup: restore runs before any scoring begins
    ckpt = CheckpointManager(args.checkpoint_dir).restore_into_scorer(
        scorer, step=args.step)
    # Held-out eval stream: never the checkpoint's recorded training seed.
    # The +1 convention alone is not a guarantee (validate --seed 41 would
    # land exactly on a 42-trained stream), so cross-check the manifest.
    train_seed = (ckpt.metadata or {}).get("sim_seed")
    val_seed = args.seed + 1
    if train_seed is not None and val_seed == int(train_seed):
        val_seed += 1
    gen = TransactionGenerator(num_users=args.users,
                               num_merchants=args.merchants, seed=val_seed)
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())

    ys, ss = [], []
    remaining = args.rows
    while remaining > 0:
        recs = gen.generate_batch(min(256, remaining))
        remaining -= len(recs)
        res = scorer.score_batch(recs)
        ys += [bool(r.get("is_fraud")) for r in recs]
        ss += [r["fraud_probability"] for r in res]
    y = np.asarray(ys, float)
    s = np.asarray(ss, float)
    pos = y > 0.5
    flag = s >= 0.5
    auc = _auc(y, s)
    tp = float((flag & pos).sum())
    report = {
        "n": int(len(y)),
        "fraud_rate": round(float(pos.mean()), 4),
        "auc": round(auc, 4),
        "accuracy": round(float((flag == pos).mean()), 4),
        "precision": round(tp / max(float(flag.sum()), 1.0), 4),
        "recall": round(tp / max(float(pos.sum()), 1.0), 4),
        "min_auc": args.min_auc,
        "passed": bool(auc >= args.min_auc),
        "eval_seed": val_seed,
        "checkpoint_step": int(ckpt.step),
    }
    if args.metrics_out:
        # Prometheus textfile (node-exporter textfile-collector format) —
        # the no-egress analog of the reference's pushgateway POST; rendered
        # by the project's own exposition code so formatting/escaping has
        # one implementation (obs/metrics.py)
        from realtime_fraud_detection_tpu.obs.metrics import Registry

        reg = Registry()
        for k, v in report.items():
            if isinstance(v, bool):
                v = int(v)
            elif not isinstance(v, (int, float)):
                continue
            reg.gauge(f"rtfd_validation_{k}",
                      f"model validation gate: {k}").set(float(v))
        with open(args.metrics_out, "w") as f:
            f.write(reg.render())
    print(json.dumps(report))
    return 0 if report["passed"] else 1


def cmd_bench(args: argparse.Namespace) -> int:
    import importlib.util
    from pathlib import Path

    # bench.py lives at the repo root (driver contract), outside the
    # package — load it by path so the command works from any cwd
    bench_path = Path(__file__).resolve().parent.parent / "bench.py"
    spec = importlib.util.spec_from_file_location("bench", bench_path)
    if spec is None or spec.loader is None:
        print(f"bench.py not found at {bench_path}", file=sys.stderr)
        return 1
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    if getattr(args, "quant", False):
        # quantized pool_scaling (bench.py reads the env in the inner
        # process; see _pool_scaling_stage)
        os.environ["RTFD_BENCH_QUANT"] = "1"
    if getattr(args, "mesh", False):
        # mesh_scaling on a tunneled TPU (bench.py reads the env in the
        # inner process; see _mesh_scaling_stage — CPU runs it always)
        os.environ["RTFD_BENCH_MESH"] = "1"
    if getattr(args, "kernels", False):
        # kernel-plane pool_scaling (bench.py reads the env in the inner
        # process; see _pool_scaling_stage)
        os.environ["RTFD_BENCH_KERNELS"] = "1"
    if getattr(args, "mega", False):
        # persistent-megakernel pool_scaling (implies the kernel plane;
        # bench.py reads the env in the inner process)
        os.environ["RTFD_BENCH_MEGA"] = "1"
    bench.main()
    return 0


def cmd_broker(args: argparse.Namespace) -> int:
    """Run the standalone durable log broker (the Kafka-role process of a
    multi-service deployment; stream/netbroker.py). Blocks until SIGINT."""
    from realtime_fraud_detection_tpu.stream.netbroker import BrokerServer

    import time as _time

    server = BrokerServer(host=args.host, port=args.port,
                          log_dir=args.log_dir or None,
                          role=getattr(args, "role", "primary"),
                          min_isr=getattr(args, "min_isr", 1)).start()
    for addr in getattr(args, "replica", []) or []:
        rhost, _, rport = addr.rpartition(":")
        # a cluster starting in parallel may bring the primary up first:
        # retry attachment until the replica answers (k8s data-plane.yaml)
        for attempt in range(60):
            try:
                server.add_replica(rhost or "127.0.0.1", int(rport))
                break
            except OSError as e:
                if attempt == 59:
                    raise
                print(f"replica {addr} not reachable yet ({e}); retrying",
                      file=sys.stderr)
                _time.sleep(2.0)
        print(f"replica {addr} caught up and in sync", file=sys.stderr)
    print(f"broker listening on {args.host}:{server.port}"
          + (f" (log_dir={args.log_dir})" if args.log_dir else "")
          + (f" role={server.role} min_isr={server.min_isr}"),
          file=sys.stderr)
    try:
        threading_event_wait()
    finally:
        server.stop()
    return 0


def cmd_cluster_worker(args: argparse.Namespace) -> int:
    """One partition-scoped fleet worker PROCESS (cluster/procfleet.py):
    spawned by the elastic coordinator (``ProcessFleet`` — the elastic
    drill, the bench elastic_scaling stage) with a JSON spec naming the
    broker, the handoff server, and this worker's identity. Consumes its
    assigned partitions over the TCP netbroker, checkpoints into the
    network handoff store, drains gracefully on SIGTERM/shutdown, and
    reports state digests in its bye event. Not normally invoked by
    hand."""
    from realtime_fraud_detection_tpu.cluster.procfleet import worker_main

    return worker_main(json.loads(args.spec))


def cmd_state_server(args: argparse.Namespace) -> int:
    """Run the shared state node (Redis-protocol; state/resp.py) — the
    RedisService-role process N scorer replicas share. Blocks until SIGINT."""
    from realtime_fraud_detection_tpu.state.resp import MiniRedisServer

    replica_of = None
    if args.replica_of:
        host, _, port = args.replica_of.rpartition(":")
        replica_of = (host, int(port))
    server = MiniRedisServer(
        host=args.host, port=args.port,
        maxmemory=args.maxmemory, policy=args.policy,
        aof_path=args.aof or None, replica_of=replica_of,
    ).start()
    role = "replica" if server.is_replica else "master"
    print(f"state server (RESP, {role}) listening on "
          f"{args.host}:{server.port}", file=sys.stderr)
    try:
        threading_event_wait()
    finally:
        server.stop()
    return 0


def threading_event_wait() -> None:  # pragma: no cover - blocks forever
    import threading

    threading.Event().wait()


def cmd_quality_eval(args: argparse.Namespace) -> int:
    """Run the production blend-selection protocol (training/blend_eval.py):
    train all 5 branches on a stream-matched segment, admit branches into
    the blend by validation A/B, report held-out quality + ablations. The
    committed QUALITY_r*.json artifacts are produced by exactly this
    command."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.training.blend_eval import (
        BlendEvalConfig,
        run_blend_eval,
    )

    cfg = _dc.replace(
        BlendEvalConfig(), seed=args.seed,
        train_batches=args.train_batches, val_batches=args.val_batches,
        test_batches=args.test_batches)
    result = run_blend_eval(
        cfg, log=lambda m: print(f"[quality-eval] {m}", file=sys.stderr,
                                 flush=True),
        checkpoint_dir=args.checkpoint_dir or None)
    payload = json.dumps(result, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(payload + "\n")
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(payload)
    return 0


def cmd_alert_router(args: argparse.Namespace) -> int:
    """Fan fraud alerts out to notification receivers.

    The reference routes high-risk events EventBridge -> Lambda -> SNS
    (fraud-detection-additional-resources.yaml:364-458: the Lambda just
    reshapes the event and publishes it). Here the same seam is a consumer
    on the ``fraud-alerts`` topic that POSTs each alert to an
    Alertmanager-compatible webhook (deploy/monitoring/alertmanager.yml
    owns the receiver fan-out: email/page/chat — the SNS-subscription
    analog), or prints JSON lines when no webhook is configured (log
    sink). ``--once`` drains and exits (the CronJob/test mode); default
    follows the topic forever.
    """
    import time as _time
    import urllib.request

    from realtime_fraud_detection_tpu.stream import topics as T

    broker = _broker_client(args.broker)
    consumer = broker.consumer([T.ALERTS], args.group)
    routed = 0
    backoff = 1.0
    try:
        while True:
            recs = consumer.poll(500)
            if not recs:
                if args.once:
                    break
                _time.sleep(args.poll_interval)
                continue
            payload = []
            for r in recs:
                a = r.value if isinstance(r.value, dict) else {}
                payload.append({
                    "labels": {
                        "alertname": str(a.get("alert_type",
                                               "FRAUD_DETECTED")),
                        "severity": ("critical"
                                     if str(a.get("decision")) == "DECLINE"
                                     else "warning"),
                        "risk_level": str(a.get("risk_level", "UNKNOWN")),
                        "merchant_id": str(a.get("merchant_id", "")),
                        "service": "rtfd",
                    },
                    "annotations": {
                        "transaction_id": str(a.get("transaction_id", "")),
                        "user_id": str(a.get("user_id", "")),
                        "amount": str(a.get("amount", "")),
                        "fraud_score": str(a.get("fraud_score", "")),
                    },
                })
            if args.webhook:
                req = urllib.request.Request(
                    args.webhook, data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                try:
                    with urllib.request.urlopen(req, timeout=10) as resp:
                        resp.read()
                except OSError as e:  # URLError subclasses OSError
                    # a receiver blip must not crash-loop the daemon:
                    # leave offsets uncommitted (the batch redelivers),
                    # back off, retry. --once propagates the failure so
                    # CronJob/test mode stays loud.
                    if args.once:
                        raise
                    print(f"webhook unreachable ({e}); retrying in "
                          f"{backoff:.0f}s", file=sys.stderr)
                    _time.sleep(backoff)
                    backoff = min(backoff * 2, 60.0)
                    # rewind to the committed offsets (the crash-recovery
                    # path) so the uncommitted batch redelivers
                    consumer.seek_to_committed()
                    continue
            else:
                for item in payload:
                    print(json.dumps(item), flush=True)
            backoff = 1.0
            # commit only after the receiver accepted the batch:
            # at-least-once alert delivery (receivers dedupe on
            # transaction_id, same contract as the predictions topic)
            consumer.commit()
            routed += len(payload)
    except KeyboardInterrupt:  # pragma: no cover - operator stop
        pass
    finally:
        broker.close()
    print(f"routed {routed} alerts", file=sys.stderr)
    return 0


def cmd_qos_drill(args: argparse.Namespace) -> int:
    """Deterministic overload demo for the QoS plane (qos/drill.py): drive
    offered load at N× the sustainable rate through the real stream path on
    a virtual clock; print the admission/ladder/budget outcome as JSON.
    Exit 1 if the admitted p99 missed the configured budget."""
    from realtime_fraud_detection_tpu.qos import run_overload_drill

    summary = run_overload_drill(
        offered_multiplier=args.multiplier,
        overload_s=args.overload_s,
        recovery_s=args.recovery_s,
        max_batch=args.batch,
        budget_ms=args.budget_ms,
        high_frac=args.high_frac,
        low_frac=args.low_frac,
        seed=args.seed,
    )
    print(json.dumps(summary, indent=2))
    return 0 if summary["p99_within_budget"] else 1


def cmd_feedback_drill(args: argparse.Namespace) -> int:
    """Deterministic closed-loop continuous-learning demo (feedback/
    drill.py): virtual clock, real scorer + retraining. Prints the full
    summary, then a compact (<2 KB) parseable verdict as the FINAL stdout
    line (the bench.py convention). Exit 1 unless the whole loop passed:
    drift injected -> prequential AUC dip -> retrain trigger -> gate
    rejects the negative control bit-identically -> genuine candidate
    promoted only on gate-pass -> AUC recovers."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.feedback.drill import (
        FeedbackDrillConfig,
        compact_drill_summary,
        run_feedback_drill,
    )

    cfg = (FeedbackDrillConfig.fast() if args.fast
           else FeedbackDrillConfig())
    cfg = _dc.replace(cfg, seed=args.seed, drift_rate=args.drift_rate)
    summary = run_feedback_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_drill_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_quant_drill(args: argparse.Namespace) -> int:
    """Deterministic quantization drill (scoring/quant_drill.py): the
    score-delta oracle gating the quantized scoring plane. One seeded
    stream through the f32 and the fully quantized fused programs (int8
    BERT + GEMM-form tree kernels): max score divergence pinned below the
    measured calibration-noise floor (what the committed bf16 compute
    policy already moves scores by), zero decision flips at the pinned
    operating point, quality-protocol AUC unchanged, exact GEMM-vs-gather
    leaf equality, >= 3.5x smaller BERT param bytes, and a bit-identical
    second run. Prints the full summary, then a compact (<2 KB) verdict
    as the FINAL stdout line (bench.py convention). Exit 1 unless every
    check passed."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.scoring.quant_drill import (
        QuantDrillConfig,
        compact_quant_summary,
        run_quant_drill,
    )

    cfg = QuantDrillConfig.fast() if args.fast else QuantDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed,
                      replay=not getattr(args, "no_replay", False))
    summary = run_quant_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_quant_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_kernel_drill(args: argparse.Namespace) -> int:
    """Deterministic kernel drill (scoring/kernel_drill.py): the parity
    oracle gating the Pallas kernel plane. One seeded stream through two
    quantized fused programs — stock XLA lowering vs every kernel on
    (fused dequant-matmul + fused score-and-blend epilogue + flash
    attention): max score divergence pinned below the measured
    calibration-noise floor, zero decision flips, exact masked-blend
    equality at every QoS ladder rung, per-kernel interpret-vs-reference
    parity on the served params, zero guard fallbacks, and a bit-identical
    second run. ``--mega`` swaps the kernel side onto the persistent
    megakernel (ops/megakernel.py) and adds its oracle section: fused
    program vs verbatim reference, GEMM-tree leaves exactly equal to the
    pointer-chase descent, per-site counters subsumed to zero, launch
    count collapsed to 1. Prints the full summary, then a compact (<2 KB)
    verdict as the FINAL stdout line (bench.py convention). Exit 1 unless
    every check passed."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.scoring.kernel_drill import (
        KernelDrillConfig,
        compact_kernel_summary,
        run_kernel_drill,
    )

    cfg = KernelDrillConfig.fast() if args.fast else KernelDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed,
                      mega=bool(getattr(args, "mega", False)),
                      replay=not getattr(args, "no_replay", False))
    summary = run_kernel_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_kernel_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_trace_drill(args: argparse.Namespace) -> int:
    """Deterministic tracing drill (obs/trace_drill.py): the real stream
    path on a virtual clock with an injected slow stage. Pins that the
    critical-path analyzer names the right culprit (slow assembly ->
    `assemble`, slow device -> `device_wait`), that the SLO burn rate
    reacts to the injected violation and recovers (engaging/releasing the
    QoS gate), that FIFO/shed behavior is identical with tracing on, and
    that per-txn tracing overhead stays under the pinned bound. Prints
    the full summary, then a compact (<2 KB) verdict as the FINAL stdout
    line (bench.py convention). Exit 1 unless every check passed."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.obs.trace_drill import (
        TraceDrillConfig,
        compact_trace_summary,
        run_trace_drill,
    )

    cfg = TraceDrillConfig.fast() if args.fast else TraceDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed)
    summary = run_trace_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_trace_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_autotune_drill(args: argparse.Namespace) -> int:
    """Deterministic self-tuning drill (tuning/drill.py): replay one
    nonstationary offered-load timeline (diurnal ramp + bursts, virtual
    clock) through a pinned grid of static fixed-deadline configs AND
    through the arrival-aware just-in-time controller. Pins that the
    controller beats every static config on admitted p99 at
    equal-or-better throughput, never sheds high-value traffic, respects
    the QoS budget floor, and that its decisions replay bit-identically.
    Prints the full summary, then a compact (<2 KB) verdict as the FINAL
    stdout line (bench.py convention). Exit 1 unless every check passed."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.tuning.drill import (
        AutotuneDrillConfig,
        compact_autotune_summary,
        run_autotune_drill,
    )

    cfg = AutotuneDrillConfig.fast() if args.fast else AutotuneDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed)
    summary = run_autotune_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_autotune_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_trace_export(args: argparse.Namespace) -> int:
    """Run a traced fake-Kafka job and export the captured window as
    Chrome-trace/Perfetto JSON (load in ui.perfetto.dev or
    chrome://tracing). The flight recorder's ring plus the slowest-N
    exemplars land in the file; a one-line capture summary goes to
    stdout.

    ``--merge ring_w0.json ring_w1.json ...`` skips the local capture
    and instead folds multi-process flight-recorder ring dumps (the
    ``{worker, pid, traces}`` shape ``rtfd obs-drill --rings-out`` and
    the workers' bye frames emit) into ONE fleet trace: a named track
    per OS process and the broker hop drawn as a flow arrow from the
    producer's transit slice to the consuming worker's first slice."""
    if getattr(args, "merge", None):
        from realtime_fraud_detection_tpu.obs.fleetmetrics import (
            merge_chrome_traces,
        )

        dumps = []
        for path in args.merge:
            with open(path) as f:
                dumps.append(json.load(f))
        payload = merge_chrome_traces(dumps)
        with open(args.out, "w") as f:
            json.dump(payload, f)
        print(json.dumps({
            "merged_rings": len(dumps),
            "traces": payload["metadata"]["n_traces"],
            "tracks": payload["metadata"]["tracks"],
            "events": len(payload["traceEvents"]),
            "out": args.out,
        }))
        return 0
    from realtime_fraud_detection_tpu.obs.tracing import Tracer
    from realtime_fraud_detection_tpu.scoring import FraudScorer, ScorerConfig
    from realtime_fraud_detection_tpu.sim.simulator import TransactionGenerator
    from realtime_fraud_detection_tpu.stream import (
        InMemoryBroker,
        JobConfig,
        StreamJob,
    )
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.utils.config import TracingSettings

    gen = TransactionGenerator(num_users=args.users,
                               num_merchants=args.merchants,
                               seed=args.seed, tps=args.tps)
    scorer = FraudScorer(scorer_config=ScorerConfig())
    scorer.seed_profiles(gen.users.profiles(), gen.merchants.profiles())
    tracer = Tracer(TracingSettings(enabled=True,
                                    ring_size=max(64, args.count)))
    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=args.batch, tracing=tracer, emit_features=False))
    produced = 0
    while produced < args.count:
        chunk = min(args.count - produced, 10_000)
        broker.produce_batch(T.TRANSACTIONS, gen.generate_batch(chunk),
                             key_fn=lambda r: str(r["user_id"]))
        produced += chunk
        job.run_until_drained()
    payload = tracer.export_chrome_trace()
    with open(args.out, "w") as f:
        json.dump(payload, f)
    bd = tracer.breakdown()
    print(json.dumps({
        "traces": bd["n"],
        "events": len(payload["traceEvents"]),
        "p99": bd["quantiles"].get("p99"),
        "out": args.out,
    }))
    return 0


def cmd_pool_drill(args: argparse.Namespace) -> int:
    """Deterministic device-pool drill (scoring/pool_drill.py): the real
    pooled scoring path on N host-platform virtual devices, pinning
    bit-equality with single-device scoring, FIFO completion, full
    utilization, hot-swap purity, and the scheduler's >= 3x virtual-time
    scaling. Prints the full summary, then a compact (<2 KB) verdict as
    the FINAL stdout line (bench.py convention). Exit 1 unless every
    check passed.

    Always re-execs onto a virtual N-device CPU host platform (the
    __graft_entry__ wedge-proofing recipe: the parent never initializes a
    backend, so a wedged TPU relay can't stall the drill, and the verdict
    is identical on every box). The measured-on-chip scaling bar lives in
    bench.py's pool_scaling stage instead.
    """
    import subprocess

    if os.environ.get("_RTFD_POOL_DRILL_CHILD") == "1":
        return _pool_drill_inprocess(args)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{args.devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["_RTFD_POOL_DRILL_CHILD"] = "1"
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "pool-drill", "--devices", str(args.devices),
            "--inflight-depth", str(args.inflight_depth),
            "--seed", str(args.seed)]
    if args.fast:
        argv.append("--fast")
    proc = subprocess.run(argv, env=env, timeout=540)
    return proc.returncode


def _pool_drill_inprocess(args: argparse.Namespace) -> int:
    import dataclasses as _dc

    import jax

    jax.config.update("jax_platforms", "cpu")

    from realtime_fraud_detection_tpu.scoring.pool_drill import (
        PoolDrillConfig,
        compact_pool_summary,
        run_pool_drill,
    )

    cfg = PoolDrillConfig.fast() if args.fast else PoolDrillConfig()
    cfg = _dc.replace(cfg, n_devices=args.devices,
                      inflight_depth=args.inflight_depth, seed=args.seed)
    summary = run_pool_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_pool_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_mesh_drill(args: argparse.Namespace) -> int:
    """Deterministic mesh-sharding drill (scoring/mesh_drill.py): the real
    GSPMD data x model serving path on N host-platform virtual devices,
    pinning bit-equality with single-device scoring for every branch-
    placement combo (quantized forms and every QoS ladder rung included),
    no-mixed-params hot swap under the same placement, donated staging
    actually consumed, per-chip BERT bytes <= 60% of replicated at
    model_axis=2, and a bit-identical second pass. Prints the full
    summary, then a compact (<2 KB) verdict as the FINAL stdout line
    (bench.py convention). Exit 1 unless every check passed.

    Always re-execs onto a virtual N-device CPU host platform (the
    pool-drill wedge-proofing recipe: the parent never initializes a
    backend, so a wedged TPU relay can't stall the drill, and the verdict
    is identical on every box). The measured throughput story lives in
    bench.py's mesh_scaling stage — model-sharding is an HBM bet that may
    LOSE on CPU, and the drill refuses to pretend otherwise.
    """
    import subprocess

    if os.environ.get("_RTFD_MESH_DRILL_CHILD") == "1":
        return _mesh_drill_inprocess(args)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{args.devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["_RTFD_MESH_DRILL_CHILD"] = "1"
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "mesh-drill", "--devices", str(args.devices),
            "--model-axis", str(args.model_axis),
            "--inflight-depth", str(args.inflight_depth),
            "--seed", str(args.seed)]
    if args.fast:
        argv.append("--fast")
    if args.no_replay:
        argv.append("--no-replay")
    proc = subprocess.run(argv, env=env, timeout=540)
    return proc.returncode


def _mesh_drill_inprocess(args: argparse.Namespace) -> int:
    import dataclasses as _dc

    import jax

    jax.config.update("jax_platforms", "cpu")

    from realtime_fraud_detection_tpu.scoring.mesh_drill import (
        MeshDrillConfig,
        compact_mesh_summary,
        run_mesh_drill,
    )

    cfg = MeshDrillConfig.fast() if args.fast else MeshDrillConfig()
    cfg = _dc.replace(cfg, n_devices=args.devices,
                      model_axis=args.model_axis,
                      inflight_depth=args.inflight_depth, seed=args.seed,
                      replay_check=not args.no_replay)
    summary = run_mesh_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_mesh_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_chaos_drill(args: argparse.Namespace) -> int:
    """Deterministic combined recovery drill (chaos/drill.py): one seeded
    virtual-clock timeline layering a flash crowd, a broker replica outage
    (real NotEnoughReplicas window + add_replica backfill), device-pool
    replica death + slow device, a label-stream stall, and a coordinated
    fraud ring — proving the QoS/tracing/pool/feedback planes hold
    TOGETHER: zero high-value sheds, effectively-once across the outage,
    ladder + SLO burn recovery, pool retries with FIFO intact, ring AUC
    retrained back past baseline via a gate-passed promotion, and a second
    run replaying bit-identically. Prints the full summary, then a compact
    (<2 KB) verdict as the FINAL stdout line (bench.py convention). Exit 1
    unless every check passed.

    Always re-execs onto a virtual N-device CPU host platform (the
    pool-drill wedge-proofing recipe: the parent never initializes a
    backend, so a wedged TPU relay can't stall the drill, and the verdict
    is identical on every box).
    """
    import subprocess

    if os.environ.get("_RTFD_CHAOS_DRILL_CHILD") == "1":
        return _chaos_drill_inprocess(args)
    from realtime_fraud_detection_tpu.chaos.drill import ChaosDrillConfig

    devices = args.devices or (ChaosDrillConfig.fast().n_devices
                               if args.fast else ChaosDrillConfig().n_devices)
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{devices}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["_RTFD_CHAOS_DRILL_CHILD"] = "1"
    argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
            "chaos-drill", "--devices", str(devices)]
    if args.seed is not None:       # explicit flag wins over chaos.seed
        argv += ["--seed", str(args.seed)]
    if args.config:
        argv += ["--config", args.config]
    if args.fast:
        argv.append("--fast")
    if args.no_replay:
        argv.append("--no-replay")
    proc = subprocess.run(argv, env=env, timeout=540)
    return proc.returncode


def _chaos_drill_inprocess(args: argparse.Namespace) -> int:
    import dataclasses as _dc

    import jax

    jax.config.update("jax_platforms", "cpu")

    from realtime_fraud_detection_tpu.chaos.drill import (
        ChaosDrillConfig,
        apply_chaos_settings,
        compact_chaos_summary,
        run_chaos_drill,
    )

    cfg = ChaosDrillConfig.fast() if args.fast else ChaosDrillConfig()
    if args.config:
        from realtime_fraud_detection_tpu.utils.config import Config

        cfg = apply_chaos_settings(cfg, Config.from_file(args.config).chaos)
    cfg = _dc.replace(cfg, replay_check=not args.no_replay,
                      **({"seed": args.seed}
                         if args.seed is not None else {}),
                      **({"n_devices": args.devices} if args.devices else {}))
    summary = run_chaos_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_chaos_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_shard_drill(args: argparse.Namespace) -> int:
    """Deterministic partition-parallel worker drill (cluster/drill.py):
    a simulated population (1M users at the full config) scored across
    >= 4 partition-scoped StreamJob workers sharing one broker log, with
    a mid-stream worker kill (chaos WorkerKill injector) recovered by
    checkpointed state handoff + committed-gap state replay. Pins zero
    lost / double-scored transactions, gap-free committed offsets,
    per-key ordering, sharded state digest-equal to a single-worker
    oracle run, consistent-hash router agreement with fleet ownership
    (only the dead worker's partitions move), and a bit-identical second
    run. Prints the full summary, then a compact (<2 KB) verdict as the
    FINAL stdout line (bench.py convention). Exit 1 unless every check
    passed. Pure host arithmetic on a virtual clock — no device needed."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.cluster.drill import (
        ShardDrillConfig,
        compact_shard_summary,
        run_shard_drill,
    )

    cfg = ShardDrillConfig.fast() if args.fast else ShardDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed,
                      replay_check=not args.no_replay,
                      **({"n_workers": args.workers} if args.workers
                         else {}))
    summary = run_shard_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_shard_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_elastic_drill(args: argparse.Namespace) -> int:
    """Deterministic elastic-cluster drill (cluster/elastic_drill.py): a
    seeded diurnal-ramp timeline over a 10M-user id space scored by a
    fleet of REAL OS worker processes over the TCP netbroker, with the
    network-served handoff store, a real SIGKILL at the busiest worker
    mid-peak, and the autoscale controller growing the fleet ahead of the
    forecast peak and draining it after. Pins effectively-once scoring
    (zero lost / conflicting-scored, gap-free offsets, state + scores
    equal to a single-process oracle), returncode -9 from the kill,
    bounded consistent-hash movement, and a digest-identical second
    fresh run (host-timing fields excluded). Prints the full summary,
    then a compact (<2 KB) verdict as the FINAL stdout line (bench.py
    convention). Exit 1 unless every check passed. Pure host arithmetic
    in the workers — no device needed, but REAL processes, REAL TCP,
    REAL signals."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.cluster.elastic_drill import (
        ElasticDrillConfig,
        compact_elastic_summary,
        run_elastic_drill,
    )

    cfg = ElasticDrillConfig.fast() if args.fast else ElasticDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed,
                      replay_check=not args.no_replay)
    summary = run_elastic_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_elastic_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_partition_drill(args: argparse.Namespace) -> int:
    """Deterministic split-brain partition drill (chaos/partition_drill
    .py): >= 4 real OS worker processes over the TCP netbroker while the
    link-fault layer (chaos/netfaults.py) degrades the network itself —
    an asymmetric partition at the busiest worker (deaf to the
    coordinator, data path alive: evicted by session expiry, fenced at
    the broker's producer-generation seam, its post-fence produces
    REFUSED and counted), a slow link under load (healthy-vs-window p99
    reported as degraded_network), and a full partition that heals
    (bounded backoff, fenced discovery, fresh rejoin). Pins zero lost /
    conflicting-scored vs a single-process oracle, gap-free offsets,
    state equality, detection inside the session-timeout bound, both
    rejoins with no double-ownership interval, bounded byte-identical
    duplicates, and a digest-identical second fresh run. Prints the full
    summary, then a compact (<2 KB) verdict as the FINAL stdout line
    (bench.py convention). Exit 1 unless every check passed. Pure host
    arithmetic in the workers — no device needed, but REAL processes,
    REAL TCP, REAL link faults."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.chaos.partition_drill import (
        PartitionDrillConfig,
        compact_partition_summary,
        run_partition_drill,
    )

    cfg = (PartitionDrillConfig.fast() if args.fast
           else PartitionDrillConfig())
    cfg = _dc.replace(cfg, seed=args.seed,
                      replay_check=not args.no_replay,
                      **({"n_workers": args.workers} if args.workers
                         else {}))
    summary = run_partition_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_partition_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_obs_drill(args: argparse.Namespace) -> int:
    """Deterministic distributed observability drill (obs/obs_drill.py):
    one seeded timeline over >= 2 real OS worker processes with the
    fleet tracing plane live — every produced record carries a wire
    trace carrier the consuming worker re-hydrates, so stitched traces
    span ingest -> broker transit (producer stamp vs consume stamp) ->
    the worker's stages -> remote graph-fetch child spans to the OTHER
    worker's fetch server. Pins: carrier losses inside the netfault
    window counted EXACTLY (fresh local roots, never a gap or wedge),
    fleet metric sums exactly equal the per-worker bye counters, the
    slow-worker injection attributed to that worker's device_wait, one
    named Chrome-trace track per process with a broker-transit flow
    arrow per stitched trace, traced-vs-untraced makespan ratio under
    the pinned bound, and a digest-identical second fresh run. Prints
    the full summary, then a compact (<2 KB) verdict as the FINAL
    stdout line (bench.py convention). Exit 1 unless every check
    passed."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.obs.obs_drill import (
        ObsDrillConfig,
        compact_obs_summary,
        run_obs_drill,
    )

    cfg = ObsDrillConfig.fast() if args.fast else ObsDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed,
                      replay_check=not args.no_replay,
                      rings_out=getattr(args, "rings_out", "") or "",
                      **({"n_workers": args.workers} if args.workers
                         else {}))
    summary = run_obs_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_obs_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_graph_drill(args: argparse.Namespace) -> int:
    """Deterministic entity-graph drill (graph/drill.py): the typed
    user/device/merchant/IP graph maintained from the transaction flow,
    serve-time two-hop neighborhood sampling through the columnar
    assemble path feeding the GNN branch, and cross-partition neighbor
    fetch over TCP — driven end-to-end across >= 2 REAL partition-scoped
    workers with a coordinated FraudRing straddling the shards. Pins
    ring-phase AUC lift of the graph-on blend over the trees-only
    incumbent on the drill's truth ledger, remote fetches demonstrably
    exercised, graceful degrade (zero lost/errored scores) under an
    injected netfault partition window, columnar == serial bit-exact
    with graph sampling on, and a digest-identical fresh second run.
    Prints the full summary, then a compact (<2 KB) verdict as the FINAL
    stdout line (bench.py convention). Exit 1 unless every check passed.
    Real fused-program scoring on whatever backend is live (CPU-sized by
    default), REAL TCP between the workers' graph-fetch planes."""
    import dataclasses as _dc

    from realtime_fraud_detection_tpu.graph.drill import (
        GraphDrillConfig,
        compact_graph_summary,
        run_graph_drill,
    )

    cfg = GraphDrillConfig.fast() if args.fast else GraphDrillConfig()
    cfg = _dc.replace(cfg, seed=args.seed,
                      replay_check=not args.no_replay,
                      **({"n_workers": args.workers} if args.workers
                         else {}))
    summary = run_graph_drill(cfg)
    print(json.dumps(summary), flush=True)
    print(json.dumps(compact_graph_summary(summary),
                     separators=(",", ":")), flush=True)
    return 0 if summary["passed"] else 1


def cmd_lint(args: argparse.Namespace) -> int:
    """Run the repo-native invariant checker (analysis/lint.py) — or, with
    --lockwatch, the dynamic lock-order watcher under all thirteen
    deterministic drills (analysis/lockwatch.py). Exit 0 only when clean.

    The static rules (wall-clock, d2h, metrics, lock-order, determinism,
    pragma-hygiene) encode THIS repo's invariants — virtual-clock
    determinism, the pre-pull-safe device-timing discipline, honest
    counter-delta Prometheus mirrors, score-lock discipline — and are
    enforced in tier-1 (tests/test_analysis.py), so `rtfd lint` on a
    committed tree must print `clean`.
    """
    if getattr(args, "lockwatch_run", ""):
        # child mode (one drill, one process): emits a single JSON line.
        # pool-drill / chaos-drill / mesh-drill children are launched with
        # the virtual 8-device host platform env by the parent below.
        from realtime_fraud_detection_tpu.analysis.lockwatch import (
            run_drill_watched,
        )

        rep = run_drill_watched(args.lockwatch_run, fast=args.fast,
                                seed=args.seed)
        print(json.dumps(rep), flush=True)
        return 0 if (rep["lockwatch"]["ok"] and rep["drill_passed"]) else 1
    if args.lockwatch:
        return _lockwatch_all_drills(args)
    from realtime_fraud_detection_tpu.analysis.lint import run_lint

    code, out = run_lint(args.paths or None, fmt=args.format)
    print(out)
    return code


def _lockwatch_all_drills(args: argparse.Namespace) -> int:
    """Parent mode: one child process per drill (pool-drill needs the
    virtual multi-device platform set before jax initializes; the others
    inherit the session platform). Prints a per-drill table plus a final
    compact JSON verdict line (bench.py convention)."""
    import subprocess

    from realtime_fraud_detection_tpu.analysis.lockwatch import (
        LOCKWATCH_DRILLS,
    )

    results: Dict[str, Any] = {}
    ok = True
    for drill in LOCKWATCH_DRILLS:
        env = dict(os.environ)
        if drill in ("pool-drill", "chaos-drill", "mesh-drill"):
            env.pop("PALLAS_AXON_POOL_IPS", None)
            flags = " ".join(
                f for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith(
                    "--xla_force_host_platform_device_count"))
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count=8").strip()
            env["JAX_PLATFORMS"] = "cpu"
        argv = [sys.executable, "-m", "realtime_fraud_detection_tpu",
                "lint", "--lockwatch-run", drill, "--seed", str(args.seed)]
        if args.fast:
            argv.append("--fast")
        print(f"[lockwatch] {drill} ...", file=sys.stderr, flush=True)
        rep: Dict[str, Any] = {}
        try:
            proc = subprocess.run(argv, env=env, timeout=540,
                                  capture_output=True, text=True)
        except subprocess.TimeoutExpired as e:
            # a hung drill is a failed drill, not a crashed parent: the
            # remaining drills still run and the final verdict line still
            # prints (callers parse it)
            rep = {"drill": drill, "drill_passed": False,
                   "lockwatch": {"ok": False,
                                 "error": f"timeout after {e.timeout}s"}}
        else:
            for line in reversed(proc.stdout.splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        rep = json.loads(line)
                        break
                    except json.JSONDecodeError:
                        continue
            if not rep:
                rep = {"drill": drill, "drill_passed": False,
                       "lockwatch": {"ok": False,
                                     "error": (proc.stderr or "")[-500:]}}
        lw = rep.get("lockwatch") or {}
        results[drill] = {
            "drill_passed": rep.get("drill_passed"),
            "ok": lw.get("ok"),
            "locks": len(lw.get("locks") or ()),
            "acquisitions": lw.get("acquisitions"),
            "edges": len(lw.get("edges") or ()),
            "cycles": lw.get("cycles") or [],
            "violations": lw.get("violations") or [],
            "warnings": len(lw.get("warnings") or ()),
            "max_hold_ms": (max(lw.get("max_hold_ms", {}).values())
                            if lw.get("max_hold_ms") else 0.0),
        }
        ok = ok and bool(lw.get("ok")) and bool(rep.get("drill_passed"))
        if results[drill]["ok"] and rep.get("drill_passed"):
            status = "clean"
        elif results[drill]["ok"]:
            status = "DRILL FAILED (locks clean)"
        else:
            status = "VIOLATIONS"
        print(f"[lockwatch] {drill}: {status} "
              f"(locks={results[drill]['locks']} "
              f"acq={results[drill]['acquisitions']} "
              f"edges={results[drill]['edges']} "
              f"max_hold={results[drill]['max_hold_ms']}ms)",
              file=sys.stderr, flush=True)
    print(json.dumps({"lockwatch": results, "passed": ok},
                     separators=(",", ":")), flush=True)
    return 0 if ok else 1


def cmd_health_check(args: argparse.Namespace) -> int:
    """Probe a running scoring service (health-check.sh analog)."""
    import urllib.error
    import urllib.request

    url = args.url.rstrip("/") + "/health"
    try:
        with urllib.request.urlopen(url, timeout=args.timeout) as resp:
            body = json.loads(resp.read())
    except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
        print(json.dumps({"healthy": False, "error": str(e)}))
        return 1
    healthy = body.get("status") == "healthy"
    print(json.dumps({"healthy": healthy, **body}))
    return 0 if healthy else 1


def cmd_topics(args: argparse.Namespace) -> int:
    """Print the topic contract; with --broker --create, materialize it on
    a running broker (create-topics.sh:101-160 analog)."""
    from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS

    broker = None
    if getattr(args, "create", False):
        if not args.broker:
            print("--create requires --broker host:port", file=sys.stderr)
            return 2
        from realtime_fraud_detection_tpu.stream.netbroker import (
            NetBrokerClient,
        )

        host, _, port = args.broker.rpartition(":")
        broker = NetBrokerClient(host=host or "127.0.0.1", port=int(port))
    for t in TOPIC_SPECS:
        flag = " compacted" if t.compacted else ""
        if broker is not None:
            broker.create_topic(t.name, t.partitions)
            print(f"created {t.name:28s} partitions={t.partitions}{flag}")
        else:
            print(f"{t.name:28s} partitions={t.partitions}{flag}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="realtime_fraud_detection_tpu",
        description="TPU-native realtime fraud detection framework")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("simulate", help="generate transaction JSON lines")
    _add_sim_args(sp)
    sp.add_argument("--count", type=int, default=1000)
    sp.add_argument("--output", default="-")
    sp.add_argument("--broker", default="",
                    help="produce to a broker (host:port, or comma list) at ~tps instead "
                         "of writing JSON lines")
    sp.set_defaults(fn=cmd_simulate)

    sp = sub.add_parser("run-job", help="run the streaming scoring job")
    _add_sim_args(sp)
    sp.add_argument("--count", type=int, default=10_000,
                    help="self-generate this many txns; 0 = consume-only "
                         "from --broker")
    sp.add_argument("--duration", type=float, default=0.0,
                    help="consume-only runtime seconds (0 = forever)")
    sp.add_argument("--broker", default="",
                    help="external broker host:port, or a comma list for the replicated cluster (default: in-memory)")
    sp.add_argument("--state", default="",
                    help="shared state server host:port (RESP)")
    sp.add_argument("--batch", type=int, default=256)
    sp.add_argument("--pipeline-depth", type=int, default=2,
                    help="in-flight microbatches (3 overlaps the result "
                         "transfer with a full batch period; see "
                         "JobConfig.pipeline_depth for the state-staleness "
                         "tradeoff)")
    sp.add_argument("--analytics", action="store_true",
                    help="attach the windowed-analytics stage")
    sp.add_argument("--enrichment", action="store_true",
                    help="blend the 6-category feature score into the "
                         "enriched output (FeatureEnrichmentProcessor)")
    sp.add_argument("--checkpoint-dir", default="",
                    help="save params+state checkpoints per chunk")
    sp.add_argument("--metadata-db", default="",
                    help="SQLite path for durable job/checkpoint metadata")
    sp.add_argument("--qos", action="store_true",
                    help="enable the deadline-aware QoS plane (admission + "
                         "degradation ladder + latency budgets)")
    sp.add_argument("--qos-budget-ms", type=float, default=20.0,
                    help="per-transaction latency budget")
    sp.add_argument("--qos-rate", type=float, default=0.0,
                    help="admission token rate in txn/s (0 = unlimited)")
    sp.add_argument("--overlap-assembly", action="store_true",
                    help="background host-assembly stage: assemble batch "
                         "N+1 while batch N runs on device (scoring/"
                         "host_pipeline.py; see JobConfig.overlap_assembly "
                         "for the staleness tradeoff)")
    sp.add_argument("--device-pool", action="store_true",
                    help="replicate the model onto every addressable "
                         "device and dispatch microbatches round-robin "
                         "across per-device in-flight queues "
                         "(scoring/device_pool.py)")
    sp.add_argument("--inflight-depth", type=int, default=2,
                    help="per-replica in-flight batches for --device-pool "
                         "(>=2 keeps each device's compute back-to-back)")
    sp.add_argument("--feedback", action="store_true",
                    help="enable the continuous-learning plane: delayed "
                         "labels -> prequential metrics -> drift-gated "
                         "retrain-and-promote (feedback/)")
    sp.add_argument("--feedback-delay-scale", type=float, default=1e-4,
                    help="compresses the chargeback label-delay "
                         "distribution (1.0 = realistic days)")
    sp.add_argument("--trace", action="store_true",
                    help="enable the per-transaction tracing plane "
                         "(obs/tracing.py): flight recorder, latency "
                         "breakdown, SLO burn rate in the summary")
    sp.add_argument("--autotune", action="store_true",
                    help="self-tuning host pipeline (tuning/): arrival-"
                         "aware just-in-time batch closing + online "
                         "config tuner replace the fixed assembly "
                         "deadline")
    sp.add_argument("--quant", action="store_true",
                    help="quantized scoring plane (models/quant.py): "
                         "weight-only int8 BERT + GEMM-form tree kernels "
                         "(the rtfd quant-drill gated configuration)")
    sp.add_argument("--kernels", action="store_true",
                    help="Pallas kernel plane (ops/): fused dequant-"
                         "matmul + fused score-and-blend epilogue + flash "
                         "attention (the rtfd kernel-drill gated "
                         "configuration)")
    sp.add_argument("--mega", action="store_true",
                    help="persistent megakernel (ops/megakernel.py): one "
                         "Pallas program scores the whole packed "
                         "microbatch (implies --kernels; the rtfd "
                         "kernel-drill --mega gated configuration)")
    sp.set_defaults(fn=cmd_run_job)

    sp = sub.add_parser("serve", help="run the scoring HTTP service")
    sp.add_argument("--host", default="")
    sp.add_argument("--port", type=int, default=None)
    sp.add_argument("--state", default="",
                    help="shared state server host:port (RESP); also "
                         "honors RTFD_STATE_ADDR")
    sp.add_argument("--config", default="", help="JSON config file")
    sp.add_argument("--checkpoint-dir", default="",
                    help="restore model params (e.g. from `train`) at startup")
    sp.add_argument("--quality-artifact", default="",
                    help="deploy the measured blend from a quality-eval "
                         "JSON (e.g. QUALITY_r05.json): enabled branches "
                         "+ weights become the artifact's selected_blend")
    sp.add_argument("--qos", action="store_true",
                    help="enable the deadline-aware QoS plane (also "
                         "toggleable at runtime via POST /qos)")
    sp.add_argument("--qos-budget-ms", type=float, default=0.0,
                    help="per-transaction latency budget (0 = default)")
    sp.add_argument("--qos-rate", type=float, default=0.0,
                    help="admission token rate in txn/s (0 = unlimited)")
    sp.add_argument("--overlap-assembly", action="store_true",
                    help="two-phase pipelined microbatcher: dispatch batch "
                         "N+1 while batch N waits on the device "
                         "(serving.overlap_assembly)")
    sp.add_argument("--device-pool", action="store_true",
                    help="replicated multi-device scoring pool "
                         "(serving.device_pool; implies the two-phase "
                         "pipelined microbatcher)")
    sp.add_argument("--inflight-depth", type=int, default=None,
                    help="per-replica in-flight batches for --device-pool "
                         "(default: serving.inflight_depth, 2)")
    sp.add_argument("--allow-arch-mismatch", action="store_true",
                    help="combine a checkpoint and quality artifact even "
                         "when their recorded text-encoder architectures "
                         "differ, and restore a checkpoint whose recorded "
                         "quantization mode crosses this server's quant "
                         "config (both refused by default)")
    sp.add_argument("--quant", action="store_true",
                    help="quantized scoring plane (models/quant.py): "
                         "weight-only int8 BERT + GEMM-form tree kernels "
                         "(the rtfd quant-drill gated configuration)")
    sp.add_argument("--kernels", action="store_true",
                    help="Pallas kernel plane (ops/): fused dequant-"
                         "matmul + fused score-and-blend epilogue + flash "
                         "attention (the rtfd kernel-drill gated "
                         "configuration)")
    sp.add_argument("--mega", action="store_true",
                    help="persistent megakernel (ops/megakernel.py): one "
                         "Pallas program scores the whole packed "
                         "microbatch (implies --kernels; the rtfd "
                         "kernel-drill --mega gated configuration)")
    sp.add_argument("--trace", action="store_true",
                    help="enable the per-transaction tracing plane: "
                         "GET /latency/breakdown, GET /slo, trace_* "
                         "Prometheus series")
    sp.add_argument("--autotune", action="store_true",
                    help="self-tuning host pipeline (tuning/): the "
                         "request microbatcher closes just-in-time "
                         "against the arrival forecast; GET /autotune, "
                         "autotune_* Prometheus series")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("train", help="train tree models on synthetic data")
    _add_sim_args(sp)
    sp.add_argument("--rows", type=int, default=10_000,
                    help="synthetic rows (model_trainer.py:123)")
    sp.add_argument("--trees", type=int, default=100)
    sp.add_argument("--neural", action="store_true",
                    help="also train the LSTM/GNN/BERT branches")
    sp.add_argument("--out", default="./checkpoints")
    sp.set_defaults(fn=cmd_train)

    sp = sub.add_parser("validate",
                        help="quality-gate a checkpoint on a fresh stream")
    _add_sim_args(sp)
    sp.add_argument("--checkpoint-dir", required=True)
    sp.add_argument("--step", type=int, default=None)
    sp.add_argument("--rows", type=int, default=4096)
    sp.add_argument("--min-auc", type=float, default=0.80)
    sp.add_argument("--metrics-out", default=None,
                    help="write a Prometheus textfile here")
    sp.set_defaults(fn=cmd_validate)

    sp = sub.add_parser("broker", help="run the durable log broker (TCP)")
    sp.add_argument("--host", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=9092)
    sp.add_argument("--log-dir", default="",
                    help="write-ahead segment dir (empty = in-memory only)")
    sp.add_argument("--role", choices=("primary", "replica"),
                    default="primary",
                    help="replica = read-only standby until promoted")
    sp.add_argument("--min-isr", type=int, default=1,
                    help="in-sync copies (self included) a produce must "
                         "reach before the ack (create-topics.sh minISR=2 "
                         "analog)")
    sp.add_argument("--replica", action="append", default=[],
                    metavar="HOST:PORT",
                    help="attach a running replica server (repeatable); "
                         "each is caught up then joins the ISR")
    sp.set_defaults(fn=cmd_broker)

    import dataclasses as _dcs
    from types import SimpleNamespace as _NS

    from realtime_fraud_detection_tpu.training.blend_eval import (
        BlendEvalConfig as _BLEND_DEFAULTS_CLS,
    )

    # read field defaults WITHOUT instantiating (the bert default factory
    # would pull jax into every CLI invocation's parser build)
    _BLEND_DEFAULTS = _NS(**{
        f.name: f.default for f in _dcs.fields(_BLEND_DEFAULTS_CLS)
        if f.default is not _dcs.MISSING
    })
    sp = sub.add_parser("quality-eval",
                        help="run the blend-selection quality protocol")
    sp.add_argument("--output", default="",
                    help="write the evidence JSON here (default stdout)")
    sp.add_argument("--seed", type=int, default=3)
    # defaults mirror BlendEvalConfig exactly — the CLI and the Python
    # entry must make identical admission decisions
    sp.add_argument("--train-batches", type=int,
                    default=_BLEND_DEFAULTS.train_batches)
    sp.add_argument("--val-batches", type=int,
                    default=_BLEND_DEFAULTS.val_batches)
    sp.add_argument("--test-batches", type=int,
                    default=_BLEND_DEFAULTS.test_batches)
    sp.add_argument("--checkpoint-dir", default="",
                    help="also save the trained+calibrated branches as a "
                         "serving checkpoint (deploy with serve "
                         "--checkpoint-dir + --quality-artifact)")
    sp.set_defaults(fn=cmd_quality_eval)

    sp = sub.add_parser("alert-router",
                        help="fan fraud alerts out to notification receivers")
    sp.add_argument("--broker", default="127.0.0.1:9092",
                    help="broker host:port to consume fraud-alerts from")
    sp.add_argument("--webhook", default="",
                    help="Alertmanager /api/v2/alerts URL "
                         "(empty = JSON lines on stdout)")
    sp.add_argument("--group", default="alert-router",
                    help="consumer group (offset checkpointing)")
    sp.add_argument("--once", action="store_true",
                    help="drain the topic and exit (CronJob/test mode)")
    sp.add_argument("--poll-interval", type=float, default=1.0)
    sp.set_defaults(fn=cmd_alert_router)

    sp = sub.add_parser("cluster-worker",
                        help="run one partition-scoped fleet worker "
                             "process (spawned by the elastic cluster "
                             "coordinator)")
    sp.add_argument("--spec", required=True,
                    help="JSON worker spec from the coordinator "
                         "(broker/handoff addresses, worker id, group, "
                         "partition count, batch/cost knobs)")
    sp.set_defaults(fn=cmd_cluster_worker)

    sp = sub.add_parser("state-server",
                        help="run the shared state server (Redis protocol)")
    sp.add_argument("--host", default="0.0.0.0")
    sp.add_argument("--port", type=int, default=6379)
    sp.add_argument("--maxmemory", type=int, default=1 << 30,
                    help="eviction threshold in bytes (0 = unlimited; "
                         "default 1 GiB like the reference redis-master.conf)")
    sp.add_argument("--policy", default="allkeys-lru",
                    choices=["allkeys-lru", "noeviction"])
    sp.add_argument("--aof", default="",
                    help="append-only persistence file (empty = volatile)")
    sp.add_argument("--replica-of", default="",
                    help="host:port of the primary to replicate from "
                         "(read-only replica; promote by restarting without)")
    sp.set_defaults(fn=cmd_state_server)

    sp = sub.add_parser("qos-drill",
                        help="deterministic QoS overload demo "
                             "(virtual clock, real stream path)")
    sp.add_argument("--multiplier", type=float, default=2.0,
                    help="offered load as a multiple of the sustainable "
                         "rate")
    sp.add_argument("--overload-s", type=float, default=1.5,
                    help="virtual seconds of overload")
    sp.add_argument("--recovery-s", type=float, default=1.5,
                    help="virtual seconds of post-overload trickle")
    sp.add_argument("--batch", type=int, default=64)
    sp.add_argument("--budget-ms", type=float, default=20.0)
    sp.add_argument("--high-frac", type=float, default=0.2,
                    help="fraction of traffic in the high (never-shed) "
                         "class")
    sp.add_argument("--low-frac", type=float, default=0.5,
                    help="fraction of traffic in the low (sheds-first) "
                         "class")
    sp.add_argument("--seed", type=int, default=7)
    sp.set_defaults(fn=cmd_qos_drill)

    sp = sub.add_parser("feedback-drill",
                        help="deterministic closed-loop continuous-"
                             "learning demo (virtual clock, real "
                             "retraining)")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--seed", type=int, default=5)
    sp.add_argument("--drift-rate", type=float, default=0.08,
                    help="fraction of the stream turned into the drifted "
                         "fraud pattern")
    sp.set_defaults(fn=cmd_feedback_drill)

    sp = sub.add_parser("trace-drill",
                        help="deterministic tracing drill (virtual "
                             "clock, injected slow stage, SLO burn + "
                             "overhead pins)")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--seed", type=int, default=7)
    sp.set_defaults(fn=cmd_trace_drill)

    sp = sub.add_parser("autotune-drill",
                        help="deterministic self-tuning drill (virtual "
                             "clock, diurnal+burst load, JIT controller "
                             "vs a pinned static-config grid)")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--seed", type=int, default=7)
    sp.set_defaults(fn=cmd_autotune_drill)

    sp = sub.add_parser("quant-drill",
                        help="deterministic quantization drill (score-"
                             "delta oracle): int8 BERT + GEMM-form tree "
                             "kernels vs the f32 fused program — "
                             "divergence below calibration noise, zero "
                             "decision flips, AUC unchanged, bit-"
                             "identical replay")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--seed", type=int, default=11)
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the bit-identical second run (bench "
                         "stage mode; the replay gate is waived)")
    sp.set_defaults(fn=cmd_quant_drill)

    sp = sub.add_parser("kernel-drill",
                        help="deterministic kernel drill (parity oracle): "
                             "the Pallas kernel plane vs the stock XLA "
                             "lowering — divergence below calibration "
                             "noise, zero decision flips, exact masked-"
                             "blend equality at every QoS rung, per-"
                             "kernel interpret-vs-reference parity, bit-"
                             "identical replay")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--seed", type=int, default=13)
    sp.add_argument("--mega", action="store_true",
                    help="serve the kernel side through the persistent "
                         "megakernel (ops/megakernel.py: one program per "
                         "microbatch) and add its oracle section")
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the bit-identical second run (bench "
                         "stage mode; the replay gate is waived)")
    sp.set_defaults(fn=cmd_kernel_drill)

    sp = sub.add_parser("trace-export",
                        help="run a traced fake-Kafka job and export "
                             "Chrome-trace/Perfetto JSON")
    _add_sim_args(sp)
    sp.add_argument("--count", type=int, default=2048,
                    help="transactions to score through the traced job")
    sp.add_argument("--batch", type=int, default=128)
    sp.add_argument("--out", default="trace.json",
                    help="Chrome-trace JSON output path (open in "
                         "ui.perfetto.dev)")
    sp.add_argument("--merge", nargs="+", default=None, metavar="RING",
                    help="merge per-worker ring dumps ({worker, pid, "
                         "traces} JSON, e.g. from `obs-drill "
                         "--rings-out`) into one fleet trace instead of "
                         "capturing locally")
    sp.set_defaults(fn=cmd_trace_export)

    sp = sub.add_parser("pool-drill",
                        help="deterministic device-pool drill (virtual "
                             "8-device host platform, real pooled "
                             "scoring path)")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--devices", type=int, default=8,
                    help="virtual host-platform device count")
    sp.add_argument("--inflight-depth", type=int, default=2,
                    help="per-replica in-flight batches")
    sp.add_argument("--seed", type=int, default=7)
    sp.set_defaults(fn=cmd_pool_drill)

    sp = sub.add_parser("mesh-drill",
                        help="deterministic mesh-sharding drill (virtual "
                             "8-device host platform, real GSPMD "
                             "data x model serving path): bit-equality "
                             "per branch placement, hot swap, donation, "
                             "per-chip param bytes")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--devices", type=int, default=8,
                    help="virtual host-platform device count")
    sp.add_argument("--model-axis", type=int, default=2,
                    help="model-parallel axis size per mesh replica")
    sp.add_argument("--inflight-depth", type=int, default=2,
                    help="in-flight programs per mesh replica")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second bit-identical pass")
    sp.set_defaults(fn=cmd_mesh_drill)

    sp = sub.add_parser("chaos-drill",
                        help="deterministic combined recovery drill: "
                             "flash crowd + broker outage + device faults "
                             "+ fraud ring on one virtual-clock timeline")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--devices", type=int, default=0,
                    help="virtual host-platform device count for the pool "
                         "(0 = the config's default: 4 full, 2 fast)")
    sp.add_argument("--seed", type=int, default=None,
                    help="timeline seed (default: chaos.seed from --config "
                         "if given, else 11)")
    sp.add_argument("--config", default="",
                    help="JSON config file; the chaos.* block reshapes the "
                         "fault timeline (outage/stall windows, flash "
                         "multipliers, ring shape)")
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second bit-identical replay run")
    sp.set_defaults(fn=cmd_chaos_drill)

    sp = sub.add_parser("shard-drill",
                        help="deterministic partition-parallel worker "
                             "drill: key-sharded state across >= 4 "
                             "workers, mid-stream worker kill, "
                             "checkpointed handoff, oracle state "
                             "equality")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--workers", type=int, default=0,
                    help="fleet size (0 = the config default, 4)")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second bit-identical replay run")
    sp.set_defaults(fn=cmd_shard_drill)

    sp = sub.add_parser("elastic-drill",
                        help="deterministic elastic-cluster drill: >= 8 "
                             "real OS worker processes over the TCP "
                             "netbroker, network handoff, autoscale "
                             "ahead of a diurnal peak, real SIGKILL "
                             "mid-peak, oracle state equality")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second fresh determinism run")
    sp.set_defaults(fn=cmd_elastic_drill)

    sp = sub.add_parser("partition-drill",
                        help="deterministic split-brain partition drill: "
                             ">= 4 real OS worker processes under link "
                             "chaos (asymmetric/slow/full partitions), "
                             "broker producer-generation fencing, "
                             "session-expiry eviction + fresh rejoin, "
                             "oracle state equality")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--workers", type=int, default=0,
                    help="fleet size (0 = the config default)")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second fresh determinism run")
    sp.set_defaults(fn=cmd_partition_drill)

    sp = sub.add_parser("obs-drill",
                        help="deterministic distributed observability "
                             "drill: >= 2 real OS worker processes with "
                             "cross-process trace carriers, fleet metric "
                             "aggregation pinned exact, slow-worker p99 "
                             "attribution, carrier loss counted under a "
                             "netfault window, merged Chrome-trace "
                             "export with broker-transit flow arrows")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--workers", type=int, default=0,
                    help="fleet size (0 = the config default)")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--rings-out", default="",
                    help="directory for per-worker flight-recorder ring "
                         "dumps (the `trace-export --merge` input)")
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second fresh determinism run")
    sp.set_defaults(fn=cmd_obs_drill)

    sp = sub.add_parser("graph-drill",
                        help="deterministic entity-graph drill: typed "
                             "user/device/merchant/IP graph + two-hop "
                             "sampling feeding the GNN branch across >= 2 "
                             "partition workers, cross-partition neighbor "
                             "fetch over TCP, netfault degrade window, "
                             "ring-phase AUC lift vs the trees-only "
                             "incumbent")
    sp.add_argument("--fast", action="store_true",
                    help="tier-1 sizes (the CI smoke configuration)")
    sp.add_argument("--workers", type=int, default=0,
                    help="fleet size (0 = the config default)")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--no-replay", action="store_true",
                    help="skip the second fresh determinism run")
    sp.set_defaults(fn=cmd_graph_drill)

    sp = sub.add_parser("lint",
                        help="repo-native invariant checker (static rules "
                             "+ --lockwatch dynamic lock-order watcher)")
    sp.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package tree "
                         "+ bench.py)")
    sp.add_argument("--format", choices=("text", "json"), default="text")
    sp.add_argument("--lockwatch", action="store_true",
                    help="run the thirteen deterministic drills under the "
                         "instrumented lock watcher instead of the static "
                         "rules")
    sp.add_argument("--lockwatch-run", default="",
                    metavar="DRILL", help=argparse.SUPPRESS)  # child mode
    sp.add_argument("--fast", action="store_true",
                    help="drill fast configs (the CI smoke sizes)")
    sp.add_argument("--seed", type=int, default=7)
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("bench", help="run the TPU benchmark")
    sp.add_argument("--quant", action="store_true",
                    help="measure the pool_scaling stage on the "
                         "quantized scoring plane (int8 BERT + GEMM-form "
                         "tree kernels); the int8 calibration pulls the "
                         "f32 weights host-side once at scorer build")
    sp.add_argument("--mesh", action="store_true",
                    help="measure the mesh_scaling stage on a tunneled "
                         "TPU too (replicated vs data-sharded vs "
                         "data x model + per-chip param bytes); CPU runs "
                         "it unconditionally")
    sp.add_argument("--kernels", action="store_true",
                    help="measure the pool_scaling stage on the Pallas "
                         "kernel plane too (fused dequant-matmul + fused "
                         "epilogue + flash attention; labels suffixed "
                         "-kern)")
    sp.add_argument("--mega", action="store_true",
                    help="measure the pool_scaling stage on the "
                         "persistent megakernel too (one program per "
                         "microbatch; implies --kernels, labels suffixed "
                         "-mega)")
    sp.set_defaults(fn=cmd_bench)

    sp = sub.add_parser("health-check", help="probe a running service")
    sp.add_argument("--url", default="http://127.0.0.1:8000")
    sp.add_argument("--timeout", type=float, default=5.0)
    sp.set_defaults(fn=cmd_health_check)

    sp = sub.add_parser("topics", help="print the topic contract")
    sp.add_argument("--broker", default="",
                    help="broker host:port to create the topics on")
    sp.add_argument("--create", action="store_true",
                    help="materialize the contract on --broker")
    sp.set_defaults(fn=cmd_topics)
    return p


def configure_process_logging() -> None:
    """Structured logging for a CLI-launched process (reference
    logging_config.py is imported by each service entry point): LOG_LEVEL /
    LOG_FILE via the config env layer; with a log file, every JSON line is
    stamped with service_name. Called from the real process entry points
    only — library callers (and tests) keep their own logging config.
    Never fatal: a bad LOG_LEVEL must not take down --help."""
    import logging

    try:
        from realtime_fraud_detection_tpu.obs.logs import setup_logging
        from realtime_fraud_detection_tpu.utils.config import Config

        cfg = Config()
        setup_logging(level=cfg.monitoring.log_level,
                      json_file=cfg.monitoring.log_file or None,
                      service_name=cfg.service_name)
    except Exception as e:  # noqa: BLE001 — fall back, don't crash the CLI
        logging.basicConfig(level=logging.INFO)
        logging.getLogger(__name__).warning(
            "logging setup failed (%s); using basicConfig", e)


def entrypoint() -> int:
    """Console-script entry (pyproject [project.scripts]): identical
    behavior to ``python -m realtime_fraud_detection_tpu``."""
    configure_process_logging()
    return main()


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    configure_process_logging()
    raise SystemExit(main())

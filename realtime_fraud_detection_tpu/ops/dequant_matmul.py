"""Fused int8 dequant-matmul Pallas kernels for the quantized BERT branch.

The weight-only int8 layout (models/quant.py) stores every dense kernel as
``{"qw": i8[K, N], "scale": f32[N], "b": f32[N]}`` (per-output-channel
scales) and the embedding tables as ``{"qe": i8[rows, H], "scale": f32[rows]}``
(per-row scales). The XLA path in models/bert.py:_dense widens the weight
``(i8 -> compute_dtype) * scale`` and trusts the compiler to fuse that read
into the matmul; these kernels make the fusion explicit so the widened
kernel never exists outside VMEM — the MXU streams i8 weight blocks and
dequantizes in registers right before the dot.

Both kernels keep the XLA expressions as their numerics oracle
(``dequant_matmul_reference`` / ``dequant_rows_reference`` are verbatim the
bert.py math) and carry a shared ``*_supported`` shape predicate: the traced
code consults it to fall back to XLA on hostile shapes, and the scorer's
host-side dispatch counters consult the SAME predicate so the
``kernel_fallback_total`` series stays honest.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Full-K blocks: every _dense site in the text encoder has K in {H, FFN}
# (128/256 tiny, 768/3072 full-size) — small enough to stream whole columns
# through VMEM. Cap guards the full-size FFN plus headroom.
_MAX_FULL_K = 4096
# Whole-array cap for the elementwise row-dequant kernel (elements).
_MAX_ROWS_ELEMS = 1 << 21

_BLOCK_M_CANDIDATES = (128, 64, 32, 16, 8)


def _pick_block_m(m: int) -> int:
    for cand in _BLOCK_M_CANDIDATES:
        if m % cand == 0:
            return cand
    return 0


def matmul_supported(m: int, k: int, n: int) -> bool:
    """True when the fused dequant-matmul kernel handles [m,k]@[k,n].

    Requirements: lane-aligned N (the i8 weight tile is (32, 128)), a
    VMEM-resident K, and an M divisible by one of the row-block sizes.
    Shared by the trace-time guard in models/bert.py and the host-side
    fallback counting in FraudScorer.dispatch_assembled.
    """
    return (n % 128 == 0 and k % 128 == 0 and k <= _MAX_FULL_K
            and _pick_block_m(m) > 0)


def rows_supported(rows: int, h: int) -> bool:
    """True when the per-row dequant kernel handles an [rows, h] gather
    result: i8-tile-aligned rows, lane-aligned H, whole array in VMEM."""
    return (rows % 32 == 0 and h % 128 == 0
            and rows * h <= _MAX_ROWS_ELEMS)


def dequant_matmul_reference(x, qw, scale, b, compute_dtype=jnp.bfloat16):
    """XLA oracle — verbatim the models/bert.py:_dense int8 branch."""
    w = qw.astype(compute_dtype) * scale.astype(compute_dtype)
    return x.astype(compute_dtype) @ w + b


def _dequant_matmul_kernel(x_ref, qw_ref, scale_ref, b_ref, o_ref, *,
                           compute_dtype):
    x = x_ref[...].astype(compute_dtype)                    # [bm, K]
    # dequantize in-register, same elementwise order as the reference so
    # the widened block is bit-identical — it just never leaves VMEM
    w = qw_ref[...].astype(compute_dtype) * scale_ref[...].astype(compute_dtype)
    acc = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                        # [bm, bn] f32
    # round once to compute_dtype (what the reference matmul emits), then
    # widen for the f32 bias add — keeps the epilogue bit-close to XLA
    o_ref[...] = acc.astype(compute_dtype).astype(jnp.float32) + b_ref[...]


@functools.partial(jax.jit, static_argnames=("compute_dtype", "interpret"))
def dequant_matmul(
    x: jax.Array,        # [M, K] any float dtype
    qw: jax.Array,       # i8[K, N]
    scale: jax.Array,    # f32[N] per-output-channel
    b: jax.Array,        # f32[N]
    compute_dtype=jnp.bfloat16,
    interpret: bool = False,
) -> jax.Array:
    """Fused ``x @ dequant(qw, scale) + b`` -> f32[M, N].

    Grid is (M/block_m, N/128); each program owns one output tile and
    reads the full K extent. Callers must pre-check ``matmul_supported``;
    ``interpret=True`` runs through the Pallas interpreter (CPU-testable).
    """
    m, k = x.shape
    _, n = qw.shape
    if not matmul_supported(m, k, n):
        raise ValueError(f"unsupported dequant_matmul shape [{m},{k}]x[{k},{n}]")
    block_m = _pick_block_m(m)
    block_n = 128

    # scale/bias staged as [1, N] f32 so their trailing dims satisfy the
    # TPU lane tiling (same trick as the flash-attention mask)
    scale2 = scale.astype(jnp.float32)[None, :]
    b2 = b.astype(jnp.float32)[None, :]

    grid = (m // block_m, n // block_n)
    kernel = functools.partial(_dequant_matmul_kernel,
                               compute_dtype=compute_dtype)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, k), lambda mi, ni: (mi, 0)),
            pl.BlockSpec((k, block_n), lambda mi, ni: (0, ni)),
            pl.BlockSpec((1, block_n), lambda mi, ni: (0, ni)),
            pl.BlockSpec((1, block_n), lambda mi, ni: (0, ni)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n), lambda mi, ni: (mi, ni)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x, qw, scale2, b2)


def dequant_rows_reference(q: jax.Array, scale: jax.Array) -> jax.Array:
    """XLA oracle — the models/bert.py:_embedding_rows widen of a gathered
    i8 row block: f32 rows = ``q * scale[:, None]``."""
    return q.astype(jnp.float32) * scale.astype(jnp.float32)[:, None]


def _dequant_rows_kernel(q_ref, scale_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * scale_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def dequant_rows(
    q: jax.Array,        # i8[rows, H] — already-gathered embedding rows
    scale: jax.Array,    # f32[rows] per-row
    interpret: bool = False,
) -> jax.Array:
    """Per-row dequant widen -> f32[rows, H].

    The arbitrary-index gather itself stays an XLA i8 gather (a Pallas
    gather buys nothing at embedding widths); this kernel fuses the widen
    x scale so only i8 rows plus a scale vector cross HBM. Single-program
    whole-array kernel — the gather result is batch-sized, not table-sized.
    """
    rows, h = q.shape
    if not rows_supported(rows, h):
        raise ValueError(f"unsupported dequant_rows shape [{rows},{h}]")
    scale2 = scale.astype(jnp.float32)[:, None]              # [rows, 1]
    return pl.pallas_call(
        _dequant_rows_kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (0, 0)),
            pl.BlockSpec((rows, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((rows, h), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), jnp.float32),
        interpret=interpret,
    )(q, scale2)

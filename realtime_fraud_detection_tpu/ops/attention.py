"""Blockwise (flash) attention Pallas kernel for TPU.

The text branch's encoder is the only model in the system big enough to have
a real attention cost (DistilBERT, seq 128-512). XLA's stock attention is
fine at these sizes, but the framework keeps the kernel blockwise from day
one (SURVEY.md 5.7): the k-loop with an online softmax is exactly the shape
that extends to ring attention over the ``seq`` mesh axis for long-context
work — each k-block step becomes a ring hop.

Layout: q, k, v are [B, H, S, D]; ``key_mask`` is bool[B, S] marking valid
(non-pad) keys. Grid is (B, H, S/block_q); each program owns one q block and
streams k/v blocks through VMEM with running (max, denominator, accumulator)
state, f32 throughout the softmax accumulation per the precision policy.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, key_mask: jax.Array | None = None
) -> jax.Array:
    """Plain XLA attention (numerics oracle + CPU fallback). [B,H,S,D]."""
    d = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) / np.sqrt(d)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, :], scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


def _flash_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, *, block_k: int, scale: float):
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, D]
    seq_len = k_ref.shape[2]
    num_kb = seq_len // block_k
    bq, d = q.shape

    def body(kb, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        mask_blk = mask_ref[0, 0, pl.ds(kb * block_k, block_k)] > 0.0  # [bk]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                    # [bq, bk]
        s = jnp.where(mask_blk[None, :], s, NEG_INF)
        m_new = jnp.maximum(m_prev, s.max(axis=1))          # [bq]
        alpha = jnp.exp(m_prev - m_new)                     # rescale old state
        p = jnp.exp(s - m_new[:, None])                     # [bq, bk]
        l_new = l_prev * alpha + p.sum(axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, _, l = jax.lax.fori_loop(0, num_kb, body, (acc0, m0, l0))
    o_ref[0, 0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k", "interpret"))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    key_mask: jax.Array | None = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Blockwise attention. q/k/v: [B, H, S, D] -> [B, H, S, D].

    ``interpret=True`` runs the kernel through the Pallas interpreter
    (CPU-testable); on TPU leave it False.
    """
    b, h, s, d = q.shape
    if key_mask is None:
        key_mask = jnp.ones((b, s), bool)
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq len {s} must be divisible by blocks ({block_q},{block_k})")

    # [B, 1, S] f32 so the mask block's trailing dims (1, S) satisfy the TPU
    # (8, 128)-or-full tiling constraint (bool [B, S] blocks do not lower)
    mask_f32 = key_mask.astype(jnp.float32)[:, None, :]

    grid = (b, h, s // block_q)
    kernel = functools.partial(
        _flash_kernel, block_k=block_k,
        scale=1.0 / float(np.sqrt(d)),  # rtfd-lint: allow[d2h] d is a host shape int
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s), lambda bi, hi, qi: (bi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=interpret,
    )(q, k, v, mask_f32)

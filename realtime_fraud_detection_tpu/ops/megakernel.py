"""Persistent ensemble megakernel: one Pallas program per microbatch.

PR 15's per-site kernels (dequant-matmul, fused epilogue) still leave the
ensemble as a CHAIN of XLA computations — five branch programs, the rule
program and the blend, each handing its intermediate back through HBM.
This kernel scores an entire packed microbatch end-to-end in ONE Pallas
program: the grid is persistent over batch blocks (TPU grids execute
sequentially on a core, so ``grid=(B/block,)`` IS the persistent loop),
the tree and isolation-forest branches run as Hummingbird GEMM-form
contractions (models/trees.py's compile-time ancestor-structure
constants, arXiv:2010.04804), per-branch probabilities accumulate in a
VMEM scratch lane, and the fused epilogue's combine math
(ops/epilogue.combine_matrix — one definition, two kernels) is inlined
as the final stage. The kernel's output IS the extended
[B, 8 + M + M + 2] packed matrix ``FraudScorer._build_responses``
already reads — branch intermediates never exist in HBM.

QoS ladder rungs arrive as ``mega_valid``: a compile-time tuple of
branch-validity booleans. Disabled branches are pruned at trace time
(their prediction lane is written as zero and their weight masked in the
blend, exactly like the runtime mask), so each rung is its own cached
program — the jit cache is the per-rung program cache, and rung changes
never retrace an already-visited rung.

``megakernel_reference`` is a verbatim composition of the very branch
functions the kernel replaces (same functions, same GEMM tree form, no
Pallas) — the parity oracle for the CPU interpreter drill. The
``mega_plan``/``mega_supported`` predicates are shared by the trace-time
guard in scoring/pipeline.py and the host-side fallback accounting in
FraudScorer, so a trace-time fallback to the PR 15 per-site kernels is
always mirrored by ``kernel_fallback_total``.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from realtime_fraud_detection_tpu.ops.epilogue import combine_matrix

# NOTE: model-branch modules (models/*, features/rules, scoring/pipeline)
# are imported lazily inside functions: models.bert imports ops.attention,
# so a module-level import here would cycle through ops/__init__ while
# models.bert is still initializing.

# Per-core VMEM is ~16 MiB; budget leaves headroom for Mosaic's own
# staging. The block-row working set (activations x block + resident
# params) must fit under this for a block size to be eligible.
_MEGA_VMEM_BUDGET = 14 * (1 << 20)

# Largest-first candidates; a block must divide the bucket size exactly
# (buckets are powers of two, core/batching.py) so the grid tiles B.
MEGA_BLOCK_CANDIDATES: Tuple[int, ...] = (128, 64, 32, 16, 8)

# Below this the launch chain is already cheap and padding waste dominates
# — bucket 1 stays on the per-site kernel path (an honest fallback).
MEGA_MIN_BATCH = 8


def _unwrap(fn):
    """The traceable body of a jitted branch function: calling the jit
    wrapper inside a Pallas kernel would nest dispatch; the unwrapped
    function is the same math."""
    return getattr(fn, "__wrapped__", fn)


def mega_param_bytes(models) -> int:
    """Resident parameter bytes for the whole 5-branch pytree. Shape/dtype
    only — works on tracers and concrete arrays alike."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(models):
        total += math.prod(leaf.shape) * jnp.dtype(leaf.dtype).itemsize
    return int(total)


def mega_act_row_bytes(bert_config, *, text_len: int, seq_len: int,
                       feature_dim: int, tree_onehot: int) -> int:
    """Per-batch-row activation working set (bytes, f32) — the dominant
    simultaneous intermediates inside one block iteration:

    - BERT: hidden + residual + FFN activations ``S*(2H+F)`` plus the
      attention probability tensor ``heads*S^2`` per row;
    - trees + iforest: the GEMM one-hot leaf tensors ``T*L`` per ensemble
      (``tree_onehot`` = sum over both);
    - LSTM: the ``T*F`` history slab the scan walks.

    docs/kernels.md reproduces this budget math per bucket size.
    """
    h = bert_config.hidden_size
    f = bert_config.intermediate_size
    bert = text_len * (2 * h + f) * 4 + bert_config.num_heads * text_len * text_len * 4
    trees = tree_onehot * 4
    lstm = seq_len * feature_dim * 4
    return int(bert + trees + lstm + feature_dim * 4)


def mega_block(b: int, param_bytes: int, act_row_bytes: int) -> int:
    """Largest block size that divides ``b`` and fits the VMEM budget;
    0 when none does (caller must fall back)."""
    for cand in MEGA_BLOCK_CANDIDATES:
        if b % cand:
            continue
        if cand * act_row_bytes + param_bytes <= _MEGA_VMEM_BUDGET:
            return cand
    return 0


def mega_supported(b: int, param_bytes: int, act_row_bytes: int,
                   has_two_hop: bool = False) -> bool:
    """True when the megakernel handles a ``b``-row microbatch. Shared by
    the trace-time guard in scoring/pipeline.py and the host-side
    fallback counting in FraudScorer._record_kernel_dispatch, so the two
    always agree. Two-hop typed-graph frontiers ([B, K, K2, D]) blow the
    per-row budget and stay on the per-site path."""
    return (b >= MEGA_MIN_BATCH and not has_two_hop
            and mega_block(b, param_bytes, act_row_bytes) > 0)


def mega_plan(models, bert_config, *, b: int, text_len: int, seq_len: int,
              feature_dim: int, has_two_hop: bool) -> Dict[str, Any]:
    """One shared shape/VMEM plan for a dispatch: the same numbers feed
    the trace-time fallback and the host-side counters."""
    pb = mega_param_bytes(models)
    t1, l1 = models.trees.leaf.shape
    t2, l2 = models.iforest.path_length.shape
    arb = mega_act_row_bytes(bert_config, text_len=text_len,
                             seq_len=seq_len, feature_dim=feature_dim,
                             tree_onehot=t1 * l1 + t2 * l2)
    return {
        "param_bytes": pb,
        "act_row_bytes": arb,
        "block": mega_block(b, pb, arb),
        "has_two_hop": bool(has_two_hop),
        "supported": mega_supported(b, pb, arb, has_two_hop),
    }


def mega_launch_accounting(b: int, m: int,
                           mega_valid: Optional[Sequence[bool]] = None
                           ) -> Dict[str, int]:
    """Launch-count / HBM-traffic accounting: the chain dispatches one
    program per enabled branch plus the rule program and the blend; the
    megakernel dispatches ONE. ``intermediate_bytes_eliminated`` counts
    the branch-boundary tensors that previously round-tripped through
    HBM between those programs (per-branch prediction vectors, the
    stacked [B, M] matrix, the validity mask and the rule score)."""
    valid = tuple(mega_valid) if mega_valid is not None else (True,) * m
    branches = sum(1 for v in valid if v)
    programs_chain = branches + 2
    eliminated = (branches * b * 4    # per-branch f32[B] predictions
                  + b * m * 4         # stacked preds f32[B, M]
                  + b * m * 4         # validity mask f32[B, M]
                  + b * 4)            # rule score f32[B]
    return {
        "programs_chain": int(programs_chain),
        "programs_mega": 1,
        "launches_per_batch_chain": int(programs_chain),
        "launches_per_batch_mega": 1,
        "intermediate_bytes_eliminated": int(eliminated),
    }


def _branch_columns(models, batch, mega_valid: Tuple[bool, ...],
                    bert_config, tree_paths=None, iforest_paths=None) -> list:
    """The five branch probabilities, GEMM tree form, in registry order —
    the SAME composition inside the kernel body and in the reference.
    Rung-disabled branches are pruned at trace time (zero lane). The
    ``*_paths`` operands carry the ancestor-structure constants into the
    Pallas body (models/trees.py); None = the lru_cached defaults."""
    from realtime_fraud_detection_tpu.models.bert import bert_predict
    from realtime_fraud_detection_tpu.models.gnn import gnn_logits
    from realtime_fraud_detection_tpu.models.isolation_forest import (
        iforest_predict,
    )
    from realtime_fraud_detection_tpu.models.lstm import lstm_logits
    from realtime_fraud_detection_tpu.models.trees import tree_ensemble_predict

    features = batch.features
    zeros = jnp.zeros((features.shape[0],), jnp.float32)
    return [
        _unwrap(tree_ensemble_predict)(models.trees, features, kernel="gemm",
                                       paths=tree_paths)
        if mega_valid[0] else zeros,
        jax.nn.sigmoid(
            _unwrap(lstm_logits)(models.lstm, batch.history,
                                 batch.history_len))
        if mega_valid[1] else zeros,
        bert_predict(models.bert, batch.token_ids, batch.token_mask,
                     bert_config, use_pallas=False)
        if mega_valid[2] else zeros,
        jax.nn.sigmoid(
            gnn_logits(models.gnn, features, batch.user_feat,
                       batch.merchant_feat, batch.user_neigh_feat,
                       batch.user_neigh_mask, batch.merch_neigh_feat,
                       batch.merch_neigh_mask))
        if mega_valid[3] else zeros,
        _unwrap(iforest_predict)(models.iforest, features, kernel="gemm",
                                 paths=iforest_paths)
        if mega_valid[4] else zeros,
    ]


def _packed_tail(preds, ep, rule, txn, m: int) -> jax.Array:
    """Assemble the extended packed matrix from the blend output — the
    layout scoring/pipeline.py's OUT_COLUMNS + preds + EXT_COLUMNS."""
    from realtime_fraud_detection_tpu.scoring.pipeline import _key_factors

    kf = _key_factors(txn)
    head = jnp.concatenate([
        ep[:, 0:4],
        rule[:, None],
        kf["high_amount"].astype(jnp.float32)[:, None],
        kf["unusual_hour"].astype(jnp.float32)[:, None],
        kf["high_risk_payment"].astype(jnp.float32)[:, None],
    ], axis=1)
    return jnp.concatenate(
        [head, preds.astype(jnp.float32), ep[:, 4:4 + m],
         ep[:, 4 + m:6 + m]], axis=1)


def megakernel_reference(models, batch, params, *,
                         mega_valid: Tuple[bool, ...],
                         bert_config=None) -> jax.Array:
    """XLA oracle: the exact branch functions + combine the kernel fuses,
    composed as a plain chain -> the same extended packed f32[B, 2M+10]
    matrix. Rung-disabled branches are pruned identically."""
    from realtime_fraud_detection_tpu.features.rules import rule_score
    from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG

    bert_config = bert_config or TINY_CONFIG
    mega_valid = tuple(bool(v) for v in mega_valid)
    m = len(mega_valid)
    preds = jnp.stack(
        _branch_columns(models, batch, mega_valid, bert_config), axis=1)
    rule = rule_score(batch.txn)
    mvf = jnp.asarray(mega_valid, jnp.float32)
    vf = batch.valid.astype(jnp.float32)[:, None] * mvf[None, :]
    ep = combine_matrix(
        preds.astype(jnp.float32), vf, rule.astype(jnp.float32)[:, None],
        params.weights.astype(jnp.float32)[None, :],
        params.confidence_multipliers.astype(jnp.float32)[None, :],
        strategy=int(params.strategy),
        fraud_threshold=float(params.fraud_threshold),        # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        confidence_threshold=float(params.confidence_threshold),  # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        decline=float(params.decline_threshold),              # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        review=float(params.review_threshold),                # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        monitor=float(params.monitor_threshold))              # rtfd-lint: allow[d2h] static host field (pytree_node=False)
    return _packed_tail(preds, ep, rule, batch.txn, m)


def _row_block_map(nd: int):
    return lambda i, _nd=nd: (i,) + (0,) * (_nd - 1)


def _whole_map(nd: int):
    return lambda i, _nd=nd: (0,) * max(_nd, 1)


@functools.partial(jax.jit, static_argnames=(
    "mega_valid", "bert_config", "block", "strategy", "fraud_threshold",
    "confidence_threshold", "decline", "review", "monitor", "interpret"))
def _mega_call(models, batch, w2, cm2, *, mega_valid, bert_config, block,
               strategy, fraud_threshold, confidence_threshold, decline,
               review, monitor, interpret):
    from realtime_fraud_detection_tpu.features.rules import rule_score
    from realtime_fraud_detection_tpu.models.trees import _complete_tree_paths

    batch_leaves, batch_def = jax.tree_util.tree_flatten(batch)
    model_leaves, model_def = jax.tree_util.tree_flatten(models)
    b = int(batch_leaves[0].shape[0])
    m = len(mega_valid)
    width = 2 * m + 10  # OUT_COLUMNS(8) + preds(M) + contributions(M) + 2

    # A kernel body cannot close over concrete arrays, so everything it
    # reads rides as an operand: the branch params, the blend vectors,
    # the QoS validity mask, and the Hummingbird ancestor-structure
    # constants for both tree ensembles (models/trees.py).
    mvf2 = jnp.asarray(
        [1.0 if v else 0.0 for v in mega_valid], jnp.float32)[None, :]
    tc, td = _complete_tree_paths(int(np.log2(models.trees.leaf.shape[1])))
    ic, idx = _complete_tree_paths(
        int(np.log2(models.iforest.path_length.shape[1])))
    extra = [w2, cm2, mvf2, jnp.asarray(tc), jnp.asarray(td),
             jnp.asarray(ic), jnp.asarray(idx)]
    n_extra = len(extra)

    # Pallas operand staging: bools ride as i32 (restored inside), 0-d
    # param leaves (tree base_score, iforest c_psi) ride as shape-(1,).
    batch_dtypes = []
    staged_batch = []
    for leaf in batch_leaves:
        arr = jnp.asarray(leaf)
        batch_dtypes.append(arr.dtype)
        staged_batch.append(
            arr.astype(jnp.int32) if arr.dtype == jnp.bool_ else arr)
    param_meta = []
    staged_params = []
    for leaf in list(model_leaves) + extra:
        arr = jnp.asarray(leaf)
        param_meta.append(arr.ndim == 0)
        staged_params.append(arr.reshape(1) if arr.ndim == 0 else arr)

    nb = len(staged_batch)
    npar = len(staged_params)
    in_specs = (
        [pl.BlockSpec((block,) + a.shape[1:], _row_block_map(a.ndim))
         for a in staged_batch]
        + [pl.BlockSpec(a.shape, _whole_map(a.ndim)) for a in staged_params]
    )

    def body(*refs):
        b_refs, p_refs = refs[:nb], refs[nb:nb + npar]
        o_ref, preds_ref = refs[nb + npar], refs[nb + npar + 1]
        bl = []
        for ref, dt in zip(b_refs, batch_dtypes):
            v = ref[...]
            bl.append(v != 0 if dt == jnp.bool_ else v)
        blk_batch = jax.tree_util.tree_unflatten(batch_def, bl)
        pv = []
        for ref, was_scalar in zip(p_refs, param_meta):
            v = ref[...]
            pv.append(v.reshape(()) if was_scalar else v)
        blk_models = jax.tree_util.tree_unflatten(
            model_def, pv[:-n_extra])
        wv, cmv, mvf, k_tc, k_td, k_ic, k_id = pv[-n_extra:]

        # branch stage: each enabled branch writes its VMEM scratch lane
        cols = _branch_columns(blk_models, blk_batch, mega_valid,
                               bert_config, tree_paths=(k_tc, k_td),
                               iforest_paths=(k_ic, k_id))
        for j in range(m):
            preds_ref[:, j] = cols[j].astype(jnp.float32)
        preds = preds_ref[...]

        # epilogue stage, inlined (ops/epilogue.combine_matrix)
        rule = _unwrap(rule_score)(blk_batch.txn).astype(jnp.float32)
        vf = blk_batch.valid.astype(jnp.float32)[:, None] * mvf
        ep = combine_matrix(
            preds, vf, rule[:, None], wv, cmv, strategy=strategy,
            fraud_threshold=fraud_threshold,
            confidence_threshold=confidence_threshold, decline=decline,
            review=review, monitor=monitor)
        o_ref[...] = _packed_tail(preds, ep, rule, blk_batch.txn, m)

    return pl.pallas_call(
        body,
        grid=(b // block,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block, width), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, width), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block, m), jnp.float32)],
        interpret=interpret,
    )(*staged_batch, *staged_params)


def fused_megakernel(models, batch, params, *,
                     mega_valid: Tuple[bool, ...], bert_config=None,
                     interpret: bool = False,
                     block: Optional[int] = None) -> jax.Array:
    """Score a whole microbatch in one persistent Pallas program.

    Returns the extended packed f32[B, 2M+10] matrix (OUT_COLUMNS, model
    predictions, contributions, rule_decision/rule_risk) — exactly what
    ``FraudScorer._build_responses`` reads. ``mega_valid`` is the QoS
    rung as a static branch-validity tuple; each distinct rung compiles
    (and caches) its own pruned program. Callers must pre-check
    ``mega_supported``/``mega_plan`` — unsupported shapes raise.
    """
    from realtime_fraud_detection_tpu.models.bert import TINY_CONFIG

    bert_config = bert_config or TINY_CONFIG
    mega_valid = tuple(bool(v) for v in mega_valid)
    b = int(batch.features.shape[0])
    if block is None:
        plan = mega_plan(
            models, bert_config, b=b,
            text_len=int(batch.token_ids.shape[1]),
            seq_len=int(batch.history.shape[1]),
            feature_dim=int(batch.features.shape[1]),
            has_two_hop=batch.user_neigh2_feat is not None)
        if not plan["supported"]:
            raise ValueError(
                f"unsupported megakernel dispatch b={b} plan={plan} "
                "(callers must pre-check mega_supported)")
        block = plan["block"]
    if b % block:
        raise ValueError(f"block {block} does not tile batch {b}")
    return _mega_call(
        models, batch,
        params.weights.astype(jnp.float32)[None, :],
        params.confidence_multipliers.astype(jnp.float32)[None, :],
        mega_valid=mega_valid, bert_config=bert_config, block=int(block),
        strategy=int(params.strategy),
        fraud_threshold=float(params.fraud_threshold),        # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        confidence_threshold=float(params.confidence_threshold),  # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        decline=float(params.decline_threshold),              # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        review=float(params.review_threshold),                # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        monitor=float(params.monitor_threshold),              # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        interpret=interpret)

"""Fused score-and-blend epilogue Pallas kernel.

The last stage of the fused program — five branch probabilities + the
branch-validity/QoS mask + blend weights + the decision/risk ladders —
is pure VPU elementwise/reduce work, but the host used to re-derive two
pieces of it per record in ``FraudScorer._build_responses``: the
per-model explanation contributions (weights x preds) and, on the QoS
rules-only rung, the whole decision ladder over the rule score. This
kernel runs the ensemble combine (ensemble/combine.py math, verbatim)
on-chip and emits those derived columns alongside, so finalize becomes
pure column reads: no per-batch host blend math at all.

Layout: one program, whole arrays resident in VMEM — the operands are
[B, M] with M=5 and B bucket-bounded, orders of magnitude under the tile
budget; a grid would only add index arithmetic. The XLA oracle is
``epilogue_reference`` (a composition of the very functions the kernel
replaces), and ``epilogue_supported`` is the shared shape guard.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from realtime_fraud_detection_tpu.ensemble.combine import (
    VOTING,
    WEIGHTED_AVERAGE,
    combine_predictions,
)
from realtime_fraud_detection_tpu.features.rules import (
    APPROVE,
    APPROVE_WITH_MONITORING,
    DECLINE,
    REVIEW,
    RISK_LEVEL_THRESHOLDS,
    risk_level_code,
)

# whole-array kernel: bound B so the [B, M(+6)] operands stay far inside
# VMEM even at the largest batch bucket
_MAX_EPILOGUE_ROWS = 1 << 16


def epilogue_supported(b: int, m: int) -> bool:
    """True when the fused epilogue kernel handles a [b, m] blend. Shared
    by the trace-time guard in scoring/pipeline.py and the host-side
    fallback counting in FraudScorer.dispatch_assembled."""
    return 0 < b <= _MAX_EPILOGUE_ROWS and m >= 1


# decision codes ride the kernel as exact small floats — all four are
# module-level host ints (features/rules.py), never device values
_APPROVE_F = float(APPROVE)                  # rtfd-lint: allow[d2h] host int constant
_MONITOR_F = float(APPROVE_WITH_MONITORING)  # rtfd-lint: allow[d2h] host int constant
_REVIEW_F = float(REVIEW)                    # rtfd-lint: allow[d2h] host int constant
_DECLINE_F = float(DECLINE)                  # rtfd-lint: allow[d2h] host int constant


def _rule_ladder(prob, decline, review, monitor):
    """Probability rungs only (no confidence clause) — exactly the host
    rules-only recompute in FraudScorer._build_responses."""
    return jnp.where(
        prob >= decline, _DECLINE_F,
        jnp.where(prob >= review, _REVIEW_F,
                  jnp.where(prob >= monitor, _MONITOR_F, _APPROVE_F)))


def _risk_code_f32(prob):
    code = jnp.zeros_like(prob)
    for t in RISK_LEVEL_THRESHOLDS:
        code = code + (prob >= t).astype(jnp.float32)
    return code


def epilogue_reference(preds: jax.Array, valid: jax.Array, rule: jax.Array,
                       params) -> Dict[str, jax.Array]:
    """XLA oracle: the exact functions the kernel fuses — ensemble
    combine + explanation contributions + the rules-only ladder."""
    out = dict(combine_predictions(preds, valid, params,
                                   with_confidences=False))
    out["model_contributions"] = params.weights[None, :] * preds
    out["rule_decision"] = _rule_ladder(
        rule, params.decline_threshold, params.review_threshold,
        params.monitor_threshold).astype(jnp.int32)
    out["rule_risk"] = risk_level_code(rule)
    return out


def combine_matrix(preds, vf, rule, wvec, cm, *,
                   strategy, fraud_threshold, confidence_threshold,
                   decline, review, monitor):
    """On-chip ensemble combine -> the [B, M+6] epilogue matrix.

    Shared by the standalone fused-epilogue kernel below and the
    persistent megakernel (ops/megakernel.py), which inlines this as its
    final stage — one definition of the blend/ladder math, two kernels.
    Operands: preds/vf f32[B, M], rule f32[B, 1], wvec/cm f32[1, M];
    statics are EnsembleParams' pytree_node=False fields.
    """
    # per-model confidence + masked weights (ensemble/combine.py:94-112)
    conf = jnp.minimum(1.0, jnp.abs(preds - 0.5) * 2.0 * cm) * vf
    w = wvec * vf

    # weighted average
    w_total = w.sum(axis=1, keepdims=True)                   # [B, 1]
    wa_prob = jnp.where(w_total > 0,
                        (preds * w).sum(axis=1, keepdims=True)
                        / jnp.maximum(w_total, 1e-12), 0.5)
    wa_conf = jnp.where(w_total > 0,
                        (conf * w).sum(axis=1, keepdims=True)
                        / jnp.maximum(w_total, 1e-12), 0.0)

    # voting
    n_valid = vf.sum(axis=1, keepdims=True)
    votes = (((preds > fraud_threshold).astype(jnp.float32)) * vf).sum(
        axis=1, keepdims=True)
    vote_prob = jnp.where(n_valid > 0,
                          votes / jnp.maximum(n_valid, 1.0), 0.0)
    vote_conf = jnp.where(n_valid > 0,
                          conf.sum(axis=1, keepdims=True)
                          / jnp.maximum(n_valid, 1.0), 0.0)

    # stacking (falls back to weighted average at zero total confidence)
    conf_total = conf.sum(axis=1, keepdims=True)
    stack_prob = jnp.where(conf_total > 0,
                           (preds * conf).sum(axis=1, keepdims=True)
                           / jnp.maximum(conf_total, 1e-12), wa_prob)
    stack_conf = jnp.where(conf_total > 0,
                           conf_total / jnp.maximum(n_valid, 1.0), wa_conf)

    if strategy == WEIGHTED_AVERAGE:
        prob, confidence = wa_prob, wa_conf
    elif strategy == VOTING:
        prob, confidence = vote_prob, vote_conf
    else:
        prob, confidence = stack_prob, stack_conf

    # decision + risk ladders (ints ride as exact small floats)
    by_prob = _rule_ladder(prob, decline, review, monitor)
    decision = jnp.where(confidence < confidence_threshold,
                         _REVIEW_F, by_prob)
    risk = _risk_code_f32(prob)

    contributions = wvec * preds                             # [B, M]
    rule_decision = _rule_ladder(rule, decline, review, monitor)
    rule_risk = _risk_code_f32(rule)

    return jnp.concatenate(
        [prob, confidence, decision, risk, contributions,
         rule_decision, rule_risk], axis=1)


def _epilogue_kernel(preds_ref, vf_ref, rule_ref, w_ref, cm_ref, o_ref, *,
                     strategy, fraud_threshold, confidence_threshold,
                     decline, review, monitor):
    o_ref[...] = combine_matrix(
        preds_ref[...], vf_ref[...], rule_ref[...], w_ref[...], cm_ref[...],
        strategy=strategy, fraud_threshold=fraud_threshold,
        confidence_threshold=confidence_threshold, decline=decline,
        review=review, monitor=monitor)


@functools.partial(jax.jit, static_argnames=(
    "strategy", "fraud_threshold", "confidence_threshold",
    "decline", "review", "monitor", "interpret"))
def _epilogue_call(preds, vf, rule2, w2, cm2, strategy, fraud_threshold,
                   confidence_threshold, decline, review, monitor,
                   interpret):
    b, m = preds.shape
    kernel = functools.partial(
        _epilogue_kernel, strategy=strategy, fraud_threshold=fraud_threshold,
        confidence_threshold=confidence_threshold, decline=decline,
        review=review, monitor=monitor)
    return pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec((b, m), lambda i: (0, 0)),
            pl.BlockSpec((b, m), lambda i: (0, 0)),
            pl.BlockSpec((b, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((b, m + 6), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, m + 6), jnp.float32),
        interpret=interpret,
    )(preds, vf, rule2, w2, cm2)


def fused_epilogue(preds: jax.Array, valid: jax.Array, rule: jax.Array,
                   params, interpret: bool = False) -> Dict[str, jax.Array]:
    """Fused on-chip combine -> the epilogue_reference dict.

    ``params`` is an ensemble.combine.EnsembleParams; its static fields
    (strategy + thresholds) close over the kernel as compile-time
    constants, its array fields (weights, confidence multipliers) ride as
    operands. Column layout of the kernel's [B, M+6] output:
    prob, confidence, decision, risk, contributions x M, rule_decision,
    rule_risk. Callers must pre-check ``epilogue_supported``.
    """
    b, m = preds.shape
    if not epilogue_supported(b, m):
        raise ValueError(f"unsupported epilogue shape [{b},{m}]")
    if valid.ndim == 1:
        valid = jnp.broadcast_to(valid[None, :], preds.shape)
    out = _epilogue_call(
        preds.astype(jnp.float32), valid.astype(jnp.float32),
        rule.astype(jnp.float32)[:, None],
        params.weights.astype(jnp.float32)[None, :],
        params.confidence_multipliers.astype(jnp.float32)[None, :],
        strategy=int(params.strategy),
        fraud_threshold=float(params.fraud_threshold),        # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        confidence_threshold=float(params.confidence_threshold),  # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        decline=float(params.decline_threshold),              # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        review=float(params.review_threshold),                # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        monitor=float(params.monitor_threshold),              # rtfd-lint: allow[d2h] static host field (pytree_node=False)
        interpret=interpret,
    )
    return {
        "fraud_probability": out[:, 0],
        "confidence": out[:, 1],
        "decision": out[:, 2].astype(jnp.int32),
        "risk_level": out[:, 3].astype(jnp.int32),
        "model_contributions": out[:, 4:4 + m],
        "rule_decision": out[:, 4 + m].astype(jnp.int32),
        "rule_risk": out[:, 5 + m].astype(jnp.int32),
    }

from realtime_fraud_detection_tpu.ops.attention import (  # noqa: F401
    flash_attention,
    attention_reference,
)
from realtime_fraud_detection_tpu.ops.dequant_matmul import (  # noqa: F401
    dequant_matmul,
    dequant_matmul_reference,
    dequant_rows,
    dequant_rows_reference,
    matmul_supported,
    rows_supported,
)
from realtime_fraud_detection_tpu.ops.epilogue import (  # noqa: F401
    combine_matrix,
    epilogue_reference,
    epilogue_supported,
    fused_epilogue,
)
from realtime_fraud_detection_tpu.ops.megakernel import (  # noqa: F401
    fused_megakernel,
    mega_launch_accounting,
    mega_plan,
    mega_supported,
    megakernel_reference,
)

from realtime_fraud_detection_tpu.ops.attention import (  # noqa: F401
    flash_attention,
    attention_reference,
)

"""Per-transaction latency budgets: ingest timestamp → remaining deadline.

The p99 < 20 ms contract is per TRANSACTION, end to end — time a record
spends queued upstream is budget already spent. The microbatchers
(serving/batcher.py, stream/microbatch.py) consult this tracker so a batch
closes EARLY when its oldest waiter's remaining budget drops under the
assembly margin: better a small batch on time than a full batch late
(deadline-aware batch assembly, arXiv:1904.07421).
"""

from __future__ import annotations

import dataclasses

__all__ = ["LatencyBudget"]


@dataclasses.dataclass
class LatencyBudget:
    """``budget_ms`` is the whole per-transaction deadline; ``margin_ms``
    reserves the tail for transfer+compute+return, so assembly must hand
    the batch off ``margin_ms`` before the deadline."""

    budget_ms: float = 20.0
    margin_ms: float = 2.0

    def deadline(self, ingest_ts: float) -> float:
        return ingest_ts + self.budget_ms / 1e3

    def remaining_ms(self, ingest_ts: float, now: float) -> float:
        """May be negative: the deadline is already blown."""
        return (self.deadline(ingest_ts) - now) * 1e3

    def close_by(self, ingest_ts: float) -> float:
        """Latest instant assembly may still hold a batch containing a
        record ingested at ``ingest_ts``."""
        return self.deadline(ingest_ts) - self.margin_ms / 1e3

    def should_close(self, oldest_ingest_ts: float, now: float) -> bool:
        return now >= self.close_by(oldest_ingest_ts)

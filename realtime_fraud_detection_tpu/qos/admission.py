"""Token-bucket admission control with priority classes.

One bucket models the sustainable scoring rate. Classes draw from it with
different privileges:

- ``high``   — never shed. A high-value transaction is admitted even when
  the bucket is in debt (tokens go negative, bounded at -burst); its cost
  still counts, so lower classes absorb the squeeze.
- ``normal`` — admitted while a whole token is available.
- ``low``    — admitted only while the bucket ALSO retains a reserve
  (``low_reserve_frac`` of burst), so under pressure the low class sheds
  first and the normal class keeps its headroom.

Every refusal is an :class:`AdmissionDecision` with an explicit reason —
callers turn it into a score-with-reason (``QosPlane.shed_result``), never a
silent drop.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional

__all__ = ["PRIORITIES", "TokenBucket", "AdmissionDecision",
           "AdmissionController"]

PRIORITIES = ("high", "normal", "low")


@dataclasses.dataclass
class AdmissionDecision:
    admitted: bool
    priority: str
    reason: str          # "unlimited" | "capacity" | "high_priority" |
    #                      "shed:rate_limit" | "shed:low_reserve"
    tokens: float = 0.0  # bucket level after the decision (observability)


class TokenBucket:
    """Classic token bucket on an injected clock value (callers pass ``now``
    explicitly so the serving path uses wall time and the drill a virtual
    clock; no hidden time source)."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(self.rate, 1.0)
        self.tokens = self.burst
        self._last: Optional[float] = None

    def refill(self, now: float) -> None:
        if self._last is None:
            self._last = now
        elapsed = max(0.0, now - self._last)
        self._last = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)

    def take(self, n: float = 1.0) -> None:
        """Unconditional draw; may push the bucket into bounded debt."""
        self.tokens = max(-self.burst, self.tokens - n)


class AdmissionController:
    """Priority-aware admission over one shared token bucket.

    ``rate`` is the sustainable txn/s; 0 disables limiting (every decision
    is ``admitted`` with reason ``unlimited``). Thread-safe: the serving
    event loop and a stream job thread may share one controller.
    """

    def __init__(self, rate: float, burst: Optional[float] = None,
                 low_reserve_frac: float = 0.25):
        self.bucket = TokenBucket(rate, burst)
        self.low_reserve_frac = float(low_reserve_frac)
        self._lock = threading.Lock()

    def configure(self, rate: Optional[float] = None,
                  burst: Optional[float] = None,
                  low_reserve_frac: Optional[float] = None) -> None:
        """Runtime knob update. ``burst=None`` with a new rate re-derives
        the bucket size from that rate (one second of tokens) — a plane
        constructed unlimited (rate 0 -> burst 1) must not keep its
        1-token bucket after being enabled at 20k txn/s."""
        with self._lock:
            if rate is not None:
                self.bucket.rate = float(rate)
                if burst is None:
                    self.bucket.burst = max(float(rate), 1.0)
            if burst is not None:
                self.bucket.burst = float(burst)
            self.bucket.tokens = min(self.bucket.tokens, self.bucket.burst)
            if low_reserve_frac is not None:
                self.low_reserve_frac = float(low_reserve_frac)

    def decide(self, priority: str, now: float) -> AdmissionDecision:
        if priority not in PRIORITIES:
            priority = "normal"
        with self._lock:
            b = self.bucket
            if b.rate <= 0:
                return AdmissionDecision(True, priority, "unlimited")
            b.refill(now)
            if priority == "high":
                # never shed — but the draw still counts, so the squeeze
                # lands on the lower classes, not on the latency budget
                b.take()
                return AdmissionDecision(True, priority, "high_priority",
                                         b.tokens)
            if priority == "low":
                reserve = self.low_reserve_frac * b.burst
                if b.tokens - 1.0 < reserve:
                    return AdmissionDecision(False, priority,
                                             "shed:low_reserve", b.tokens)
                b.take()
                return AdmissionDecision(True, priority, "capacity", b.tokens)
            if b.tokens < 1.0:
                return AdmissionDecision(False, priority, "shed:rate_limit",
                                         b.tokens)
            b.take()
            return AdmissionDecision(True, priority, "capacity", b.tokens)

"""The degradation ladder: trade ensemble quality for latency, reversibly.

Under sustained backlog the full 5-branch ensemble is the wrong program to
run — every batch scored at full cost pushes the queue (and every waiter's
latency) further out. The ladder steps the ensemble DOWN one rung at a time:

    0  full_ensemble   all 5 branches
    1  no_text_graph   drop BERT + GNN (the two heavy branches)
    2  trees_iforest   XGBoost + isolation forest only
    3  rules_only      the §rule ladder alone — no learned branch

and back UP when the backlog drains. Each rung is just a branch-validity
mask: the fused program's per-branch ``valid`` input renormalizes the blend
over the surviving branches (ensemble/combine.py) with ZERO recompiles —
degrading is a runtime tensor change, exactly like a branch failure.

Hysteresis: a step (either direction) requires ``patience`` CONSECUTIVE
observations past the watermark, and the high/low watermarks are separated,
so a backlog oscillating around one threshold cannot flap the ensemble.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Optional, Sequence, Tuple

import numpy as np

__all__ = ["LadderLevel", "LADDER_LEVELS", "LadderConfig",
           "DegradationLadder"]


@dataclasses.dataclass(frozen=True)
class LadderLevel:
    name: str
    dropped_branches: FrozenSet[str]
    rules_only: bool = False


LADDER_LEVELS: Tuple[LadderLevel, ...] = (
    LadderLevel("full_ensemble", frozenset()),
    LadderLevel("no_text_graph", frozenset({"bert_text", "graph_neural"})),
    LadderLevel("trees_iforest",
                frozenset({"bert_text", "graph_neural", "lstm_sequential"})),
    LadderLevel("rules_only",
                frozenset({"xgboost_primary", "lstm_sequential", "bert_text",
                           "graph_neural", "isolation_forest"}),
                rules_only=True),
)


@dataclasses.dataclass
class LadderConfig:
    """Watermarks are in BACKLOG RECORDS (consumer lag + in-flight)."""

    high_backlog: float = 2048.0   # sustained above this -> step down
    low_backlog: float = 256.0     # sustained below this -> step up
    patience: int = 2              # consecutive observations to step DOWN
    # recovery is deliberately slower than degradation (None = patience):
    # stepping down buys capacity immediately, but stepping up hands it
    # back — under a sustained overload a symmetric ladder would flap
    # degrade→drain→recover→backlog every few batches, and each recovery
    # buys a fresh queueing spike straight out of the latency budget
    up_patience: Optional[int] = None
    max_level: int = len(LADDER_LEVELS) - 1


class DegradationLadder:
    """Observe the backlog, return the current level. Pure host state —
    observations are explicit calls, so the drill drives it on a virtual
    clock and production drives it once per dispatched microbatch."""

    def __init__(self, config: LadderConfig = None):
        self.config = config or LadderConfig()
        self.level = 0
        self.transitions_down = 0
        self.transitions_up = 0
        self._over = 0
        self._under = 0

    @property
    def current(self) -> LadderLevel:
        return LADDER_LEVELS[self.level]

    def observe(self, backlog: float) -> int:
        c = self.config
        if backlog > c.high_backlog:
            self._over += 1
            self._under = 0
            if self._over >= c.patience and self.level < c.max_level:
                self.level += 1
                self.transitions_down += 1
                self._over = 0
        elif backlog <= c.low_backlog:   # inclusive: a fully drained (0)
            # backlog must count as low even when low_backlog is 0
            self._under += 1
            self._over = 0
            up_patience = (c.up_patience if c.up_patience is not None
                           else c.patience)
            if self._under >= up_patience and self.level > 0:
                self.level -= 1
                self.transitions_up += 1
                self._under = 0
        else:
            # the hysteresis band: hold the level, reset both streaks
            self._over = 0
            self._under = 0
        return self.level

    def level_mask(self, model_names: Sequence[str],
                   level: Optional[int] = None) -> np.ndarray:
        """Branch-validity mask over ``model_names`` (and-ed with the
        deployment's own validity in the scorer) — for the CURRENT level
        by default, or an explicit ``level`` (the SLO-floored effective
        rung the QoS plane serves)."""
        rung = LADDER_LEVELS[self.level if level is None else level]
        dropped = rung.dropped_branches
        return np.asarray([n not in dropped for n in model_names], bool)

    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "level_name": self.current.name,
            "rules_only": self.current.rules_only,
            "transitions_down": self.transitions_down,
            "transitions_up": self.transitions_up,
            "high_backlog": self.config.high_backlog,
            "low_backlog": self.config.low_backlog,
            "patience": self.config.patience,
        }

"""QosPlane: admission + budget + ladder bundled behind one object.

This is what the serving app and the stream job actually hold. It owns (or
shares) a :class:`~realtime_fraud_detection_tpu.obs.metrics.MetricsCollector`
so every admit/shed/step/budget observation lands on the Prometheus
exposition the deployment already scrapes.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Mapping, Optional

from realtime_fraud_detection_tpu.obs.metrics import MetricsCollector
from realtime_fraud_detection_tpu.qos.admission import (
    AdmissionController,
    AdmissionDecision,
    PRIORITIES,
)
from realtime_fraud_detection_tpu.qos.budget import LatencyBudget
from realtime_fraud_detection_tpu.qos.ladder import (
    DegradationLadder,
    LADDER_LEVELS,
    LadderConfig,
)
from realtime_fraud_detection_tpu.utils.config import QosSettings

__all__ = ["QosPlane"]


class QosPlane:
    """One QoS plane per serving app / stream job."""

    def __init__(self, settings: Optional[QosSettings] = None,
                 metrics: Optional[MetricsCollector] = None):
        self.settings = settings or QosSettings()
        self.metrics = metrics if metrics is not None else MetricsCollector()
        s = self.settings
        self.admission = AdmissionController(
            s.admission_rate, s.admission_burst or None, s.low_reserve_frac)
        self.budget = LatencyBudget(s.budget_ms, s.assemble_margin_ms)
        self.ladder = DegradationLadder(LadderConfig(
            high_backlog=s.ladder_high_backlog,
            low_backlog=s.ladder_low_backlog,
            patience=s.ladder_patience,
            up_patience=s.ladder_up_patience or None))
        self.counters: Dict[str, int] = {"admitted": 0, "shed": 0}
        self._lock = threading.Lock()
        # SLO-burn degradation signal (obs/tracing.py SloTracker feeds
        # this): an engaged gate floors the served rung at level 1 on top
        # of the backlog ladder. Same asymmetric-hysteresis discipline as
        # the ladder — engagement needs `patience` consecutive
        # over-threshold burn observations, recovery `up_patience` under.
        self.slo_engaged = False
        self._slo_over = 0
        self._slo_under = 0

    @property
    def enabled(self) -> bool:
        return bool(self.settings.enabled)

    # -------------------------------------------------------- configuration
    def configure(self, updates: Mapping[str, Any]) -> Dict[str, Any]:
        """Apply a partial settings update (the ``POST /qos`` body). Only
        known QosSettings fields are accepted; the combined result must
        satisfy the same invariants ``Config.validate`` enforces at load
        time (a 200 must never put the plane into a state the config
        loader would refuse). Returns the applied subset. All of it is
        runtime state — no recompile, no restart."""
        applied: Dict[str, Any] = {}
        s = self.settings
        previous = {key: getattr(s, key) for key in updates
                    if hasattr(s, key)}
        try:
            for key, value in updates.items():
                if not hasattr(s, key):
                    raise ValueError(f"unknown qos setting {key!r}")
                current = getattr(s, key)
                if isinstance(current, bool):
                    # bool("false") is True — reject strings outright
                    if not isinstance(value, bool):
                        raise ValueError(
                            f"qos setting {key!r} must be a JSON boolean, "
                            f"got {value!r}")
                    setattr(s, key, value)
                elif isinstance(value, (bool, str)):
                    raise ValueError(
                        f"qos setting {key!r} must be a number, "
                        f"got {value!r}")
                else:
                    setattr(s, key, type(current)(value))
                applied[key] = getattr(s, key)
            s.validate()
        except (TypeError, ValueError):
            for key, value in previous.items():
                setattr(s, key, value)
            raise
        # push the knobs into the live components
        self.admission.configure(
            rate=s.admission_rate,
            burst=(s.admission_burst or None),
            low_reserve_frac=s.low_reserve_frac)
        self.budget.budget_ms = s.budget_ms
        self.budget.margin_ms = s.assemble_margin_ms
        lc = self.ladder.config
        lc.high_backlog = s.ladder_high_backlog
        lc.low_backlog = s.ladder_low_backlog
        lc.patience = s.ladder_patience
        lc.up_patience = s.ladder_up_patience or None
        return applied

    # ----------------------------------------------------------- admission
    def classify(self, txn: Mapping[str, Any]) -> str:
        """Priority class: an explicit ``priority`` field wins; otherwise
        by amount (high-value never sheds)."""
        p = txn.get("priority")
        if isinstance(p, str) and p in PRIORITIES:
            return p
        try:
            amount = float(txn.get("amount", 0.0))
        except (TypeError, ValueError):
            amount = 0.0
        if amount >= self.settings.high_value_amount:
            return "high"
        if amount < self.settings.low_value_amount:
            return "low"
        return "normal"

    def admit(self, txn: Mapping[str, Any], now: float) -> AdmissionDecision:
        decision = self.admission.decide(self.classify(txn), now)
        if decision.admitted:
            self.metrics.qos_admitted.inc(priority=decision.priority)
            with self._lock:
                self.counters["admitted"] += 1
        else:
            self.metrics.qos_shed.inc(priority=decision.priority,
                                      reason=decision.reason)
            with self._lock:
                self.counters["shed"] += 1
        return decision

    def shed_result(self, txn: Mapping[str, Any],
                    decision: AdmissionDecision) -> Dict[str, Any]:
        """A §2.7-shaped score-with-reason for a shed transaction. Never a
        silent drop: downstream sees a REVIEW with the shed reason in the
        explanation, on the same schema as every scored record."""
        return {
            "transaction_id": str(txn.get("transaction_id", "")),
            "fraud_probability": 0.5,
            "fraud_score": 0.5,
            "risk_level": "SHED",
            "decision": "REVIEW",
            "model_predictions": {},
            "confidence": 0.0,
            "processing_time_ms": 0.0,
            "explanation": {
                "shed": True,
                "shed_reason": decision.reason,
                "priority": decision.priority,
            },
        }

    # -------------------------------------------------------------- ladder
    def observe_backlog(self, backlog: float) -> int:
        """Feed one backlog observation to the ladder; publishes the level
        gauge and any transition."""
        if not self.settings.ladder_enabled:
            return self.ladder.level
        prev = self.ladder.level
        level = self.ladder.observe(backlog)
        self.metrics.qos_ladder_level.set(level)
        if level != prev:
            self.metrics.qos_ladder_transitions.inc(
                direction="down" if level > prev else "up")
        return level

    def observe_slo_burn(self, burn_rate: float,
                         threshold: float = 2.0,
                         patience: int = 3,
                         up_patience: int = 12) -> bool:
        """Feed one SLO burn-rate observation (the tracing plane's fast
        window) to the hysteresis gate; returns whether the gate is
        engaged. An engaged gate makes ``apply_degradation`` serve at
        least ladder rung 1 (drop BERT/GNN) even while the backlog signal
        reads calm — latency can burn the error budget without a queue
        ever forming (e.g. a slow stage, not an arrival spike)."""
        prev_level = self.effective_level()
        if burn_rate > threshold:
            self._slo_over += 1
            self._slo_under = 0
            if self._slo_over >= max(1, int(patience)) \
                    and not self.slo_engaged:
                self.slo_engaged = True
                self._slo_over = 0
        else:
            self._slo_under += 1
            self._slo_over = 0
            if self._slo_under >= max(1, int(up_patience)) \
                    and self.slo_engaged:
                self.slo_engaged = False
                self._slo_under = 0
        # count a transition only when the SERVED rung actually moved: a
        # gate flip while the backlog ladder already sits at level >= 1
        # changes nothing downstream, and double-counting it would make
        # rate(qos_ladder_transitions) unreadable as "rung changes"
        level = self.effective_level()
        if level != prev_level:
            self.metrics.qos_ladder_transitions.inc(
                direction="down" if level > prev_level else "up")
        return self.slo_engaged

    def effective_level(self) -> int:
        """The rung actually served: the backlog ladder's level, floored
        at 1 while the SLO-burn gate is engaged."""
        level = self.ladder.level
        if self.slo_engaged:
            level = max(level, 1)
        return min(level, len(LADDER_LEVELS) - 1)

    def apply_degradation(self, scorer) -> int:
        """Push the current rung into a scorer as a branch-validity mask
        (+ the rules-only flag for the last rung). The scorer's own
        deployment validity is preserved — the rung only ever narrows it.
        The rung is the backlog ladder's, floored by the SLO-burn gate
        (``effective_level``)."""
        from realtime_fraud_detection_tpu.scoring.pipeline import MODEL_NAMES

        level = self.effective_level()
        rung = LADDER_LEVELS[level]
        if level == 0:
            scorer.set_degradation(None, rules_only=False, level=0)
        else:
            scorer.set_degradation(
                self.ladder.level_mask(MODEL_NAMES, level=level),
                rules_only=rung.rules_only, level=level)
        if level > 0:
            self.metrics.qos_degraded_scored.inc(
                0, level=rung.name)  # materialize the series
        return level

    def record_scored(self, n: int) -> None:
        """Count transactions scored at the current (degraded) rung."""
        level = self.effective_level()
        if n and level > 0:
            # rtfd-lint: allow[metrics] n is this batch's event count — a delta by construction, not a cumulative mirror
            self.metrics.qos_degraded_scored.inc(
                n, level=LADDER_LEVELS[level].name)

    # -------------------------------------------------------------- budget
    def record_completion(self, ingest_ts: float, now: float) -> float:
        """Observe a transaction's budget headroom at completion (negative
        = the deadline was blown). Returns the remaining seconds."""
        remaining_s = self.budget.remaining_ms(ingest_ts, now) / 1e3
        self.metrics.qos_budget_remaining.observe(remaining_s)
        return remaining_s

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, Any]:
        """The ``GET /qos`` payload."""
        with self._lock:
            counters = dict(self.counters)
        s = self.settings
        return {
            "enabled": s.enabled,
            "budget_ms": s.budget_ms,
            "assemble_margin_ms": s.assemble_margin_ms,
            "admission": {
                "rate": s.admission_rate,
                "burst": self.admission.bucket.burst,
                "tokens": round(self.admission.bucket.tokens, 3),
                "low_reserve_frac": s.low_reserve_frac,
                "high_value_amount": s.high_value_amount,
                "low_value_amount": s.low_value_amount,
            },
            "ladder": self.ladder.snapshot(),
            "ladder_levels": [lvl.name for lvl in LADDER_LEVELS],
            "effective_level": self.effective_level(),
            "slo_gate": {
                "engaged": self.slo_engaged,
                "over_streak": self._slo_over,
                "under_streak": self._slo_under,
            },
            "counters": counters,
        }

"""Deterministic overload drill: prove the QoS plane on a virtual clock.

Drives offered load ≥ N× the sustainable scoring rate through the REAL
stream path — MicrobatchAssembler → StreamJob.dispatch_batch/complete_batch
→ QosPlane admission/ladder/budget → fan-out → offset commit — with two
deliberate substitutions that make the run exactly reproducible on any CPU:

- time is a virtual clock (records carry virtual ingest timestamps; the
  assembler, admission bucket, and budget tracker all read it), and
- the device is a :class:`_DrillScorer`: the same dispatch/finalize seam as
  ``FraudScorer`` with a deterministic per-batch service cost that shrinks
  as the ladder degrades (the whole point of degrading).

Used by ``rtfd qos-drill`` (the overload demo) and pinned by the tier-1
overload tests (tests/test_stream.py): ladder engages under overload, sheds
only low-priority records, admitted p99 stays inside the budget, and the
ladder steps back up when the backlog drains.
"""

from __future__ import annotations

import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from realtime_fraud_detection_tpu.qos.plane import QosPlane
from realtime_fraud_detection_tpu.utils.config import QosSettings

__all__ = ["DrillScorer", "run_overload_drill"]


class _NoCache:
    """The drill generates unique transaction ids; dedupe never hits."""

    def get_transaction(self, txn_id, now=None):
        return None


class _DrillPending:
    def __init__(self, records, cost_s, level, rules_only):
        self.records = list(records)
        self.n = len(self.records)
        self.features = None
        self.cost_s = cost_s
        self.level = level
        self.rules_only = rules_only


class DrillScorer:
    """Deterministic FraudScorer stand-in for overload drills.

    Service cost per dispatched batch is ``(base_ms + n*per_txn_ms) /
    speedup[level]`` of VIRTUAL time — the ladder's rungs genuinely buy
    capacity, so the control loop being exercised (backlog → degrade →
    drain → recover) has the same feedback shape as the real ensemble,
    just with exact arithmetic instead of wall-clock noise.
    """

    SPEEDUP = (1.0, 2.0, 4.0, 8.0)   # one entry per ladder level

    def __init__(self, base_ms: float = 1.0, per_txn_ms: float = 0.05):
        self.base_ms = float(base_ms)
        self.per_txn_ms = float(per_txn_ms)
        self.model_valid = np.ones(5, bool)
        self.txn_cache = _NoCache()
        self.qos_level = 0
        self._qos_rules_only = False
        self.last_cost_s = 0.0

    # the QoS seam FraudScorer exposes (qos/plane.py apply_degradation)
    def set_degradation(self, mask, rules_only: bool = False,
                        level: int = 0) -> None:
        self.qos_level = int(level)
        self._qos_rules_only = bool(rules_only)

    def cost_s(self, n: int) -> float:
        return ((self.base_ms + n * self.per_txn_ms) / 1e3) \
            / self.SPEEDUP[self.qos_level]

    def sustainable_tps(self, batch: int) -> float:
        """Level-0 (full ensemble) capacity at a given batch size."""
        return batch / self.cost_s(batch) if batch else 0.0

    def dispatch(self, records, now: Optional[float] = None) -> _DrillPending:
        self.last_cost_s = self.cost_s(len(records))
        return _DrillPending(records, self.last_cost_s, self.qos_level,
                             self._qos_rules_only)

    def finalize(self, pending: _DrillPending, now: Optional[float] = None,
                 lock=None) -> List[Dict[str, Any]]:
        results = []
        for r in pending.records:
            tid = str(r.get("transaction_id", ""))
            # deterministic pseudo-score in [0, 0.65): id-hashed, stable
            # across runs, below the alert threshold by construction
            score = (zlib.crc32(tid.encode()) % 650) / 1000.0
            results.append({
                "transaction_id": tid,
                "fraud_probability": score,
                "fraud_score": score,
                "risk_level": "LOW" if score < 0.3 else "MEDIUM",
                "decision": "APPROVE" if score < 0.6 else
                            "APPROVE_WITH_MONITORING",
                "model_predictions": {},
                "confidence": 0.9,
                "processing_time_ms": pending.cost_s * 1e3 / max(pending.n, 1),
                "explanation": {"drill": True,
                                "ladder_level": pending.level,
                                "rules_only": pending.rules_only},
            })
        return results


def _make_txn(i: int, ts: float, amount: float) -> Dict[str, Any]:
    return {
        "transaction_id": f"drill-{i}",
        "user_id": f"u{i % 97}",
        "merchant_id": f"m{i % 31}",
        "amount": amount,
        "timestamp": str(ts),
    }


def run_overload_drill(
    offered_multiplier: float = 2.0,
    overload_s: float = 1.5,
    recovery_s: float = 1.5,
    max_batch: int = 64,
    max_delay_ms: float = 5.0,
    budget_ms: float = 20.0,
    assemble_margin_ms: float = 2.0,
    high_frac: float = 0.2,
    low_frac: float = 0.5,
    seed: int = 7,
    return_state: bool = False,
) -> Any:
    """Run the overload drill; returns a JSON-able summary (and, with
    ``return_state``, the live job + plane for assertions on metrics and
    topics).

    Timeline: ``overload_s`` of offered load at ``offered_multiplier`` ×
    the level-0 sustainable rate, then ``recovery_s`` at 0.3× so the
    backlog drains and the ladder steps back up, then a full drain.
    """
    from realtime_fraud_detection_tpu.stream import topics as T
    from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob
    from realtime_fraud_detection_tpu.stream.microbatch import (
        MicrobatchAssembler,
    )
    from realtime_fraud_detection_tpu.stream.transport import InMemoryBroker

    rng = np.random.default_rng(seed)
    scorer = DrillScorer()
    capacity = scorer.sustainable_tps(max_batch)
    offered = offered_multiplier * capacity

    settings = QosSettings(
        enabled=True,
        budget_ms=budget_ms,
        assemble_margin_ms=assemble_margin_ms,
        admission_rate=capacity,
        admission_burst=capacity * 0.05,        # 50 ms of tokens
        high_value_amount=500.0,
        low_value_amount=25.0,
        # watermarks in records: ~4 ms / ~1 ms of backlog at capacity —
        # the ladder must engage well before queueing alone eats the
        # budget; slow recovery (up_patience) keeps it from flapping
        ladder_high_backlog=capacity * 0.004,
        ladder_low_backlog=capacity * 0.001,
        ladder_patience=2,
        ladder_up_patience=12,
    )
    plane = QosPlane(settings)
    broker = InMemoryBroker()
    job = StreamJob(broker, scorer, JobConfig(
        max_batch=max_batch, max_delay_ms=max_delay_ms,
        emit_features=False, emit_enriched=False, qos=plane))

    # virtual clock: the assembler's delay/budget triggers, the admission
    # bucket, and every latency measurement read the same timeline
    clock = [0.0]
    vclock = lambda: clock[0]                                  # noqa: E731
    job.assembler = MicrobatchAssembler(
        job.consumer, max_batch=max_batch, max_delay_ms=max_delay_ms,
        clock=vclock, budget=plane.budget, budget_clock=vclock)

    # precomputed arrival schedule (uniform spacing per phase — exact)
    arrivals: List[Tuple[float, Dict[str, Any]]] = []
    t = 0.0
    while t < overload_s:
        arrivals.append((t, None))
        t += 1.0 / offered
    recovery_rate = 0.3 * capacity
    while t < overload_s + recovery_s:
        arrivals.append((t, None))
        t += 1.0 / recovery_rate
    # priority mix: high never sheds, low sheds first
    amounts = rng.choice(
        [1000.0, 60.0, 5.0],
        p=[high_frac, 1.0 - high_frac - low_frac, low_frac],
        size=len(arrivals))
    arrivals = [(ts, _make_txn(j, ts, float(amounts[j])))
                for j, (ts, _) in enumerate(arrivals)]

    latencies_ms: List[float] = []
    level_trace: List[int] = []
    max_level = 0
    next_i = 0
    idle_step = 0.001
    while True:
        # deliver every arrival due at the current virtual instant
        due = []
        while next_i < len(arrivals) and arrivals[next_i][0] <= clock[0]:
            ts, txn = arrivals[next_i]
            due.append((txn, ts))
            next_i += 1
        for txn, ts in due:
            broker.produce(T.TRANSACTIONS, txn, key=txn["user_id"],
                           timestamp=ts)

        batch = job.assembler.next_batch(block=False)
        if not batch and next_i >= len(arrivals):
            batch = job.assembler.flush()
        if batch:
            ctx = job.dispatch_batch(batch, now=clock[0])
            clock[0] += (scorer.last_cost_s if ctx is not None
                         and ctx.pending is not None else idle_step)
            if ctx is not None:
                job.complete_batch(ctx, now=clock[0])
                for r in ctx.fresh:
                    latencies_ms.append(
                        (clock[0] - float(r.timestamp)) * 1e3)
            level_trace.append(plane.ladder.level)
            max_level = max(max_level, plane.ladder.level)
            continue
        if next_i >= len(arrivals) and job.consumer.lag() == 0:
            break
        # nothing assembled yet: advance to the next arrival (or tick)
        clock[0] = (max(clock[0] + idle_step, arrivals[next_i][0])
                    if next_i < len(arrivals) else clock[0] + idle_step)

    # a drained system observes a zero backlog until the ladder fully
    # recovers (the run loops would keep polling; the drill is explicit)
    recovery_observations = 0
    while plane.ladder.level > 0 and recovery_observations < 32:
        plane.observe_backlog(0)
        # rtfd-lint: allow[lock-order] drill drives the plane from one thread on the virtual clock
        plane.apply_degradation(scorer)
        recovery_observations += 1

    lat = np.asarray(latencies_ms) if latencies_ms else np.zeros(1)
    shed_by = {}
    for key, count in plane.metrics.qos_shed._values.items():
        labels = dict(key)
        shed_by[f"{labels.get('priority')}:{labels.get('reason')}"] = \
            int(count)
    summary = {
        "capacity_tps_level0": round(capacity, 1),
        "offered_multiplier": offered_multiplier,
        "offered_tps": round(offered, 1),
        "produced": len(arrivals),
        "scored": job.counters["scored"],
        "shed": job.counters["shed"],
        "shed_by_priority_reason": shed_by,
        "budget_ms": budget_ms,
        "admitted_latency_ms": {
            "p50": round(float(np.percentile(lat, 50)), 3),
            "p99": round(float(np.percentile(lat, 99)), 3),
            "max": round(float(lat.max()), 3),
        },
        "p99_within_budget": bool(np.percentile(lat, 99) <= budget_ms),
        "ladder": plane.ladder.snapshot(),
        "max_ladder_level": max_level,
        "virtual_duration_s": round(clock[0], 3),
        "counters": dict(job.counters),
    }
    if return_state:
        return summary, job, plane
    return summary

"""Deadline-aware quality-of-service plane.

The north star (BASELINE.json) is p99 < 20 ms at 50k txn/s — but a latency
target is only a *property of the system* if it still holds when the offered
load exceeds what the accelerator can sustain. Production serving systems
hold tail latency by shaping load BEFORE the device ("Scaling TensorFlow to
300M predictions/sec", arXiv:2109.09541; deadline-aware batch assembly,
arXiv:1904.07421). This package is that shaping layer:

- ``admission``  — token-bucket admission control with priority classes
  (high-value transactions never shed; shed decisions are explicit
  scores-with-reason, never silent drops).
- ``budget``     — per-transaction latency budgets (ingest timestamp →
  remaining deadline); the microbatchers consult it so a batch closes
  early when the oldest waiter's budget runs low.
- ``ladder``     — the degradation ladder with hysteresis: under sustained
  backlog the ensemble steps down (full 5-branch → drop BERT/GNN →
  trees+iforest → rules-only) and steps back up when the backlog drains,
  reusing the per-branch validity/renormalization machinery in
  ``ensemble/combine.py``.
- ``plane``      — QosPlane: the bundle wired into ``serving/app.py`` and
  ``stream/job.py``, publishing admitted/shed/ladder metrics through
  ``obs/metrics.py``'s Prometheus exposition.
- ``drill``      — a deterministic overload drill (virtual clock, real
  batcher/job path) used by ``rtfd qos-drill`` and the tier-1 tests.
"""

from realtime_fraud_detection_tpu.qos.admission import (  # noqa: F401
    AdmissionController,
    AdmissionDecision,
    PRIORITIES,
    TokenBucket,
)
from realtime_fraud_detection_tpu.qos.budget import LatencyBudget  # noqa: F401
from realtime_fraud_detection_tpu.qos.ladder import (  # noqa: F401
    DegradationLadder,
    LADDER_LEVELS,
    LadderConfig,
    LadderLevel,
)
from realtime_fraud_detection_tpu.qos.plane import QosPlane  # noqa: F401
from realtime_fraud_detection_tpu.qos.drill import (  # noqa: F401
    DrillScorer,
    run_overload_drill,
)

__all__ = [
    "DrillScorer",
    "run_overload_drill",
    "AdmissionController",
    "AdmissionDecision",
    "DegradationLadder",
    "LADDER_LEVELS",
    "LadderConfig",
    "LadderLevel",
    "LatencyBudget",
    "PRIORITIES",
    "QosPlane",
    "TokenBucket",
]

"""In-process Kafka-protocol broker: the contract test double for KafkaBroker.

A TCP server that speaks the same wire-protocol subset the client uses
(Metadata v1, Produce v2, Fetch v2, ListOffsets v1, FindCoordinator v0,
OffsetCommit v2, OffsetFetch v1) over an ``InMemoryBroker`` log. It exists
so the Kafka transport's produce/fetch/commit logic — encoding, CRC,
partitioning, offset bookkeeping — is exercised end-to-end over real
sockets without a Kafka installation (none exists in this image; the
reference gets its brokers from docker-compose.yml).

This is a *fake*, not a broker: one node, no replication, no rebalance
protocol, topics auto-created on first touch with the framework's
partition counts (stream/topics.py). Request decoding here is written
against the public protocol spec (kafka.apache.org/protocol), so a codec
bug that's symmetric in the client would still be caught by the spec-shaped
header/field layout assertions in tests/test_kafka.py.
"""

from __future__ import annotations

import socketserver
import struct
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from realtime_fraud_detection_tpu.stream.kafka import (
    API_FETCH,
    API_FIND_COORDINATOR,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    Reader,
    Writer,
    decode_message_set,
    encode_message_set,
)
from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS, TopicSpec

__all__ = ["FakeKafkaServer"]


class _Partition:
    __slots__ = ("messages",)

    def __init__(self) -> None:
        # (key bytes|None, value bytes|None, timestamp_ms)
        self.messages: List[Tuple[Optional[bytes], Optional[bytes], int]] = []


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: FakeKafkaServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        while True:
            try:
                header = self._recv_exact(sock, 4)
            except ConnectionError:
                return
            if header is None:
                return
            (length,) = struct.unpack(">i", header)
            frame = self._recv_exact(sock, length)
            if frame is None:
                return
            r = Reader(frame)
            api_key, api_version, corr = r.i16(), r.i16(), r.i32()
            r.string()                             # client_id
            try:
                body = server.dispatch(api_key, api_version, r)
            except Exception:  # noqa: BLE001 - kill the connection like a broker
                return
            resp = Writer().i32(corr).raw(body).done()
            sock.sendall(struct.pack(">i", len(resp)) + resp)

    @staticmethod
    def _recv_exact(sock, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FakeKafkaServer:
    """Single-node Kafka-wire-protocol log over TCP (testing/dev only)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Sequence[TopicSpec] = TOPIC_SPECS,
                 auto_create_partitions: int = 4):
        self._log: Dict[str, List[_Partition]] = {}
        self._committed: Dict[Tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._auto_partitions = auto_create_partitions
        for t in topics:
            self._log[t.name] = [_Partition() for _ in range(t.partitions)]
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="fake-kafka", daemon=True)

    def start(self) -> "FakeKafkaServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    @property
    def host(self) -> str:
        return self._tcp.server_address[0]

    def _partitions(self, topic: str) -> List[_Partition]:
        with self._lock:
            parts = self._log.get(topic)
            if parts is None:
                parts = [_Partition() for _ in range(self._auto_partitions)]
                self._log[topic] = parts
            return parts

    # -------------------------------------------------------------- dispatch
    def dispatch(self, api_key: int, api_version: int, r: Reader) -> bytes:
        if api_key == API_METADATA:
            return self._metadata(r)
        if api_key == API_PRODUCE:
            return self._produce(r)
        if api_key == API_FETCH:
            return self._fetch(r)
        if api_key == API_LIST_OFFSETS:
            return self._list_offsets(r)
        if api_key == API_FIND_COORDINATOR:
            r.string()                             # group id — we coordinate
            return (Writer().i16(0).i32(1).string(self.host)
                    .i32(self.port).done())
        if api_key == API_OFFSET_COMMIT:
            return self._offset_commit(r)
        if api_key == API_OFFSET_FETCH:
            return self._offset_fetch(r)
        raise NotImplementedError(f"api_key {api_key}")

    def _metadata(self, r: Reader) -> bytes:
        names = r.array(Reader.string)
        if not names:                              # null/empty -> all topics
            with self._lock:
                names = sorted(self._log)
        w = Writer()
        w.array([(1, self.host, self.port, None)], lambda ww, b:
                ww.i32(b[0]).string(b[1]).i32(b[2]).string(b[3]))
        w.i32(1)                                   # controller id
        w.i32(len(names))
        for name in names:
            parts = self._partitions(name)
            w.i16(0).string(name).i8(0)
            w.i32(len(parts))
            for pid in range(len(parts)):
                w.i16(0).i32(pid).i32(1)
                w.array([1], Writer.i32).array([1], Writer.i32)
        return w.done()

    def _produce(self, r: Reader) -> bytes:
        acks, _timeout = r.i16(), r.i32()
        del acks                                   # single node: always "all"
        results = []                               # (topic, part, base_offset)
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                part_id = r.i32()
                record_set = r.bytes_() or b""
                msgs = decode_message_set(record_set)
                parts = self._partitions(topic)
                part = parts[part_id]
                with self._lock:
                    base = len(part.messages)
                    part.messages.extend(
                        (key, value, ts) for _off, key, value, ts in msgs)
                results.append((topic, part_id, base))
        w = Writer()
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for topic, pid, base in results:
            by_topic.setdefault(topic, []).append((pid, base))
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic).i32(len(parts))
            for pid, base in parts:
                w.i32(pid).i16(0).i64(base).i64(-1)
        w.i32(0)                                   # throttle_time_ms
        return w.done()

    def _fetch(self, r: Reader) -> bytes:
        r.i32(); r.i32(); r.i32()                  # replica, max_wait, min_bytes
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                pid, offset, max_bytes = r.i32(), r.i64(), r.i32()
                req.append((topic, pid, offset, max_bytes))
        w = Writer()
        w.i32(0)                                   # throttle_time_ms
        by_topic: Dict[str, List[Tuple[int, int, int]]] = {}
        for topic, pid, offset, max_bytes in req:
            by_topic.setdefault(topic, []).append((pid, offset, max_bytes))
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic).i32(len(parts))
            for pid, offset, max_bytes in parts:
                part = self._partitions(topic)[pid]
                with self._lock:
                    msgs = part.messages[offset:]
                    hw = len(part.messages)
                # encode incrementally with absolute offsets and stop once
                # max_bytes is exceeded (the overflowing message is
                # truncated, Kafka-style) — never the whole partition tail
                chunks: list = []
                used = 0
                for i, msg in enumerate(msgs):
                    piece = encode_message_set([msg])
                    piece = struct.pack(">q", offset + i) + piece[8:]
                    chunks.append(piece)
                    used += len(piece)
                    if used > max_bytes:
                        break
                encoded = b"".join(chunks)
                if len(encoded) > max_bytes:
                    encoded = encoded[:max_bytes]
                w.i32(pid).i16(0).i64(hw).bytes_(encoded)
        return w.done()

    def _list_offsets(self, r: Reader) -> bytes:
        r.i32()                                    # replica id
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                pid, _ts = r.i32(), r.i64()
                req.append((topic, pid))
        w = Writer()
        by_topic: Dict[str, List[int]] = {}
        for topic, pid in req:
            by_topic.setdefault(topic, []).append(pid)
        w.i32(len(by_topic))
        for topic, pids in by_topic.items():
            w.string(topic).i32(len(pids))
            for pid in pids:
                part = self._partitions(topic)[pid]
                with self._lock:
                    end = len(part.messages)
                w.i32(pid).i16(0).i64(-1).i64(end)
        return w.done()

    def _offset_commit(self, r: Reader) -> bytes:
        group = r.string()
        r.i32(); r.string(); r.i64()               # generation, member, retention
        committed = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                pid, off = r.i32(), r.i64()
                r.string()                         # metadata
                with self._lock:
                    key = (group, topic, pid)
                    if off > self._committed.get(key, 0):
                        self._committed[key] = off
                committed.append((topic, pid))
        w = Writer()
        by_topic: Dict[str, List[int]] = {}
        for topic, pid in committed:
            by_topic.setdefault(topic, []).append(pid)
        w.i32(len(by_topic))
        for topic, pids in by_topic.items():
            w.string(topic).i32(len(pids))
            for pid in pids:
                w.i32(pid).i16(0)
        return w.done()

    def _offset_fetch(self, r: Reader) -> bytes:
        group = r.string()
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            for pid in r.array(Reader.i32):
                req.append((topic, pid))
        w = Writer()
        by_topic: Dict[str, List[int]] = {}
        for topic, pid in req:
            by_topic.setdefault(topic, []).append(pid)
        w.i32(len(by_topic))
        for topic, pids in by_topic.items():
            w.string(topic).i32(len(pids))
            for pid in pids:
                with self._lock:
                    off = self._committed.get((group, topic, pid), -1)
                w.i32(pid).i64(off).string(None).i16(0)
        return w.done()

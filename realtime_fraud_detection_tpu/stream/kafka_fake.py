"""In-process Kafka-protocol broker: the contract test double for KafkaBroker.

A TCP server that speaks the same wire-protocol subset the client uses
(Metadata v1, Produce v2/v3, Fetch v2, ListOffsets v1, FindCoordinator v0,
OffsetCommit v2, OffsetFetch v1, InitProducerId v0, JoinGroup v1,
SyncGroup v0, Heartbeat v0, LeaveGroup v0). It exists so the Kafka
transport's produce/fetch/commit/membership logic — encoding, CRC/CRC32C,
partitioning, offset bookkeeping, sequence fencing, rebalancing — is
exercised end-to-end over real sockets without a Kafka installation (none
exists in this image; the reference gets its brokers from
docker-compose.yml).

Broker-side semantics implemented because the contract tests need them:
- **Group coordinator** (``_Group``): generations, join barriers, leader
  selection, session-timeout eviction, commit fencing — the server half of
  the reference's consumer-group failover (consumer.properties:5).
- **Idempotent produce fencing**: per-(producer_id, partition) sequence
  tracking; a replayed batch is acked with its original offset, a sequence
  gap is rejected (producer.properties:8).

Still a *fake*, not a broker: one node, no replication, topics auto-created
on first touch with the framework's partition counts (stream/topics.py).
Request decoding here is written against the public protocol spec
(kafka.apache.org/protocol); tests/test_kafka.py additionally pins
hand-assembled golden frame bytes so a symmetric client/fake codec bug
cannot hide.
"""

from __future__ import annotations

import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from realtime_fraud_detection_tpu.stream.kafka import (
    API_FETCH,
    API_FIND_COORDINATOR,
    API_HEARTBEAT,
    API_INIT_PRODUCER_ID,
    API_JOIN_GROUP,
    API_LEAVE_GROUP,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    API_SYNC_GROUP,
    ERR_ILLEGAL_GENERATION,
    ERR_OUT_OF_ORDER_SEQUENCE,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_UNKNOWN_MEMBER_ID,
    Reader,
    Writer,
    decode_message_set,
    decode_record_batch,
    encode_message_set,
)
from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS, TopicSpec

__all__ = ["FakeKafkaServer"]


class _Partition:
    __slots__ = ("messages", "producer_state")

    def __init__(self) -> None:
        # (key bytes|None, value bytes|None, timestamp_ms)
        self.messages: List[Tuple[Optional[bytes], Optional[bytes], int]] = []
        # idempotence fencing: producer_id -> (base_seq, count, base_offset)
        # of the last accepted batch — a replay of the same base_seq is a
        # duplicate and returns the original offset without appending
        self.producer_state: Dict[int, Tuple[int, int, int]] = {}


class _Group:
    """Coordinator-side consumer group (JoinGroup/SyncGroup state machine).

    States mirror Kafka's GroupCoordinator: ``empty`` -> ``joining``
    (PreparingRebalance: members must (re)join) -> ``awaiting_sync``
    (CompletingRebalance: leader computes assignment) -> ``stable``.
    A join while stable, a member death (session timeout), or a leave all
    kick the group back to ``joining`` and bump the generation when the
    round completes.
    """

    def __init__(self) -> None:
        self.cond = threading.Condition()
        self.state = "empty"
        self.generation = 0
        self.members: Dict[str, dict] = {}        # id -> {last_seen, meta}
        # rejoined members this round: id -> (metadata, session_ms)
        self.pending: Dict[str, Tuple[bytes, int]] = {}
        self.leader = ""
        self.assignments: Dict[str, bytes] = {}
        self.join_deadline = 0.0
        self.next_member_n = 0


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        server: FakeKafkaServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        while True:
            try:
                header = self._recv_exact(sock, 4)
            except ConnectionError:
                return
            if header is None:
                return
            (length,) = struct.unpack(">i", header)
            frame = self._recv_exact(sock, length)
            if frame is None:
                return
            r = Reader(frame)
            api_key, api_version, corr = r.i16(), r.i16(), r.i32()
            r.string()                             # client_id
            try:
                body = server.dispatch(api_key, api_version, r)
            except Exception:  # noqa: BLE001 - kill the connection like a broker
                return
            resp = Writer().i32(corr).raw(body).done()
            sock.sendall(struct.pack(">i", len(resp)) + resp)

    @staticmethod
    def _recv_exact(sock, n: int) -> Optional[bytes]:
        buf = bytearray()
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class FakeKafkaServer:
    """Single-node Kafka-wire-protocol log over TCP (testing/dev only)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Sequence[TopicSpec] = TOPIC_SPECS,
                 auto_create_partitions: int = 4):
        self._log: Dict[str, List[_Partition]] = {}
        self._committed: Dict[Tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._auto_partitions = auto_create_partitions
        self._groups: Dict[str, _Group] = {}
        self._next_pid = 1000
        for t in topics:
            self._log[t.name] = [_Partition() for _ in range(t.partitions)]
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="fake-kafka", daemon=True)

    def start(self) -> "FakeKafkaServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    @property
    def host(self) -> str:
        return self._tcp.server_address[0]

    def _partitions(self, topic: str) -> List[_Partition]:
        with self._lock:
            parts = self._log.get(topic)
            if parts is None:
                parts = [_Partition() for _ in range(self._auto_partitions)]
                self._log[topic] = parts
            return parts

    # -------------------------------------------------------------- dispatch
    def dispatch(self, api_key: int, api_version: int, r: Reader) -> bytes:
        if api_key == API_METADATA:
            return self._metadata(r)
        if api_key == API_PRODUCE:
            return self._produce(r, api_version)
        if api_key == API_FETCH:
            return self._fetch(r)
        if api_key == API_LIST_OFFSETS:
            return self._list_offsets(r)
        if api_key == API_FIND_COORDINATOR:
            r.string()                             # group id — we coordinate
            return (Writer().i16(0).i32(1).string(self.host)
                    .i32(self.port).done())
        if api_key == API_OFFSET_COMMIT:
            return self._offset_commit(r)
        if api_key == API_OFFSET_FETCH:
            return self._offset_fetch(r)
        if api_key == API_JOIN_GROUP:
            return self._join_group(r)
        if api_key == API_SYNC_GROUP:
            return self._sync_group(r)
        if api_key == API_HEARTBEAT:
            return self._heartbeat(r)
        if api_key == API_LEAVE_GROUP:
            return self._leave_group(r)
        if api_key == API_INIT_PRODUCER_ID:
            r.string()                             # transactional_id (null)
            r.i32()                                # transaction_timeout_ms
            with self._lock:
                pid = self._next_pid
                self._next_pid += 1
            return Writer().i32(0).i16(0).i64(pid).i16(0).done()
        raise NotImplementedError(f"api_key {api_key}")

    def _metadata(self, r: Reader) -> bytes:
        names = r.array(Reader.string)
        if not names:                              # null/empty -> all topics
            with self._lock:
                names = sorted(self._log)
        w = Writer()
        w.array([(1, self.host, self.port, None)], lambda ww, b:
                ww.i32(b[0]).string(b[1]).i32(b[2]).string(b[3]))
        w.i32(1)                                   # controller id
        w.i32(len(names))
        for name in names:
            parts = self._partitions(name)
            w.i16(0).string(name).i8(0)
            w.i32(len(parts))
            for pid in range(len(parts)):
                w.i16(0).i32(pid).i32(1)
                w.array([1], Writer.i32).array([1], Writer.i32)
        return w.done()

    def _append(self, topic: str, part_id: int,
                record_set: bytes) -> Tuple[int, int]:
        """Append one record set; returns (error_code, base_offset).

        Detects the format by the magic byte (offset 16 in both layouts).
        RecordBatch v2 with a producer id goes through sequence fencing:
        a replayed baseSequence is a DUPLICATE -> acked with the original
        base offset, nothing appended (enable.idempotence=true semantics);
        a gap is OUT_OF_ORDER_SEQUENCE (45).
        """
        part = self._partitions(topic)[part_id]
        if len(record_set) > 16 and record_set[16] == 2:
            msgs4, pid, _pepoch, base_seq = decode_record_batch(record_set)
            msgs = [(key, value, ts) for _off, key, value, ts in msgs4]
            with self._lock:
                if pid >= 0:
                    state = part.producer_state.get(pid)
                    if state is not None:
                        last_seq, last_count, last_base = state
                        if base_seq == last_seq:          # retry: dedupe
                            return 0, last_base
                        if base_seq != last_seq + last_count:
                            return ERR_OUT_OF_ORDER_SEQUENCE, -1
                base = len(part.messages)
                part.messages.extend(msgs)
                if pid >= 0:
                    part.producer_state[pid] = (base_seq, len(msgs), base)
            return 0, base
        msgs = [(key, value, ts)
                for _off, key, value, ts in decode_message_set(record_set)]
        with self._lock:
            base = len(part.messages)
            part.messages.extend(msgs)
        return 0, base

    def _produce(self, r: Reader, api_version: int = 2) -> bytes:
        if api_version >= 3:
            r.string()                             # transactional_id
        acks, _timeout = r.i16(), r.i32()
        del acks                                   # single node: always "all"
        results = []                               # (topic, part, err, base)
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                part_id = r.i32()
                record_set = r.bytes_() or b""
                err, base = self._append(topic, part_id, record_set)
                results.append((topic, part_id, err, base))
        w = Writer()
        by_topic: Dict[str, List[Tuple[int, int, int]]] = {}
        for topic, pid, err, base in results:
            by_topic.setdefault(topic, []).append((pid, err, base))
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic).i32(len(parts))
            for pid, err, base in parts:
                w.i32(pid).i16(err).i64(base).i64(-1)
        w.i32(0)                                   # throttle_time_ms
        return w.done()

    def _fetch(self, r: Reader) -> bytes:
        r.i32(); r.i32(); r.i32()                  # replica, max_wait, min_bytes
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                pid, offset, max_bytes = r.i32(), r.i64(), r.i32()
                req.append((topic, pid, offset, max_bytes))
        w = Writer()
        w.i32(0)                                   # throttle_time_ms
        by_topic: Dict[str, List[Tuple[int, int, int]]] = {}
        for topic, pid, offset, max_bytes in req:
            by_topic.setdefault(topic, []).append((pid, offset, max_bytes))
        w.i32(len(by_topic))
        for topic, parts in by_topic.items():
            w.string(topic).i32(len(parts))
            for pid, offset, max_bytes in parts:
                part = self._partitions(topic)[pid]
                with self._lock:
                    msgs = part.messages[offset:]
                    hw = len(part.messages)
                # encode incrementally with absolute offsets and stop once
                # max_bytes is exceeded (the overflowing message is
                # truncated, Kafka-style) — never the whole partition tail
                chunks: list = []
                used = 0
                for i, msg in enumerate(msgs):
                    piece = encode_message_set([msg])
                    piece = struct.pack(">q", offset + i) + piece[8:]
                    chunks.append(piece)
                    used += len(piece)
                    if used > max_bytes:
                        break
                encoded = b"".join(chunks)
                if len(encoded) > max_bytes:
                    encoded = encoded[:max_bytes]
                w.i32(pid).i16(0).i64(hw).bytes_(encoded)
        return w.done()

    def _list_offsets(self, r: Reader) -> bytes:
        r.i32()                                    # replica id
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                pid, _ts = r.i32(), r.i64()
                req.append((topic, pid))
        w = Writer()
        by_topic: Dict[str, List[int]] = {}
        for topic, pid in req:
            by_topic.setdefault(topic, []).append(pid)
        w.i32(len(by_topic))
        for topic, pids in by_topic.items():
            w.string(topic).i32(len(pids))
            for pid in pids:
                part = self._partitions(topic)[pid]
                with self._lock:
                    end = len(part.messages)
                w.i32(pid).i16(0).i64(-1).i64(end)
        return w.done()

    def _offset_commit(self, r: Reader) -> bytes:
        group = r.string()
        generation, member = r.i32(), r.string()
        r.i64()                                    # retention
        # fence group-managed commits (simple consumers send gen=-1, ""):
        # a member evicted by a rebalance must NOT advance offsets the new
        # owner is already consuming from
        err = 0
        if member:
            g = self._groups.get(group)
            if g is None:
                err = ERR_UNKNOWN_MEMBER_ID
            else:
                with g.cond:
                    self._evict_dead(g)
                    if member not in g.members:
                        err = ERR_UNKNOWN_MEMBER_ID
                    elif generation != g.generation:
                        err = ERR_ILLEGAL_GENERATION
                    elif g.state != "stable":
                        err = ERR_REBALANCE_IN_PROGRESS
        committed = []
        for _ in range(r.i32()):
            topic = r.string()
            for _ in range(r.i32()):
                pid, off = r.i32(), r.i64()
                r.string()                         # metadata
                if err == 0:
                    with self._lock:
                        key = (group, topic, pid)
                        if off > self._committed.get(key, 0):
                            self._committed[key] = off
                committed.append((topic, pid))
        w = Writer()
        by_topic: Dict[str, List[int]] = {}
        for topic, pid in committed:
            by_topic.setdefault(topic, []).append(pid)
        w.i32(len(by_topic))
        for topic, pids in by_topic.items():
            w.string(topic).i32(len(pids))
            for pid in pids:
                w.i32(pid).i16(err)
        return w.done()

    def _offset_fetch(self, r: Reader) -> bytes:
        group = r.string()
        req = []
        for _ in range(r.i32()):
            topic = r.string()
            for pid in r.array(Reader.i32):
                req.append((topic, pid))
        w = Writer()
        by_topic: Dict[str, List[int]] = {}
        for topic, pid in req:
            by_topic.setdefault(topic, []).append(pid)
        w.i32(len(by_topic))
        for topic, pids in by_topic.items():
            w.string(topic).i32(len(pids))
            for pid in pids:
                with self._lock:
                    off = self._committed.get((group, topic, pid), -1)
                w.i32(pid).i64(off).string(None).i16(0)
        return w.done()

    # ----------------------------------------------------- group coordinator
    def _group(self, group_id: str) -> _Group:
        with self._lock:
            g = self._groups.get(group_id)
            if g is None:
                g = self._groups[group_id] = _Group()
            return g

    @staticmethod
    def _evict_dead(g: _Group) -> None:
        """Session-timeout eviction (lock held): a member that stopped
        heartbeating is removed; if the group was stable, that triggers a
        rebalance — the survivors' next heartbeat says REBALANCE_IN_PROGRESS
        and they rejoin to adopt the dead member's partitions."""
        # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
        now = time.monotonic()
        dead = [mid for mid, m in g.members.items()
                if now - m["last_seen"] > m["session_ms"] / 1000.0]
        for mid in dead:
            del g.members[mid]
            g.pending.pop(mid, None)
        if dead and g.state == "stable":
            g.state = "joining"
            g.pending = {}
            g.join_deadline = now + 10.0
            g.cond.notify_all()

    def _join_group(self, r: Reader) -> bytes:
        group_id = r.string()
        session_ms, rebalance_ms = r.i32(), r.i32()
        member_id = r.string()
        proto_type = r.string()
        protocols = r.array(lambda rr: (rr.string(), rr.bytes_()))
        metadata = protocols[0][1] if protocols else b""
        g = self._group(group_id)
        with g.cond:
            self._evict_dead(g)
            if not member_id:
                g.next_member_n += 1
                member_id = f"{proto_type}-{g.next_member_n}"
            if g.state in ("empty", "stable", "awaiting_sync"):
                g.state = "joining"
                g.pending = {}
                # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
                g.join_deadline = (time.monotonic()
                                   + min(rebalance_ms, 30_000) / 1000.0)
            # each member's OWN session timeout rides with its join — the
            # completing thread must not stamp everyone with its value
            g.pending[member_id] = (metadata, session_ms)
            g.cond.notify_all()
            # the round completes when every live member has rejoined, or
            # at the rebalance deadline (stragglers are dropped)
            while g.state == "joining":
                known = set(g.members)
                if (known <= set(g.pending)
                        # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
                        or time.monotonic() >= g.join_deadline):
                    g.generation += 1
                    # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
                    now = time.monotonic()
                    g.members = {
                        mid: {"last_seen": now, "session_ms": sess,
                              "metadata": meta}
                        for mid, (meta, sess) in g.pending.items()
                    }
                    g.leader = sorted(g.members)[0]
                    g.assignments = {}
                    g.state = "awaiting_sync"
                    g.cond.notify_all()
                    break
                g.cond.wait(timeout=0.05)
            if member_id not in g.members:
                # joined too late: this round closed without us
                return (Writer().i16(ERR_UNKNOWN_MEMBER_ID).i32(-1)
                        .string("").string("").string("")
                        .array([], lambda w, _: None).done())
            members = (
                [(mid, m["metadata"]) for mid, m in sorted(g.members.items())]
                if member_id == g.leader else []
            )
            return (
                Writer().i16(0).i32(g.generation).string("range")
                .string(g.leader).string(member_id)
                .array(members,
                       lambda w, kv: w.string(kv[0]).bytes_(kv[1]))
                .done()
            )

    def _sync_group(self, r: Reader) -> bytes:
        group_id = r.string()
        generation, member_id = r.i32(), r.string()
        assignments = r.array(lambda rr: (rr.string(), rr.bytes_()))
        g = self._group(group_id)
        with g.cond:
            if member_id not in g.members:
                return Writer().i16(ERR_UNKNOWN_MEMBER_ID).bytes_(b"").done()
            if generation != g.generation:
                return Writer().i16(ERR_ILLEGAL_GENERATION).bytes_(b"").done()
            if member_id == g.leader and assignments:
                g.assignments = dict(assignments)
                g.state = "stable"
                g.cond.notify_all()
            # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
            deadline = time.monotonic() + 10.0
            while (g.state == "awaiting_sync"
                   and g.generation == generation
                   # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
                   and time.monotonic() < deadline):
                g.cond.wait(timeout=0.05)
            if g.generation != generation or g.state == "joining":
                return (Writer().i16(ERR_REBALANCE_IN_PROGRESS)
                        .bytes_(b"").done())
            if g.state != "stable":
                return (Writer().i16(ERR_REBALANCE_IN_PROGRESS)
                        .bytes_(b"").done())
            # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
            g.members[member_id]["last_seen"] = time.monotonic()
            return (Writer().i16(0)
                    .bytes_(g.assignments.get(member_id, b"")).done())

    def _heartbeat(self, r: Reader) -> bytes:
        group_id = r.string()
        generation, member_id = r.i32(), r.string()
        g = self._group(group_id)
        with g.cond:
            self._evict_dead(g)
            if member_id not in g.members:
                return Writer().i16(ERR_UNKNOWN_MEMBER_ID).done()
            # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
            g.members[member_id]["last_seen"] = time.monotonic()
            if generation != g.generation:
                return Writer().i16(ERR_ILLEGAL_GENERATION).done()
            if g.state != "stable":
                return Writer().i16(ERR_REBALANCE_IN_PROGRESS).done()
            return Writer().i16(0).done()

    def _leave_group(self, r: Reader) -> bytes:
        group_id = r.string()
        member_id = r.string()
        g = self._group(group_id)
        with g.cond:
            if member_id in g.members:
                del g.members[member_id]
                g.pending.pop(member_id, None)
                if g.state == "stable":
                    g.state = "joining" if g.members else "empty"
                    g.pending = {}
                    # rtfd-lint: allow[wall-clock] broker-protocol timeouts (real I/O even in the fake)
                    g.join_deadline = time.monotonic() + 10.0
                g.cond.notify_all()
        return Writer().i16(0).done()

    def kill_member(self, group_id: str, member_id: str) -> None:
        """Test hook: drop a member as if its process died (no LeaveGroup,
        no more heartbeats) by expiring its session immediately."""
        g = self._group(group_id)
        with g.cond:
            if member_id in g.members:
                g.members[member_id]["last_seen"] = -1e9
                self._evict_dead(g)

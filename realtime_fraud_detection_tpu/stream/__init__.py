"""Streaming layer: transport, microbatch assembly, and the scoring job."""

from realtime_fraud_detection_tpu.stream.topics import (  # noqa: F401
    ALERTS,
    DECISIONS,
    ENRICHED,
    FEATURES,
    PREDICTIONS,
    TOPIC_SPECS,
    TRANSACTIONS,
)
from realtime_fraud_detection_tpu.stream.transport import (  # noqa: F401
    Consumer,
    FaultInjector,
    InMemoryBroker,
    KafkaTransport,
    Record,
)
from realtime_fraud_detection_tpu.stream.kafka import KafkaBroker  # noqa: F401
from realtime_fraud_detection_tpu.stream.netbroker import (  # noqa: F401
    BrokerServer,
    HaBrokerClient,
    NetBrokerClient,
    NotEnoughReplicasError,
)
from realtime_fraud_detection_tpu.stream.gateway import (  # noqa: F401
    IngressGateway,
)
from realtime_fraud_detection_tpu.stream.microbatch import (  # noqa: F401
    DoubleBufferedScorer,
    MicrobatchAssembler,
)
from realtime_fraud_detection_tpu.stream.job import JobConfig, StreamJob  # noqa: F401
from realtime_fraud_detection_tpu.stream.windows import (  # noqa: F401
    WindowedAnalytics,
    WindowOperator,
)
from realtime_fraud_detection_tpu.stream.joins import (  # noqa: F401
    MultiStreamCorrelator,
    WindowJoin,
)

"""Kafka consumer-group membership: JoinGroup/SyncGroup/Heartbeat/LeaveGroup.

The reference runs group-managed consumers (config/kafka/consumer.properties:5
``group.id=fraud-detection-group``, ``:36`` CooperativeStickyAssignor): when a
consumer process dies, the coordinator rebalances its partitions onto the
survivors, resuming from committed offsets — no records lost, none stuck.
This module implements that client side over the framework's own wire client
(stream/kafka.py), spec-shaped per kafka.apache.org/protocol:

- ``GroupMembership`` — the membership state machine: JoinGroup v1 (member id
  + generation), leader-side range assignment, SyncGroup v0 (assignment
  distribution), Heartbeat v0 (liveness + rebalance signal), LeaveGroup v0.
- ``KafkaGroupConsumer`` — the framework ``Consumer`` contract (poll /
  commit / snapshot_positions / lag) over a dynamic partition assignment.
  Commits carry (generation, member_id) so the coordinator fences a zombie
  member's commit after it has been rebalanced away — the at-least-once
  guarantee survives process death.

Assignor: range (the protocol's default), computed client-side by the group
leader exactly as Kafka's RangeAssignor does — per topic, sorted members get
ceil/floor-even contiguous partition spans. Sticky assignment is a
rebalance-cost optimization, not a correctness feature; range keeps the
leader logic auditable.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from realtime_fraud_detection_tpu.stream.kafka import (
    API_HEARTBEAT,
    API_JOIN_GROUP,
    API_LEAVE_GROUP,
    API_SYNC_GROUP,
    ERR_ILLEGAL_GENERATION,
    ERR_REBALANCE_IN_PROGRESS,
    ERR_UNKNOWN_MEMBER_ID,
    KafkaBroker,
    KafkaProtocolError,
    Reader,
    Writer,
)
from realtime_fraud_detection_tpu.stream.transport import Record

__all__ = ["GroupMembership", "KafkaGroupConsumer"]

_REJOIN_ERRORS = (ERR_ILLEGAL_GENERATION, ERR_UNKNOWN_MEMBER_ID,
                  ERR_REBALANCE_IN_PROGRESS)


def encode_subscription(topics: List[str]) -> bytes:
    """ConsumerProtocolSubscription v0: version, topics, user_data."""
    return (Writer().i16(0).array(sorted(topics), Writer.string)
            .bytes_(b"").done())


def decode_subscription(buf: bytes) -> List[str]:
    r = Reader(buf)
    r.i16()                                       # version
    return r.array(Reader.string)


def encode_assignment(parts_by_topic: Dict[str, List[int]]) -> bytes:
    """ConsumerProtocolAssignment v0: version, [topic -> partitions], data."""
    return (
        Writer().i16(0)
        .array(sorted(parts_by_topic.items()), lambda w, kv:
               w.string(kv[0]).array(sorted(kv[1]), Writer.i32))
        .bytes_(b"").done()
    )


def decode_assignment(buf: bytes) -> Dict[str, List[int]]:
    if not buf:
        return {}
    r = Reader(buf)
    r.i16()                                       # version
    pairs = r.array(lambda rr: (rr.string(), rr.array(Reader.i32)))
    return {topic: parts for topic, parts in pairs}


def range_assign(
    subscriptions: Dict[str, List[str]],
    partition_counts: Dict[str, int],
) -> Dict[str, Dict[str, List[int]]]:
    """Kafka RangeAssignor: per topic, sorted subscribers split the sorted
    partition list into contiguous near-even spans (first members get the
    remainder). Returns member -> topic -> partitions."""
    out: Dict[str, Dict[str, List[int]]] = {m: {} for m in subscriptions}
    topics = sorted({t for ts in subscriptions.values() for t in ts})
    for topic in topics:
        members = sorted(m for m, ts in subscriptions.items() if topic in ts)
        n_parts = partition_counts[topic]
        base, extra = divmod(n_parts, len(members))
        start = 0
        for i, member in enumerate(members):
            n = base + (1 if i < extra else 0)
            if n:
                out[member][topic] = list(range(start, start + n))
            start += n
    return out


class GroupMembership:
    """One consumer's membership in a Kafka consumer group."""

    def __init__(self, broker: KafkaBroker, group_id: str, topics: List[str],
                 session_timeout_ms: int = 10_000,
                 rebalance_timeout_ms: int = 10_000,
                 rejoin_sleep=None):
        from realtime_fraud_detection_tpu.utils.backoff import (
            DeterministicBackoff,
            instance_seed,
        )

        self.broker = broker
        self.group_id = group_id
        self.topics = list(topics)
        self.session_timeout_ms = session_timeout_ms
        self.rebalance_timeout_ms = rebalance_timeout_ms
        # rejoin-retry schedule: bounded exponential + deterministic jitter
        # seeded PER MEMBER INSTANCE (a group's members are exactly the
        # herd that must stagger its rejoin storm — a group-keyed seed
        # would synchronize them); ``rejoin_sleep`` is the injected seam
        self._backoff = DeterministicBackoff(
            base_s=0.05, mult=2.0, max_s=0.4,
            seed=instance_seed(group_id), sleep=rejoin_sleep)
        self.member_id = ""
        self.generation = -1
        self.is_leader = False
        self.assignment: Dict[str, List[int]] = {}
        self.rebalances = 0
        # serializes join/heartbeat/leave between the poll thread and the
        # background heartbeat thread (KafkaGroupConsumer)
        self.lock = threading.RLock()

    # ------------------------------------------------------------------ join
    def ensure_active(self) -> bool:
        """Join (or rejoin) if not currently in a stable generation.
        Returns True when a (re)join happened — positions must be reset."""
        with self.lock:
            if self.generation >= 0:
                return False
            # rtfd-lint: allow[wall-clock] group-membership heartbeats/deadlines are real time
            deadline = (time.monotonic()
                        + self.rebalance_timeout_ms / 1000.0 * 2)
            attempt = 0
            while True:
                try:
                    self._join_sync()
                    self.rebalances += 1
                    return True
                except KafkaProtocolError as e:
                    if (e.code not in _REJOIN_ERRORS
                            # rtfd-lint: allow[wall-clock] group-membership heartbeats/deadlines are real time
                            or time.monotonic() > deadline):
                        raise
                    if e.code == ERR_UNKNOWN_MEMBER_ID:
                        self.member_id = ""
                    # The membership lock deliberately spans this retry
                    # wait (no concurrent join/heartbeat allowed); the
                    # wait goes through the injected backoff seam —
                    # bounded exponential + deterministic jitter instead
                    # of a fixed bare sleep.
                    self._backoff.sleep(attempt)
                    attempt += 1

    def _join_sync(self) -> None:
        join_body = (
            Writer().string(self.group_id).i32(self.session_timeout_ms)
            .i32(self.rebalance_timeout_ms).string(self.member_id)
            .string("consumer")
            .array([("range", encode_subscription(self.topics))],
                   lambda w, p: w.string(p[0]).bytes_(p[1]))
            .done()
        )

        def _join(conn):
            r = conn.request(API_JOIN_GROUP, 1, join_body)
            err = r.i16()
            if err:
                raise KafkaProtocolError("JoinGroup", err)
            generation = r.i32()
            r.string()                            # protocol name
            leader = r.string()
            member_id = r.string()
            members = r.array(lambda rr: (rr.string(), rr.bytes_()))
            return generation, leader, member_id, members

        generation, leader, member_id, members = (
            self.broker._with_coordinator(self.group_id, "JoinGroup", _join))
        self.member_id = member_id
        self.is_leader = leader == member_id
        assignments: List[Tuple[str, bytes]] = []
        if self.is_leader:
            subscriptions = {
                mid: decode_subscription(meta) for mid, meta in members
            }
            counts = {
                t: self.broker.partitions(t)
                for ts in subscriptions.values() for t in ts
            }
            computed = range_assign(subscriptions, counts)
            assignments = [(mid, encode_assignment(parts))
                           for mid, parts in computed.items()]

        sync_body = (
            Writer().string(self.group_id).i32(generation)
            .string(self.member_id)
            .array(assignments, lambda w, a: w.string(a[0]).bytes_(a[1]))
            .done()
        )

        def _sync(conn):
            r = conn.request(API_SYNC_GROUP, 0, sync_body)
            err = r.i16()
            if err:
                raise KafkaProtocolError("SyncGroup", err)
            return r.bytes_()

        my_assignment = self.broker._with_coordinator(
            self.group_id, "SyncGroup", _sync)
        self.assignment = decode_assignment(my_assignment or b"")
        self.generation = generation

    # ------------------------------------------------------------- liveness
    def heartbeat(self) -> bool:
        """Returns False when the coordinator demands a rejoin (rebalance
        in progress / evicted); the caller must ensure_active() again."""
        with self.lock:
            if self.generation < 0:
                return False
            body = (Writer().string(self.group_id).i32(self.generation)
                    .string(self.member_id).done())

            def _hb(conn):
                r = conn.request(API_HEARTBEAT, 0, body)
                return r.i16()

            err = self.broker._with_coordinator(
                self.group_id, "Heartbeat", _hb)
            if err == 0:
                return True
            if err in _REJOIN_ERRORS:
                self.generation = -1
                if err == ERR_UNKNOWN_MEMBER_ID:
                    self.member_id = ""
                return False
            raise KafkaProtocolError("Heartbeat", err)

    def leave(self) -> None:
        with self.lock:
            self._leave_locked()

    def _leave_locked(self) -> None:
        if not self.member_id:
            return
        body = (Writer().string(self.group_id).string(self.member_id).done())

        def _leave(conn):
            r = conn.request(API_LEAVE_GROUP, 0, body)
            return r.i16()

        try:
            self.broker._with_coordinator(self.group_id, "LeaveGroup", _leave)
        except (KafkaProtocolError, ConnectionError, OSError):
            pass                                  # dying anyway
        self.generation = -1
        self.member_id = ""


class KafkaGroupConsumer:
    """Framework ``Consumer`` contract over a group-managed assignment.

    The StreamJob drives this exactly like the static transport.Consumer —
    poll / snapshot_positions / commit(positions) / lag — but partitions
    come and go with group rebalances, and commits are fenced by
    (generation, member_id). On any rebalance the positions reset to the
    committed offsets of the NEW assignment: records in flight from the old
    assignment simply replay on whichever member now owns the partition
    (at-least-once; dedupe is the scorer's txn-cache, stream/job.py).
    """

    def __init__(self, broker: KafkaBroker, topics: List[str], group_id: str,
                 session_timeout_ms: int = 10_000,
                 heartbeat_interval_s: float = 1.0,
                 rejoin_sleep=None):
        self.broker = broker
        self.topics = list(topics)
        self.group_id = group_id
        self.membership = GroupMembership(
            broker, group_id, topics, session_timeout_ms=session_timeout_ms,
            rejoin_sleep=rejoin_sleep)
        self.heartbeat_interval_s = heartbeat_interval_s
        self._last_heartbeat = 0.0
        self._position: Dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self.membership.ensure_active()
        self.seek_to_committed()
        # Background heartbeat (Kafka's heartbeat thread): keeps the member
        # alive through processing gaps longer than the session timeout —
        # e.g. a first-batch XLA compile — during which poll() isn't called.
        # It only SIGNALS rebalances (generation=-1); the rejoin itself
        # happens on the poll thread, which owns the positions.
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name=f"kafka-hb-{group_id}", daemon=True)
        self._hb_thread.start()

    def _hb_loop(self) -> None:
        while not self._closed.wait(self.heartbeat_interval_s):
            try:
                self.membership.heartbeat()
                # rtfd-lint: allow[wall-clock] group-membership heartbeats/deadlines are real time
                self._last_heartbeat = time.monotonic()
            except (KafkaProtocolError, ConnectionError, OSError):
                pass                      # next poll's _maintain recovers

    # ---------------------------------------------------------- assignment
    def _maintain(self) -> None:
        """Heartbeat on cadence; rejoin + reset positions on rebalance."""
        # rtfd-lint: allow[wall-clock] group-membership heartbeats/deadlines are real time
        now = time.monotonic()
        if now - self._last_heartbeat >= self.heartbeat_interval_s:
            self._last_heartbeat = now
            if not self.membership.heartbeat():
                self.membership.ensure_active()
                self.seek_to_committed()
        elif self.membership.generation < 0:
            self.membership.ensure_active()
            self.seek_to_committed()

    def assigned_partitions(self) -> Dict[str, List[int]]:
        return dict(self.membership.assignment)

    def seek_to_committed(self) -> None:
        with self._lock:
            self._position = {
                (t, p): self.broker.committed(self.group_id, t, p)
                for t, parts in self.membership.assignment.items()
                for p in parts
            }

    # ---------------------------------------------------------------- poll
    def poll(self, max_records: int = 256) -> List[Record]:
        self._maintain()
        out: List[Record] = []
        with self._lock:
            positions = list(self._position.items())
        for (t, p), pos in positions:
            if len(out) >= max_records:
                break
            recs = self.broker.read(t, p, pos, max_records - len(out))
            if recs:
                with self._lock:
                    self._position[(t, p)] = recs[-1].offset + 1
                out.extend(recs)
        return out

    def commit(self, offsets: Optional[Dict[tuple, int]] = None) -> None:
        """Fenced commit: ILLEGAL_GENERATION / UNKNOWN_MEMBER_ID mean this
        member was rebalanced away — drop the commit (the new owner will
        rescore from its committed offset) and rejoin."""
        with self._lock:
            to_commit = dict(self._position) if offsets is None else offsets
        if not to_commit:
            return
        m = self.membership
        try:
            self.broker.commit(self.group_id, to_commit,
                               generation_id=m.generation,
                               member_id=m.member_id)
        except KafkaProtocolError as e:
            if e.code not in _REJOIN_ERRORS:
                raise
            m.generation = -1
            self._maintain()

    def snapshot_positions(self) -> Dict[tuple, int]:
        with self._lock:
            return dict(self._position)

    def positions(self) -> Dict[str, int]:
        with self._lock:
            return {f"{t}:{p}": pos for (t, p), pos in self._position.items()}

    def lag(self) -> int:
        """Lag over this member's ASSIGNED partitions only (the group's
        total lag is the sum across members)."""
        total = 0
        for t, parts in self.membership.assignment.items():
            ends = self.broker.end_offsets(t)
            for p in parts:
                total += max(0, ends[p] - self.broker.committed(
                    self.group_id, t, p))
        return total

    def close(self) -> None:
        self._closed.set()
        self.membership.leave()

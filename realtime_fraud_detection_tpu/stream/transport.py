"""Streaming transport: partitioned in-memory broker + gated Kafka backend.

The reference's data backbone is a 3-broker Kafka cluster with idempotent
lz4 producers and read_committed consumers (config/kafka/*.properties,
FraudDetectionJob.java:141-213). This module provides the same *semantics*
behind one interface:

- ``InMemoryBroker`` — partitioned, offset-addressed, consumer-group topic
  log entirely in process. This is the test/dev/bench transport and the
  SURVEY.md §4 "fake in-process transport" testing strategy. Supports
  deterministic fault injection (drop/dup/delay) for failure-path tests.
- ``KafkaBroker`` (stream/kafka.py) — a real Kafka wire-protocol client
  (no library dependency) behind the same interface; ``NetBrokerClient``
  (stream/netbroker.py) — the framework's own networked durable broker.
  The interface is the contract, so transports are a deployment choice,
  not a rewrite (contract suite: tests/test_netbroker.py, test_kafka.py).

Offset semantics (the exactly-once story, SURVEY.md §5.4): consumers read
from their group's committed offset; commit happens only after downstream
write-back, so a crash replays the tail. Replay-idempotence is provided by
the scorer's transaction cache keyed on transaction_id.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import zlib
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Sequence

import numpy as np

from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS, TopicSpec


@dataclasses.dataclass
class Record:
    topic: str
    partition: int
    offset: int
    key: Optional[str]
    value: Any
    timestamp: float


class StaleGenerationError(RuntimeError):
    """A generation-stamped produce/commit hit a partition fenced at a
    NEWER assignment generation: the writer lost ownership in a rebalance
    it has not observed yet — the classic zombie of an asymmetric
    partition (deaf to the coordinator, still reaching the broker). The
    write is refused loudly at the broker, the same way Kafka's producer
    epoch fences a zombie transactional producer; unstamped producers
    (external feeds that never participate in assignment) are unaffected.
    """


@dataclasses.dataclass
class FaultInjector:
    """Deterministic transport fault injection (absent in the reference —
    SURVEY.md §5.3 'fault injection: none').

    A *drop* models an in-flight delivery failure: the record is withheld
    from this poll AND the consumer position must not advance past it, so it
    is re-delivered on the next poll (at-least-once preserved). A *duplicate*
    models redelivery: the record appears twice in one poll.
    """

    drop_prob: float = 0.0
    duplicate_prob: float = 0.0
    seed: int = 0

    def __post_init__(self) -> None:
        self._rng = np.random.default_rng(self.seed)

    def apply(self, records: List[Record]) -> tuple[List[Record], Optional[Record]]:
        """Returns (delivered, first_dropped). Delivery truncates at the
        first drop so the caller can rewind its position to it."""
        out: List[Record] = []
        for r in records:
            u = self._rng.random()
            if u < self.drop_prob:
                return out, r
            out.append(r)
            if u > 1.0 - self.duplicate_prob:
                out.append(r)
        return out, None


class _PartitionLog:
    __slots__ = ("records", "lock")

    def __init__(self) -> None:
        self.records: List[Record] = []
        self.lock = threading.Lock()


class InMemoryBroker:
    """Partitioned topic log with consumer groups, single process."""

    def __init__(self, topics: Sequence[TopicSpec] = TOPIC_SPECS,
                 auto_create_partitions: int = 4):
        self._topics: Dict[str, List[_PartitionLog]] = {}
        self._committed: Dict[tuple, int] = {}   # (group, topic, part) -> next offset
        self._rr: Dict[str, int] = {}            # round-robin cursor per topic
        self._lock = threading.Lock()
        self._auto_partitions = auto_create_partitions
        # producer generation fences: (topic, partition) -> minimum
        # assignment generation a STAMPED produce/commit must carry. The
        # cluster coordinator bumps these in its rebalance fence step so
        # a partitioned-away worker is fenced at the WRITE seam, not just
        # the checkpoint seam (see StaleGenerationError).
        self._gen_fence: Dict[tuple, int] = {}
        self.fenced_produces = 0
        self.fenced_commits = 0
        for t in topics:
            self.create_topic(t.name, t.partitions)

    # ------------------------------------------------------------- topology
    def create_topic(self, name: str, partitions: int) -> None:
        with self._lock:
            if name not in self._topics:
                self._topics[name] = [_PartitionLog() for _ in range(partitions)]

    def _logs(self, topic: str) -> List[_PartitionLog]:
        logs = self._topics.get(topic)
        if logs is None:
            self.create_topic(topic, self._auto_partitions)
            logs = self._topics[topic]
        return logs

    def partitions(self, topic: str) -> int:
        return len(self._logs(topic))

    # -------------------------------------------------------------- produce
    def select_partition(self, topic: str, key: Optional[str]) -> int:
        """Key hash (same key -> same partition -> per-key ordering), or
        round-robin for unkeyed records, like Kafka's default partitioner.

        crc32, NOT ``hash()``: Python salts ``str.__hash__`` per process, so
        a WAL-backed broker restarted with ``hash()`` would route old keys to
        new partitions and break per-key ordering. Matches stream/kafka.py's
        partitioner so the two transports agree on key->partition."""
        logs = self._logs(topic)
        if key is not None:
            return zlib.crc32(key.encode()) % len(logs)
        with self._lock:
            part = self._rr.get(topic, 0) % len(logs)
            self._rr[topic] = part + 1
        return part

    def append(self, topic: str, partition: int, value: Any,
               key: Optional[str] = None,
               timestamp: Optional[float] = None) -> Record:
        """Append to a specific partition (produce = select + append; split
        so a durable front-end can write its WAL between the two)."""
        log = self._logs(topic)[partition]
        with log.lock:
            rec = Record(topic, partition, len(log.records), key, value,
                         # rtfd-lint: allow[wall-clock] record-timestamp default; callers pass ts
                         timestamp if timestamp is not None else time.time())
            log.records.append(rec)
        return rec

    def produce(self, topic: str, value: Any, key: Optional[str] = None,
                timestamp: Optional[float] = None,
                generation: Optional[int] = None) -> Record:
        """Append one record; partition chosen by key hash. A stamped
        ``generation`` is checked against the partition's producer fence
        (unstamped produces pass — generation fencing is opt-in, like
        Kafka's producer epochs)."""
        part = self.select_partition(topic, key)
        self.check_producer_generation(topic, part, generation)
        return self.append(topic, part, value, key, timestamp)

    # ------------------------------------------------ generation fencing
    def fence_producers(self, topic: str, partitions: Sequence[int],
                        generation: int) -> None:
        """Refuse future STAMPED produces/commits for these partitions
        whose generation is older than ``generation`` (monotonic: a fence
        never moves backwards)."""
        with self._lock:
            for p in partitions:
                key = (topic, int(p))
                if int(generation) > self._gen_fence.get(key, 0):
                    self._gen_fence[key] = int(generation)

    def producer_fence(self, topic: str, partition: int) -> int:
        return self._gen_fence.get((topic, int(partition)), 0)

    def check_producer_generation(self, topic: str, partition: int,
                                  generation: Optional[int],
                                  op: str = "produce") -> None:
        """Raise :class:`StaleGenerationError` when a stamped write hits
        a newer fence. ``None`` (unstamped) always passes."""
        if generation is None:
            return
        fence = self._gen_fence.get((topic, int(partition)))
        if fence is not None and int(generation) < fence:
            with self._lock:
                if op == "commit":
                    self.fenced_commits += 1
                else:
                    self.fenced_produces += 1
            raise StaleGenerationError(
                f"{op} to {topic}-{partition} at generation {generation} "
                f"refused: partition fenced at generation {fence} "
                f"(writer lost ownership in an unobserved rebalance)")

    def producer_fence_stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "fenced_produces": self.fenced_produces,
                "fenced_commits": self.fenced_commits,
                "fenced_partitions": len(self._gen_fence),
            }

    def produce_batch(self, topic: str, values: Iterable[Any],
                      key_fn: Optional[Callable[[Any], str]] = None) -> int:
        n = 0
        for v in values:
            self.produce(topic, v, key_fn(v) if key_fn else None)
            n += 1
        return n

    def produce_batch_stamped(self, topic: str,
                              items: Iterable[tuple]) -> int:
        """(key, value, timestamp) triples — contract parity with
        ``NetBrokerClient.produce_batch_stamped`` so drill producers run
        unchanged against either transport."""
        n = 0
        for k, v, ts in items:
            self.produce(topic, v, k, timestamp=ts)
            n += 1
        return n

    def produce_batch_keyed(self, topic: str,
                            items: Iterable[tuple]) -> int:
        """Batch produce of explicit (key, value) pairs — for payloads that
        do not carry their own routing key (e.g. the predictions fan-out,
        keyed by user but the §2.7 response has no user field). Networked
        brokers override this with a single-frame implementation; per-call
        produces over TCP cost one round trip EACH (measured 8.6x slower
        on loopback for a 256-record fan-out)."""
        n = 0
        for k, v in items:
            self.produce(topic, v, k)
            n += 1
        return n

    # -------------------------------------------------------------- consume
    def consumer(self, topics: Sequence[str], group_id: str,
                 faults: Optional[FaultInjector] = None,
                 partitions: Optional[Mapping[str, Sequence[int]]] = None,
                 ) -> "Consumer":
        """``partitions`` scopes the consumer to an explicit topic →
        partition-list assignment (the partition-parallel worker plane,
        cluster/fleet.py) instead of every partition of every topic."""
        return Consumer(self, list(topics), group_id, faults,
                        partitions=partitions)

    def end_offsets(self, topic: str) -> List[int]:
        return [len(p.records) for p in self._logs(topic)]

    def read(self, topic: str, partition: int, start: int, limit: int) -> List[Record]:
        log = self._logs(topic)[partition]
        with log.lock:
            return log.records[start:start + limit]

    # -------------------------------------------------------------- offsets
    def committed(self, group: str, topic: str, partition: int) -> int:
        return self._committed.get((group, topic, partition), 0)

    def commit(self, group: str, offsets: Mapping[tuple, int],
               generation: Optional[int] = None) -> None:
        # a stamped commit is fence-checked for EVERY partition BEFORE
        # any offset is applied: a zombie's commit must not advance the
        # group past records whose predictions were refused at the
        # produce fence (that would silently lose them)
        if generation is not None:
            for (topic, part) in offsets:
                self.check_producer_generation(topic, part, generation,
                                               op="commit")
        with self._lock:
            for (topic, part), off in offsets.items():
                key = (group, topic, part)
                if off > self._committed.get(key, 0):
                    self._committed[key] = off

    def lag(self, group: str, topic: str) -> int:
        return sum(
            max(0, end - self.committed(group, topic, p))
            for p, end in enumerate(self.end_offsets(topic))
        )


class Consumer:
    """Offset-tracking consumer over the in-memory broker.

    ``poll`` returns up to max_records across all assigned partitions from
    the *position* (not yet committed); ``commit`` durably advances the
    group offset. ``seek_to_committed`` rewinds to the last commit —
    the crash-recovery path.

    With an explicit ``partitions`` assignment (topic → partition list)
    the consumer reads ONLY those partitions — the partition-parallel
    worker plane's affinity contract (cluster/): N workers in one group,
    each scoped to a disjoint partition set. ``set_assignment`` adopts a
    new assignment mid-life (rebalance) and rewinds the new partitions to
    their committed offsets, exactly like a fresh member would.
    """

    def __init__(self, broker: InMemoryBroker, topics: List[str],
                 group_id: str, faults: Optional[FaultInjector] = None,
                 partitions: Optional[Mapping[str, Sequence[int]]] = None):
        self.broker = broker
        self.topics = topics
        self.group_id = group_id
        self.faults = faults
        self._assignment: Optional[Dict[str, List[int]]] = (
            {t: sorted(int(p) for p in parts)
             for t, parts in partitions.items()}
            if partitions is not None else None)
        self._position: Dict[tuple, int] = {}
        # networked brokers expose a monotonic reconnect epoch; each
        # consumer tracks its OWN last-seen value, so every consumer
        # sharing one client observes every reconnect (see poll)
        self._epoch_fn = getattr(broker, "reconnect_epoch", None)
        self._seen_epoch = self._epoch_fn() if self._epoch_fn else 0
        self.seek_to_committed()

    def _assigned(self, topic: str) -> Sequence[int]:
        if self._assignment is not None:
            return self._assignment.get(topic, ())
        return range(self.broker.partitions(topic))

    def set_assignment(self,
                       partitions: Mapping[str, Sequence[int]]) -> None:
        """Adopt a new explicit partition assignment (rebalance).

        Cooperative-sticky semantics: partitions RETAINED across the
        change keep their in-memory positions (rewinding them would
        re-poll records already sitting in the owner's assembler or in
        flight — a storm of cached-dup re-emissions for no safety gain);
        newly ACQUIRED partitions start from their committed offsets (the
        handoff contract: state was restored/replayed exactly to there);
        released partitions drop out of the position map."""
        self._assignment = {t: sorted(int(p) for p in parts)
                            for t, parts in partitions.items()}
        old = self._position
        self._position = {
            (t, p): old.get((t, p),
                            self.broker.committed(self.group_id, t, p))
            for t, parts in self._assignment.items()
            for p in parts
        }

    def assigned_partitions(self) -> Dict[str, List[int]]:
        return {t: list(self._assigned(t)) for t in self.topics}

    def seek_to_committed(self) -> None:
        self._position = {
            (t, p): self.broker.committed(self.group_id, t, p)
            for t in self.topics
            for p in self._assigned(t)
        }

    def poll(self, max_records: int = 256) -> List[Record]:
        # Networked brokers bump a reconnect epoch after a connection loss
        # (possibly a broker RESTART): the in-memory cursor may sit past
        # records that were polled but never committed when the connection
        # died — continuing from it would let the NEXT commit advance past
        # them (silent loss). Rewind to the committed offsets instead;
        # re-delivered records dedupe downstream (scorer txn-cache).
        # Epoch-compared per consumer: a shared client's OTHER consumers
        # each still see the reconnect on their own next poll.
        if self._epoch_fn is not None:
            epoch = self._epoch_fn()
            if epoch != self._seen_epoch:
                self._seen_epoch = epoch
                self.seek_to_committed()
        out: List[Record] = []
        for (t, p), pos in self._position.items():
            if len(out) >= max_records:
                break
            recs = self.broker.read(t, p, pos, max_records - len(out))
            if not recs:
                continue
            if self.faults is not None:
                recs, dropped = self.faults.apply(recs)
                if dropped is not None:
                    # position stops AT the dropped record: re-delivered on
                    # the next poll, never silently lost past a commit
                    self._position[(t, p)] = dropped.offset
                    out.extend(recs)
                    continue
            if recs:
                self._position[(t, p)] = recs[-1].offset + 1
                out.extend(recs)
        return out

    def commit(self, offsets: Optional[Dict[tuple, int]] = None) -> None:
        """Commit positions. With ``offsets`` (a ``snapshot_positions()``
        result), commit exactly those — the pipelined job snapshots positions
        at dispatch time so a batch still in flight on the device is never
        committed past by a later poll."""
        self.broker.commit(
            self.group_id,
            dict(self._position) if offsets is None else offsets)

    def snapshot_positions(self) -> Dict[tuple, int]:
        """Copy of current read positions keyed (topic, partition)."""
        return dict(self._position)

    def positions(self) -> Dict[str, int]:
        """JSON-safe snapshot of current read positions
        ("topic:partition" -> next offset) for checkpoint manifests."""
        return {f"{t}:{p}": pos for (t, p), pos in self._position.items()}

    def seek_to_positions(self, offsets: Mapping[str, int]) -> None:
        """Inverse of ``positions()``: restore read positions from a
        checkpoint manifest. The offsets-as-truth resume path (reference:
        Flink restores Kafka offsets from ITS checkpoint, not the broker,
        JobConfig.java exactly-once contract): scorer state and transport
        positions come from the SAME checkpoint, so effectively-once
        scoring holds across a restart even against a broker whose group
        offsets were lost."""
        for key, off in offsets.items():
            t, _, p = key.rpartition(":")
            self._position[(t, int(p))] = int(off)

    def lag(self) -> int:
        """Uncommitted lag over THIS consumer's assigned partitions (all
        partitions when unscoped) — a fleet of scoped consumers summing
        their lags must count each partition once, not once per worker."""
        total = 0
        for t in self.topics:
            ends = self.broker.end_offsets(t)
            for p in self._assigned(t):
                total += max(0, ends[p] - self.broker.committed(
                    self.group_id, t, p))
        return total


def KafkaTransport(bootstrap_servers: str = "localhost:9092", **kwargs):
    """Real Kafka adapter: the framework's own wire-protocol client
    (stream/kafka.py — no client-library dependency). Returns a
    ``KafkaBroker`` implementing this module's broker interface, so
    ``StreamJob(broker=KafkaTransport(...))`` runs unchanged against a
    cluster. Kept as a factory here for backward-compatible imports."""
    from realtime_fraud_detection_tpu.stream.kafka import KafkaBroker

    return KafkaBroker(bootstrap=bootstrap_servers, **kwargs)

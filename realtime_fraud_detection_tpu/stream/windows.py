"""Event-time windowed analytics: this framework's WindowProcessor.

Equivalent of the reference's Flink windowing layer
(WindowProcessor.java:36-166) — seven keyed window computations over the
transaction stream:

    1. user velocity        keyBy user,            sliding 5m / 1m
    2. merchant patterns    keyBy merchant,        tumbling 1h
    3. user sessions        keyBy user,            session gap 30m
    4. geo clustering       keyBy 1-degree grid,   tumbling 15m
    5. fraud patterns       keyBy (payment, category, amount-bucket),
                                                   sliding 10m / 2m
    6. high frequency       keyBy user,            tumbling 5m + count-10
                                                   early trigger
    7. amount clustering    keyBy log10 bucket,    tumbling 30m

The reference defines all seven stream graphs but implements only the first
two aggregate functions; the other five reference result/aggregate classes
that do not exist (WindowProcessor.java:486-487, SURVEY.md §0.2). Here all
seven are real, built on one event-time engine with bounded-out-of-orderness
watermarks (10 s, matching the reference's WatermarkStrategy; 5 s for the
high-frequency path).

Design notes (host-side, single-writer — the same discipline as
state/stores.py): windows live in plain dicts keyed by (key, window_start);
watermark advance fires and evicts closed windows. Merchant amount spread
uses Welford's online (count, mean, M2) instead of the reference's
keep-every-amount list (MerchantAggregateFunction.calculateStandardDeviation
stores all amounts) — same population std-dev, O(1) state per window.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, Iterable, List, Mapping, Optional, Tuple

__all__ = [
    "SlidingWindow", "TumblingWindow", "SessionWindow", "WindowOperator",
    "WindowedAnalytics",
    "user_velocity_windows", "merchant_pattern_windows",
    "user_session_windows", "geo_cluster_windows", "fraud_pattern_windows",
    "high_frequency_windows", "amount_cluster_windows",
    "geo_grid_key", "fraud_pattern_key", "amount_cluster_key",
    "amount_bucket",
]

DEFAULT_OUT_OF_ORDERNESS_S = 10.0     # WindowProcessor.java:41

Txn = Mapping[str, Any]


# --------------------------------------------------------------- assigners
@dataclasses.dataclass(frozen=True)
class SlidingWindow:
    """SlidingEventTimeWindows.of(size, slide) — one event lands in
    size/slide overlapping windows."""

    size_s: float
    slide_s: float

    def assign(self, ts: float) -> List[Tuple[float, float]]:
        last_start = ts - (ts % self.slide_s)
        out = []
        start = last_start
        while start > ts - self.size_s:
            out.append((start, start + self.size_s))
            start -= self.slide_s
        return out


@dataclasses.dataclass(frozen=True)
class TumblingWindow:
    size_s: float

    def assign(self, ts: float) -> List[Tuple[float, float]]:
        start = ts - (ts % self.size_s)
        return [(start, start + self.size_s)]


@dataclasses.dataclass(frozen=True)
class SessionWindow:
    """SessionWindows.withGap — per-event window [ts, ts+gap) that merges
    with any overlapping session of the same key."""

    gap_s: float

    def assign(self, ts: float) -> List[Tuple[float, float]]:
        return [(ts, ts + self.gap_s)]


# ------------------------------------------------------------- aggregates
class Aggregate:
    """AggregateFunction contract: fresh accumulator, add, merge, result."""

    def create(self) -> Any:
        raise NotImplementedError

    def add(self, acc: Any, txn: Txn, ts: float) -> None:
        raise NotImplementedError

    def merge(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def result(self, acc: Any, key: str,
               window: Tuple[float, float]) -> Dict[str, Any]:
        raise NotImplementedError


def _base_result(key_field: str, key: str, window: Tuple[float, float],
                 acc: "_BaseAcc") -> Dict[str, Any]:
    n = acc.count
    return {
        key_field: key,
        "window_start": window[0],
        "window_end": window[1],
        "event_time_start": acc.first_ts,
        "event_time_end": acc.last_ts,
        "transaction_count": n,
        "total_amount": acc.total,
        "avg_amount": acc.total / n if n else 0.0,
        "fraud_count": acc.fraud,
        "fraud_rate": acc.fraud / n if n else 0.0,
        "high_risk_count": acc.high_risk,
    }


@dataclasses.dataclass
class _BaseAcc:
    count: int = 0
    total: float = 0.0
    fraud: int = 0
    high_risk: int = 0
    first_ts: float = math.inf
    last_ts: float = -math.inf

    def take(self, txn: Txn, ts: float) -> None:
        self.count += 1
        self.total += float(txn.get("amount") or 0.0)
        if txn.get("is_fraud"):
            self.fraud += 1
        if float(txn.get("fraud_score") or 0.0) > 0.7:
            self.high_risk += 1
        self.first_ts = min(self.first_ts, ts)
        self.last_ts = max(self.last_ts, ts)

    def fold(self, other: "_BaseAcc") -> None:
        self.count += other.count
        self.total += other.total
        self.fraud += other.fraud
        self.high_risk += other.high_risk
        self.first_ts = min(self.first_ts, other.first_ts)
        self.last_ts = max(self.last_ts, other.last_ts)


@dataclasses.dataclass
class _VelocityAcc(_BaseAcc):
    merchants: set = dataclasses.field(default_factory=set)
    payment_methods: set = dataclasses.field(default_factory=set)


class UserVelocityAggregate(Aggregate):
    """UserVelocityAggregateFunction (WindowProcessor.java:248-352)."""

    def create(self) -> _VelocityAcc:
        return _VelocityAcc()

    def add(self, acc: _VelocityAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)
        acc.merchants.add(str(txn.get("merchant_id")))
        pm = txn.get("payment_method")
        if pm:
            acc.payment_methods.add(str(pm))

    def merge(self, a: _VelocityAcc, b: _VelocityAcc) -> _VelocityAcc:
        a.fold(b)
        a.merchants |= b.merchants
        a.payment_methods |= b.payment_methods
        return a

    def result(self, acc, key, window):
        out = _base_result("user_id", key, window, acc)
        out["unique_merchant_count"] = len(acc.merchants)
        out["unique_payment_method_count"] = len(acc.payment_methods)
        out["velocity_score"] = self._velocity_score(acc)
        return out

    @staticmethod
    def _velocity_score(acc: _VelocityAcc) -> float:
        """(WindowProcessor.java:328-351) count, amount, fraud-rate, and
        low-merchant-diversity factors, capped at 1."""
        score = 0.0
        if acc.count > 20:
            score += 0.4
        elif acc.count > 10:
            score += 0.2
        elif acc.count > 5:
            score += 0.1
        if acc.total > 10_000:
            score += 0.3
        elif acc.total > 5_000:
            score += 0.2
        elif acc.total > 1_000:
            score += 0.1
        if acc.count:
            score += (acc.fraud / acc.count) * 0.4
            if len(acc.merchants) / acc.count < 0.2:
                score += 0.2
        return min(1.0, score)


@dataclasses.dataclass
class _MerchantAcc(_BaseAcc):
    fraud_amount: float = 0.0
    users: set = dataclasses.field(default_factory=set)
    payment_methods: set = dataclasses.field(default_factory=set)
    # Welford state for amount std-dev
    mean: float = 0.0
    m2: float = 0.0


class MerchantPatternAggregate(Aggregate):
    """MerchantAggregateFunction (WindowProcessor.java:358-489) with Welford
    replacing the stored-amounts list."""

    def create(self) -> _MerchantAcc:
        return _MerchantAcc()

    def add(self, acc: _MerchantAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)
        amount = float(txn.get("amount") or 0.0)
        if txn.get("is_fraud"):
            acc.fraud_amount += amount
        acc.users.add(str(txn.get("user_id")))
        pm = txn.get("payment_method")
        if pm:
            acc.payment_methods.add(str(pm))
        delta = amount - acc.mean
        acc.mean += delta / acc.count
        acc.m2 += delta * (amount - acc.mean)

    def merge(self, a: _MerchantAcc, b: _MerchantAcc) -> _MerchantAcc:
        # Chan's parallel Welford merge
        n = a.count + b.count
        if b.count:
            delta = b.mean - a.mean
            if n:
                a.m2 = a.m2 + b.m2 + delta * delta * a.count * b.count / n
                a.mean = (a.mean * a.count + b.mean * b.count) / n
        a.fold(b)
        a.fraud_amount += b.fraud_amount
        a.users |= b.users
        a.payment_methods |= b.payment_methods
        return a

    def result(self, acc, key, window):
        out = _base_result("merchant_id", key, window, acc)
        std = math.sqrt(acc.m2 / acc.count) if acc.count >= 2 else 0.0
        out["fraud_amount"] = acc.fraud_amount
        out["unique_user_count"] = len(acc.users)
        out["unique_payment_method_count"] = len(acc.payment_methods)
        out["amount_std_dev"] = std
        out["risk_score"] = self._risk_score(acc, std)
        return out

    @staticmethod
    def _risk_score(acc: _MerchantAcc, std: float) -> float:
        """(WindowProcessor.java:460-484) fraud rate, volume, amount
        dispersion, and low-user-diversity factors, capped at 1."""
        score = 0.0
        if acc.count:
            score += (acc.fraud / acc.count) * 0.5
        if acc.count > 1000:
            score += 0.2
        elif acc.count > 500:
            score += 0.1
        avg = acc.total / acc.count if acc.count else 0.0
        if avg > 0 and std / avg > 2.0:
            score += 0.2
        if acc.count and len(acc.users) / acc.count < 0.1:
            score += 0.3
        return min(1.0, score)


@dataclasses.dataclass
class _SessionAcc(_BaseAcc):
    merchants: set = dataclasses.field(default_factory=set)
    max_amount: float = 0.0


class UserSessionAggregate(Aggregate):
    """Session analytics (the reference's UserSessionAggregateFunction is
    referenced but never written — designed here): duration, tempo, burst
    intensity of one user session."""

    def create(self) -> _SessionAcc:
        return _SessionAcc()

    def add(self, acc: _SessionAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)
        acc.merchants.add(str(txn.get("merchant_id")))
        acc.max_amount = max(acc.max_amount, float(txn.get("amount") or 0.0))

    def merge(self, a: _SessionAcc, b: _SessionAcc) -> _SessionAcc:
        a.fold(b)
        a.merchants |= b.merchants
        a.max_amount = max(a.max_amount, b.max_amount)
        return a

    def result(self, acc, key, window):
        out = _base_result("user_id", key, window, acc)
        duration = max(0.0, acc.last_ts - acc.first_ts)
        out["session_duration_s"] = duration
        out["unique_merchant_count"] = len(acc.merchants)
        out["max_amount"] = acc.max_amount
        # txns per minute of active session (>=1-minute floor so one-txn
        # sessions don't divide by ~0)
        out["transactions_per_minute"] = acc.count / max(duration / 60.0, 1.0)
        return out


@dataclasses.dataclass
class _GeoAcc(_BaseAcc):
    users: set = dataclasses.field(default_factory=set)
    merchants: set = dataclasses.field(default_factory=set)


class GeoClusterAggregate(Aggregate):
    """Per-1-degree-grid activity (GeographicAggregateFunction analog)."""

    def create(self) -> _GeoAcc:
        return _GeoAcc()

    def add(self, acc: _GeoAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)
        acc.users.add(str(txn.get("user_id")))
        acc.merchants.add(str(txn.get("merchant_id")))

    def merge(self, a: _GeoAcc, b: _GeoAcc) -> _GeoAcc:
        a.fold(b)
        a.users |= b.users
        a.merchants |= b.merchants
        return a

    def result(self, acc, key, window):
        out = _base_result("geo_key", key, window, acc)
        out["unique_user_count"] = len(acc.users)
        out["unique_merchant_count"] = len(acc.merchants)
        return out


class FraudPatternAggregate(Aggregate):
    """Per (payment-method, merchant-category, amount-bucket) pattern cell
    (FraudPatternAggregateFunction analog)."""

    def create(self) -> _BaseAcc:
        return _BaseAcc()

    def add(self, acc: _BaseAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)

    def merge(self, a: _BaseAcc, b: _BaseAcc) -> _BaseAcc:
        a.fold(b)
        return a

    def result(self, acc, key, window):
        return _base_result("pattern_key", key, window, acc)


class HighFrequencyAggregate(Aggregate):
    """Early-firing burst detector (HighFrequencyAggregateFunction analog):
    fires every `trigger_count` events inside the 5m window."""

    def create(self) -> _BaseAcc:
        return _BaseAcc()

    def add(self, acc: _BaseAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)

    def merge(self, a: _BaseAcc, b: _BaseAcc) -> _BaseAcc:
        a.fold(b)
        return a

    def result(self, acc, key, window):
        out = _base_result("user_id", key, window, acc)
        span = max(1.0, acc.last_ts - acc.first_ts)
        out["alert_type"] = "HIGH_FREQUENCY"
        out["transactions_per_second"] = acc.count / span
        return out


class AmountClusterAggregate(Aggregate):
    """Per log-bucket amount concentration (AmountClusterAggregateFunction
    analog). High same-bucket counts reveal structuring (many just-below-
    threshold amounts land in the same 9xxx bucket)."""

    def create(self) -> _BaseAcc:
        return _BaseAcc()

    def add(self, acc: _BaseAcc, txn: Txn, ts: float) -> None:
        acc.take(txn, ts)

    def merge(self, a: _BaseAcc, b: _BaseAcc) -> _BaseAcc:
        a.fold(b)
        return a

    def result(self, acc, key, window):
        return _base_result("amount_bucket", key, window, acc)


# ------------------------------------------------------------- key selectors
def geo_grid_key(txn: Txn) -> str:
    """1-degree grid key (GeographicKeySelector, WindowProcessor.java:173-193)."""
    geo = txn.get("geolocation") or {}
    lat, lon = geo.get("lat"), geo.get("lon")
    if lat is None or lon is None:
        return "unknown"
    return f"geo_{math.floor(float(lat))}_{math.floor(float(lon))}"


def amount_bucket(amount: float) -> str:
    """Range buckets (FraudPatternKeySelector.getAmountBucket, :213-221)."""
    if amount < 10:
        return "micro"
    if amount < 100:
        return "small"
    if amount < 500:
        return "medium"
    if amount < 2000:
        return "large"
    if amount < 10000:
        return "very_large"
    return "extreme"


def fraud_pattern_key(txn: Txn) -> str:
    """(payment, merchant-category, amount-bucket) cell key
    (FraudPatternKeySelector, :198-222)."""
    pm = txn.get("payment_method") or "unknown"
    cat = txn.get("merchant_category") or "unknown"
    amount = float(txn.get("amount") or 0.0)
    return f"pattern_{pm}_{cat}_{amount_bucket(amount)}"


def amount_cluster_key(txn: Txn) -> str:
    """Logarithmic bucket key (AmountClusterKeySelector, :227-242):
    amount_{floor(log10)}_{leading digit band}."""
    amount = float(txn.get("amount") or 0.0)
    if amount <= 0:
        return "zero"
    bucket = math.floor(math.log10(amount))
    sub = math.floor(amount / (10.0 ** bucket))
    return f"amount_{bucket}_{sub}"


# ---------------------------------------------------------------- operator
class WindowOperator:
    """One keyed event-time window computation.

    ``process(txn, ts)`` adds the event and returns any results fired by a
    count trigger; ``advance_watermark(ts)`` (called automatically as event
    time progresses) closes windows whose end precedes
    watermark = max_event_time - out_of_orderness and returns their results.
    Late events (behind the watermark) are counted and dropped, mirroring
    Flink's default lateness handling.
    """

    def __init__(
        self,
        name: str,
        key_fn: Callable[[Txn], str],
        assigner: SlidingWindow | TumblingWindow | SessionWindow,
        aggregate: Aggregate,
        out_of_orderness_s: float = DEFAULT_OUT_OF_ORDERNESS_S,
        trigger_count: Optional[int] = None,
    ):
        self.name = name
        self.key_fn = key_fn
        self.assigner = assigner
        self.agg = aggregate
        self.ooo_s = out_of_orderness_s
        self.trigger_count = trigger_count
        self._is_session = isinstance(assigner, SessionWindow)
        # (key, (start, end)) -> (accumulator, events_since_fire)
        self._windows: Dict[Tuple[str, Tuple[float, float]], List[Any]] = {}
        self.max_event_ts = -math.inf
        self._fired_wm = -math.inf    # watermark at the last eviction scan
        # earliest end among open windows (may be stale-low after session
        # merges — that only costs an occasional no-op scan, never misses)
        self._min_open_end = math.inf
        self.late_dropped = 0
        self.fired = 0

    @property
    def watermark(self) -> float:
        return self.max_event_ts - self.ooo_s

    def process(self, txn: Txn, ts: float) -> List[Dict[str, Any]]:
        self.max_event_ts = max(self.max_event_ts, ts)
        wm = self.watermark
        key = self.key_fn(txn)
        fired: List[Dict[str, Any]] = []
        if self._is_session:
            if ts + self.assigner.gap_s > wm:
                self._add_session(key, txn, ts)
            else:
                self.late_dropped += 1
        else:
            # an element is late only when ALL its windows are already
            # closed (Flink semantics) — a slightly-late event still lands
            # in its open windows
            open_windows = [w for w in self.assigner.assign(ts) if w[1] > wm]
            if not open_windows:
                self.late_dropped += 1
            for window in open_windows:
                slot = self._windows.get((key, window))
                if slot is None:
                    slot = self._windows[(key, window)] = [self.agg.create(), 0]
                    self._min_open_end = min(self._min_open_end, window[1])
                self.agg.add(slot[0], txn, ts)
                slot[1] += 1
                if self.trigger_count and slot[1] >= self.trigger_count:
                    # early fire: emit current aggregate, keep accumulating
                    # (Flink CountTrigger FIREs without purging)
                    fired.append(self.agg.result(slot[0], key, window))
                    self.fired += 1
                    slot[1] = 0
        fired.extend(self.advance_watermark(self.max_event_ts))
        return fired

    def _add_session(self, key: str, txn: Txn, ts: float) -> None:
        """Merge the event's [ts, ts+gap) window with overlapping sessions."""
        (start, end), = self.assigner.assign(ts)
        acc = self.agg.create()
        self.agg.add(acc, txn, ts)
        merged_keys = [
            (k, w) for (k, w) in self._windows
            if k == key and w[0] <= end and start <= w[1]
        ]
        for k_w in merged_keys:
            other_acc, _ = self._windows.pop(k_w)
            acc = self.agg.merge(acc, other_acc)
            start = min(start, k_w[1][0])
            end = max(end, k_w[1][1])
        self._windows[(key, (start, end))] = [acc, 0]
        self._min_open_end = min(self._min_open_end, end)

    def advance_watermark(self, event_ts: Optional[float] = None
                          ) -> List[Dict[str, Any]]:
        if event_ts is not None:
            self.max_event_ts = max(self.max_event_ts, event_ts)
        wm = self.watermark
        # hot-path fast exits: nothing to do unless the watermark moved AND
        # crossed the earliest open window's end (in-order streams advance
        # the watermark every event; without the second check each event
        # would pay a full open-window scan)
        if wm <= self._fired_wm or wm < self._min_open_end:
            if wm > self._fired_wm:
                self._fired_wm = wm
            return []
        self._fired_wm = wm
        fired = []
        for (key, window) in sorted(
                [kw for kw in self._windows if kw[1][1] <= wm],
                key=lambda kw: kw[1][1]):
            acc, _ = self._windows.pop((key, window))
            fired.append(self.agg.result(acc, key, window))
            self.fired += 1
        self._min_open_end = min(
            (kw[1][1] for kw in self._windows), default=math.inf)
        return fired

    def flush(self) -> List[Dict[str, Any]]:
        """Close every open window (end-of-stream)."""
        fired = []
        for (key, window) in sorted(self._windows, key=lambda kw: kw[1][1]):
            acc, _ = self._windows.pop((key, window))
            fired.append(self.agg.result(acc, key, window))
            self.fired += 1
        self._min_open_end = math.inf
        return fired

    def __len__(self) -> int:
        return len(self._windows)


# ------------------------------------------------------------ constructors
def user_velocity_windows() -> WindowOperator:
    """Sliding 5m/1m per-user velocity (WindowProcessor.java:36-52)."""
    return WindowOperator(
        "user_velocity", lambda t: str(t.get("user_id")),
        SlidingWindow(300.0, 60.0), UserVelocityAggregate())


def merchant_pattern_windows() -> WindowOperator:
    """Tumbling 1h per-merchant patterns (:55-71)."""
    return WindowOperator(
        "merchant_patterns", lambda t: str(t.get("merchant_id")),
        TumblingWindow(3600.0), MerchantPatternAggregate())


def user_session_windows() -> WindowOperator:
    """30m-gap user sessions (:74-90)."""
    return WindowOperator(
        "user_sessions", lambda t: str(t.get("user_id")),
        SessionWindow(1800.0), UserSessionAggregate())


def geo_cluster_windows() -> WindowOperator:
    """Tumbling 15m per geo grid cell (:93-109)."""
    return WindowOperator(
        "geo_clusters", geo_grid_key, TumblingWindow(900.0),
        GeoClusterAggregate())


def fraud_pattern_windows() -> WindowOperator:
    """Sliding 10m/2m per pattern cell (:112-126)."""
    return WindowOperator(
        "fraud_patterns", fraud_pattern_key, SlidingWindow(600.0, 120.0),
        FraudPatternAggregate())


def high_frequency_windows(trigger_count: int = 10) -> WindowOperator:
    """Tumbling 5m per user with count-10 early trigger, 5s watermark
    (:129-150)."""
    return WindowOperator(
        "high_frequency", lambda t: str(t.get("user_id")),
        TumblingWindow(300.0), HighFrequencyAggregate(),
        out_of_orderness_s=5.0, trigger_count=trigger_count)


def amount_cluster_windows() -> WindowOperator:
    """Tumbling 30m per log-amount bucket (:153-169)."""
    return WindowOperator(
        "amount_clusters", amount_cluster_key, TumblingWindow(1800.0),
        AmountClusterAggregate())


# --------------------------------------------------------------- composite
# result topic per operator (create-topics.sh stream-processing group)
ANALYTICS_TOPIC = {
    "user_velocity": "velocity-checks",
    "merchant_patterns": "merchant-transactions",
    "user_sessions": "user-sessions",
    "geo_clusters": "geographic-analysis",
    "fraud_patterns": "pattern-detection",
    "high_frequency": "velocity-checks",
    "amount_clusters": "transaction-metrics",
}


class WindowedAnalytics:
    """All seven window computations over one stream, fanning results out to
    the stream-processing topics (the analytics side of the reference's job
    graph that was never attached, SURVEY.md §0.3)."""

    def __init__(self, broker=None,
                 operators: Optional[Iterable[WindowOperator]] = None):
        self.broker = broker
        self.operators = list(operators) if operators is not None else [
            user_velocity_windows(), merchant_pattern_windows(),
            user_session_windows(), geo_cluster_windows(),
            fraud_pattern_windows(), high_frequency_windows(),
            amount_cluster_windows(),
        ]

    def process(self, txn: Txn, ts: float) -> Dict[str, List[Dict[str, Any]]]:
        out: Dict[str, List[Dict[str, Any]]] = {}
        for op in self.operators:
            fired = op.process(txn, ts)
            if fired:
                out[op.name] = fired
                self._emit(op.name, fired)
        return out

    def flush(self) -> Dict[str, List[Dict[str, Any]]]:
        out = {}
        for op in self.operators:
            fired = op.flush()
            if fired:
                out[op.name] = fired
                self._emit(op.name, fired)
        return out

    def _emit(self, name: str, results: List[Dict[str, Any]]) -> None:
        if self.broker is None:
            return
        topic = ANALYTICS_TOPIC[name]
        for r in results:
            self.broker.produce(topic, r)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {
            op.name: {"open_windows": len(op), "fired": op.fired,
                      "late_dropped": op.late_dropped,
                      "watermark": op.watermark}
            for op in self.operators
        }

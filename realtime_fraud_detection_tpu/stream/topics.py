"""Topic contract: names, partition counts, retention/compaction classes.

Mirror of the reference's Kafka topic contract (create-topics.sh:60-151):
29 reference topics — 27 regular + 2 compacted profile topics — across
core / behavioral / alert / stream-processing / analytics / test groups,
RF=3 minISR=2 lz4 in the real deployment, plus this framework's one
extension: ``transaction-labels``, the delayed ground-truth stream that
closes the continuous-learning loop (feedback/). The in-memory broker
honors the same names and partition counts so partition-keyed ordering
semantics match a real Kafka deployment.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class TopicSpec:
    name: str
    partitions: int
    compacted: bool = False


# names + partition counts exactly as create-topics.sh materializes them
TOPIC_SPECS: tuple[TopicSpec, ...] = (
    # core transaction flow (create-topics.sh:92-96)
    TopicSpec("payment-transactions", 12),
    TopicSpec("transaction-enriched", 12),
    TopicSpec("transaction-features", 12),
    TopicSpec("fraud-predictions", 12),
    TopicSpec("fraud-decisions", 6),
    # compacted profile topics (:103, :114)
    TopicSpec("user-profiles", 6, compacted=True),
    TopicSpec("merchant-profiles", 4, compacted=True),
    # user & behavioral (:101-110)
    TopicSpec("user-behavior", 8),
    TopicSpec("device-fingerprints", 4),
    TopicSpec("user-sessions", 6),
    TopicSpec("login-events", 4),
    # merchant & risk (:112-120)
    TopicSpec("merchant-transactions", 8),
    TopicSpec("risk-signals", 6),
    TopicSpec("blacklist-updates", 2),
    # alerts & audit (:122-128)
    TopicSpec("fraud-alerts", 6),
    TopicSpec("system-alerts", 2),
    TopicSpec("audit-logs", 4),
    TopicSpec("model-metrics", 2),
    # stream processing (:130-136)
    TopicSpec("velocity-checks", 8),
    TopicSpec("geographic-analysis", 4),
    TopicSpec("pattern-detection", 6),
    TopicSpec("network-analysis", 4),
    # analytics & reporting (:138-144)
    TopicSpec("transaction-metrics", 4),
    TopicSpec("fraud-metrics", 2),
    TopicSpec("dashboard-updates", 2),
    TopicSpec("reporting-data", 4),
    # test topics (:146-151)
    TopicSpec("test-transactions", 4),
    TopicSpec("model-experiments", 2),
    TopicSpec("feature-experiments", 2),
    # framework extension (no reference analog): delayed ground-truth
    # labels — chargeback outcomes keyed by user like the transactions
    # they label, consumed by the continuous-learning plane (feedback/)
    TopicSpec("transaction-labels", 12),
)

TOPIC_BY_NAME = {t.name: t for t in TOPIC_SPECS}

TRANSACTIONS = "payment-transactions"
ENRICHED = "transaction-enriched"
FEATURES = "transaction-features"
PREDICTIONS = "fraud-predictions"
DECISIONS = "fraud-decisions"
ALERTS = "fraud-alerts"
LABELS = "transaction-labels"

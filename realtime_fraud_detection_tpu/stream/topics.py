"""Topic contract: names, partition counts, retention/compaction classes.

Mirror of the reference's Kafka topic contract (create-topics.sh:101-160):
29 topics across core / behavioral / alert / stream-processing / analytics /
test groups, RF=3 minISR=2 lz4 in the real deployment. The in-memory broker
honors the same names and partition counts so partition-keyed ordering
semantics match a real Kafka deployment.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class TopicSpec:
    name: str
    partitions: int
    compacted: bool = False


# (create-topics.sh:101-160)
TOPIC_SPECS: tuple[TopicSpec, ...] = (
    # core transaction flow
    TopicSpec("payment-transactions", 12),
    TopicSpec("transaction-enriched", 12),
    TopicSpec("transaction-features", 12),
    TopicSpec("fraud-predictions", 12),
    TopicSpec("fraud-decisions", 6),
    # compacted profile topics
    TopicSpec("user-profiles", 6, compacted=True),
    TopicSpec("merchant-profiles", 4, compacted=True),
    # behavioral
    TopicSpec("user-behavior", 8),
    TopicSpec("session-events", 8),
    TopicSpec("device-fingerprints", 4),
    # alerts
    TopicSpec("fraud-alerts", 6),
    TopicSpec("high-risk-transactions", 6),
    TopicSpec("manual-review-queue", 4),
    # stream processing
    TopicSpec("velocity-checks", 8),
    TopicSpec("pattern-analysis", 8),
    TopicSpec("geolocation-events", 6),
    TopicSpec("merchant-analytics", 4),
    # analytics / audit
    TopicSpec("transaction-analytics", 6),
    TopicSpec("model-metrics", 4),
    TopicSpec("audit-log", 4),
    # test topics (create-topics.sh:148-151)
    TopicSpec("test-transactions", 2),
    TopicSpec("model-experiments", 2),
    TopicSpec("feature-experiments", 2),
)

TOPIC_BY_NAME = {t.name: t for t in TOPIC_SPECS}

TRANSACTIONS = "payment-transactions"
ENRICHED = "transaction-enriched"
FEATURES = "transaction-features"
PREDICTIONS = "fraud-predictions"
DECISIONS = "fraud-decisions"
ALERTS = "fraud-alerts"

"""Kafka transport: a dependency-free client speaking the Kafka wire protocol.

The reference's backbone is Kafka — idempotent lz4 producers, read_committed
consumers, 29 topics (config/kafka/producer.properties,
FraudDetectionJob.java:141-213, scripts/setup/create-topics.sh). No Kafka
client library is baked into this image, so this module implements the
protocol directly over TCP (the format is public: kafka.apache.org/protocol):

  Metadata v1 · Produce v2 (MessageSet v1 + CRC32) · Produce v3
  (RecordBatch v2 + CRC32C, idempotent) · Fetch v2 · ListOffsets v1 ·
  FindCoordinator v0 · OffsetCommit v2 · OffsetFetch v1 ·
  InitProducerId v0 · JoinGroup v1 · SyncGroup v0 · Heartbeat v0 ·
  LeaveGroup v0 (membership client lives in stream/kafka_group.py)

``KafkaBroker`` exposes the exact broker interface the framework's
``transport.Consumer`` consumes (committed/partitions/read/commit/lag plus
the producer surface), so ``StreamJob(broker=KafkaBroker(...))`` runs
unchanged against a real cluster — same contract suite as InMemoryBroker
and NetBrokerClient (tests/test_kafka.py runs it against an in-process
protocol fake, stream/kafka_fake.py).

Production semantics (reference config/kafka/*.properties):
- ``idempotent=True`` == ``enable.idempotence=true`` (producer.properties:8):
  batches go out as RecordBatch v2 stamped (producer_id, epoch,
  base_sequence) via InitProducerId + Produce v3; a retry after a lost ack
  resends the SAME sequence and the broker dedupes it. acks defaults to -1
  (``acks=all``, producer.properties:19).
- ``consumer(..., group_managed=True)`` == the reference's consumer group
  (consumer.properties:5): coordinator-managed membership with automatic
  partition rebalance on member death (stream/kafka_group.py).

Scope notes (deliberate, documented):
- ``compression="gzip"`` on the RecordBatch v2 producer path mirrors the
  reference's ``compression.type=lz4`` (producer.properties:11) with the
  codec this image's stdlib provides — lz4 has none; codec choice is
  per-batch in the protocol. The legacy v1 message-set path (non-idempotent
  producers) stays uncompressed.
- Exactly-once is the framework's own offset/dedupe protocol (commit after
  fan-out + txn-cache dedupe, stream/job.py), not Kafka transactions.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from realtime_fraud_detection_tpu.stream.transport import (
    Consumer,
    FaultInjector,
    Record,
)

__all__ = ["KafkaBroker", "KafkaConnection", "KafkaProtocolError"]

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14
API_INIT_PRODUCER_ID = 22

ERR_OFFSET_OUT_OF_RANGE = 1
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27
ERR_OUT_OF_ORDER_SEQUENCE = 45

_ERRORS = {
    0: "NONE", 1: "OFFSET_OUT_OF_RANGE", 3: "UNKNOWN_TOPIC_OR_PARTITION",
    5: "LEADER_NOT_AVAILABLE", 6: "NOT_LEADER_FOR_PARTITION",
    15: "COORDINATOR_NOT_AVAILABLE", 16: "NOT_COORDINATOR",
    22: "ILLEGAL_GENERATION", 25: "UNKNOWN_MEMBER_ID",
    27: "REBALANCE_IN_PROGRESS", 45: "OUT_OF_ORDER_SEQUENCE_NUMBER",
}


class KafkaProtocolError(RuntimeError):
    def __init__(self, api: str, code: int):
        super().__init__(
            f"{api}: error_code={code} ({_ERRORS.get(code, 'UNKNOWN')})")
        self.code = code


# ---------------------------------------------------------------------------
# primitive codec (big-endian, pre-flexible-versions encoding)
# ---------------------------------------------------------------------------


class Writer:
    __slots__ = ("_parts",)

    def __init__(self) -> None:
        self._parts: List[bytes] = []

    def i8(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">b", v)); return self

    def i16(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">h", v)); return self

    def i32(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">i", v)); return self

    def i64(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">q", v)); return self

    def u32(self, v: int) -> "Writer":
        self._parts.append(struct.pack(">I", v)); return self

    def string(self, s: Optional[str]) -> "Writer":
        if s is None:
            return self.i16(-1)
        b = s.encode()
        self.i16(len(b)); self._parts.append(b); return self

    def bytes_(self, b: Optional[bytes]) -> "Writer":
        if b is None:
            return self.i32(-1)
        self.i32(len(b)); self._parts.append(b); return self

    def raw(self, b: bytes) -> "Writer":
        self._parts.append(b); return self

    def array(self, items, encode_one) -> "Writer":
        self.i32(len(items))
        for it in items:
            encode_one(self, it)
        return self

    def done(self) -> bytes:
        return b"".join(self._parts)


class Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0) -> None:
        self.buf = buf
        self.pos = pos

    def _take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) < n:
            raise EOFError("short read in Kafka frame")
        self.pos += n
        return b

    def i8(self) -> int:
        return struct.unpack(">b", self._take(1))[0]

    def i16(self) -> int:
        return struct.unpack(">h", self._take(2))[0]

    def i32(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def i64(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self._take(n)

    def array(self, decode_one) -> list:
        return [decode_one(self) for _ in range(self.i32())]

    def remaining(self) -> int:
        return len(self.buf) - self.pos


# ---------------------------------------------------------------------------
# MessageSet v1 (magic=1): the on-wire record format for Produce/Fetch v0-v3
# ---------------------------------------------------------------------------


def encode_message_set(
    messages: Sequence[Tuple[Optional[bytes], Optional[bytes], int]],
) -> bytes:
    """[(key, value, timestamp_ms)] -> MessageSet v1 bytes (offsets 0..n-1;
    the broker rewrites offsets on append)."""
    w = Writer()
    for i, (key, value, ts) in enumerate(messages):
        body = (
            Writer().i8(1).i8(0).i64(ts).bytes_(key).bytes_(value).done()
        )  # magic=1, attributes=0 (uncompressed)
        crc = zlib.crc32(body) & 0xFFFFFFFF
        msg = Writer().u32(crc).raw(body).done()
        w.i64(i).i32(len(msg)).raw(msg)
    return w.done()


def decode_message_set(buf: bytes) -> List[Tuple[int, Optional[bytes], Optional[bytes], int]]:
    """MessageSet bytes -> [(offset, key, value, timestamp_ms)].

    A Fetch response may end with a truncated message (Kafka semantics);
    the incomplete tail is dropped. CRC is verified per message.

    Handles what a real broker can hand a Fetch v2 consumer:
    - plain v0/v1 messages;
    - a gzip WRAPPER message (codec bits 1): its value is itself an encoded
      message set holding the batch — the down-converted form of this
      client's own gzip RecordBatch v2 produces. The wrapper's offset is
      the offset of the LAST inner message (v1 semantics); inner relative
      offsets are rebased accordingly;
    - a raw RecordBatch v2 (magic=2) if the broker skips down-conversion.
    """
    out: List[Tuple[int, Optional[bytes], Optional[bytes], int]] = []
    r = Reader(buf)
    while r.remaining() >= 12:
        # magic=2 batches are not framed as [offset][size][message]: peek
        # the magic byte at its fixed RecordBatch position (offset 16)
        if r.remaining() >= 17 and r.buf[r.pos + 16] == 2:
            base = r.pos
            _off, size = struct.unpack_from(">qi", r.buf, base)
            if r.remaining() < 12 + size:
                break                  # truncated trailing batch
            batch = r._take(12 + size)
            recs, _pid, _pe, _seq = decode_record_batch(batch)
            out.extend(recs)
            continue
        offset = r.i64()
        size = r.i32()
        if r.remaining() < size:
            break                      # truncated trailing message
        msg = Reader(r._take(size))
        crc = msg.u32()
        body_start = msg.pos
        if zlib.crc32(msg.buf[body_start:]) & 0xFFFFFFFF != crc:
            raise ValueError(f"bad CRC in message at offset {offset}")
        magic = msg.i8()
        attributes = msg.i8()
        codec = attributes & 0x07
        ts = msg.i64() if magic >= 1 else -1
        key = msg.bytes_()
        value = msg.bytes_()
        if codec == 0:
            out.append((offset, key, value, ts))
            continue
        if codec != 1 or value is None:
            raise NotImplementedError(
                f"unsupported message-set codec {codec} (gzip only)")
        import gzip as _gzip

        inner = decode_message_set(_gzip.decompress(value))
        # v1 wrapper offset = offset of the LAST inner message; inner
        # offsets are 0..n-1 relative
        last_rel = inner[-1][0] if inner else 0
        for rel, ik, iv, its in inner:
            out.append((offset - last_rel + rel, ik, iv,
                        its if its != -1 else ts))
    return out


# ---------------------------------------------------------------------------
# RecordBatch v2 (magic=2): the format idempotent producers must use — it is
# the only record format carrying producerId/producerEpoch/baseSequence
# (reference producer.properties:8 enable.idempotence=true). Varint-encoded
# records, CRC32C (Castagnoli) integrity — implemented here because zlib
# only has CRC32.
# ---------------------------------------------------------------------------


def _crc32c_table() -> List[int]:
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ 0x82F63B78 if crc & 1 else crc >> 1
        table.append(crc)
    return table


_CRC32C = _crc32c_table()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _write_varint(out: bytearray, v: int) -> None:
    """Zigzag + LEB128, the Kafka record field encoding."""
    u = ((v << 1) ^ (v >> 63)) & ((1 << 64) - 1)
    while True:
        if u < 0x80:
            out.append(u)
            return
        out.append((u & 0x7F) | 0x80)
        u >>= 7


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift, u = 0, 0
    while True:
        b = buf[pos]
        pos += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    v = (u >> 1) ^ -(u & 1)
    return v, pos


def encode_record_batch(
    messages: Sequence[Tuple[Optional[bytes], Optional[bytes], int]],
    producer_id: int = -1, producer_epoch: int = -1,
    base_sequence: int = -1, compression: Optional[str] = None,
) -> bytes:
    """[(key, value, timestamp_ms)] -> RecordBatch v2 bytes.

    ``compression="gzip"`` gzips the records section and sets the batch
    attributes codec bits (codec 1) — the v2 analog of the reference's
    ``compression.type`` producer setting (producer.properties:11; the
    reference uses lz4, whose codec has no stdlib implementation here, so
    this client speaks gzip — codec negotiation is per-batch in the
    protocol, brokers accept any supported codec).
    """
    first_ts = messages[0][2]
    max_ts = max(m[2] for m in messages)
    records = bytearray()
    for i, (key, value, ts) in enumerate(messages):
        body = bytearray()
        body.append(0)                            # record attributes
        _write_varint(body, ts - first_ts)
        _write_varint(body, i)                    # offset delta
        for blob in (key, value):
            if blob is None:
                _write_varint(body, -1)
            else:
                _write_varint(body, len(blob))
                body.extend(blob)
        _write_varint(body, 0)                    # headers
        _write_varint(records, len(body))
        records.extend(body)
    if compression is None:
        attrs, records_wire = 0, bytes(records)
    elif compression == "gzip":
        import gzip as _gzip

        attrs, records_wire = 1, _gzip.compress(bytes(records), mtime=0)
    else:
        raise ValueError(f"unsupported compression codec: {compression}")
    after_crc = (
        struct.pack(">hiqqqhii", attrs, len(messages) - 1, first_ts, max_ts,
                    producer_id, producer_epoch, base_sequence,
                    len(messages))
        + records_wire
    )
    crc = crc32c(after_crc)
    tail = struct.pack(">ibI", -1, 2, crc) + after_crc   # leaderEpoch, magic
    return struct.pack(">qi", 0, len(tail)) + tail       # baseOffset, length


def decode_record_batch(buf: bytes) -> Tuple[
    List[Tuple[int, Optional[bytes], Optional[bytes], int]], int, int, int,
]:
    """RecordBatch v2 bytes -> ([(offset_delta, key, value, ts_ms)],
    producer_id, producer_epoch, base_sequence). Verifies CRC32C."""
    base_offset, _length, _epoch, magic, crc = struct.unpack_from(">qiibI", buf)
    if magic != 2:
        raise ValueError(f"not a v2 record batch (magic={magic})")
    after_crc = buf[21:]
    if crc32c(after_crc) != crc:
        raise ValueError("bad CRC32C in record batch")
    (attrs, _last_delta, first_ts, _max_ts, pid, pepoch, base_seq,
     count) = struct.unpack_from(">hiqqqhii", after_crc)
    hdr_end = struct.calcsize(">hiqqqhii")
    codec = attrs & 0x07
    if codec == 0:
        recs, pos = after_crc, hdr_end
    elif codec == 1:                              # gzip
        import gzip as _gzip

        recs, pos = _gzip.decompress(after_crc[hdr_end:]), 0
    else:
        raise ValueError(f"unsupported record-batch codec {codec}")
    out: List[Tuple[int, Optional[bytes], Optional[bytes], int]] = []
    for _ in range(count):
        _rec_len, pos = _read_varint(recs, pos)
        pos += 1                                  # record attributes
        ts_delta, pos = _read_varint(recs, pos)
        off_delta, pos = _read_varint(recs, pos)
        blobs: List[Optional[bytes]] = []
        for _f in range(2):
            n, pos = _read_varint(recs, pos)
            if n < 0:
                blobs.append(None)
            else:
                blobs.append(recs[pos:pos + n])
                pos += n
        n_headers, pos = _read_varint(recs, pos)
        for _h in range(n_headers):
            for _kv in range(2):
                n, pos = _read_varint(recs, pos)
                pos += max(0, n)
        out.append((base_offset + off_delta, blobs[0], blobs[1],
                    first_ts + ts_delta))
    return out, pid, pepoch, base_seq


# ---------------------------------------------------------------------------
# connection: framed request/response with correlation ids
# ---------------------------------------------------------------------------


class KafkaConnection:
    """One broker connection. Thread-safe; requests are serialized."""

    def __init__(self, host: str, port: int, client_id: str = "rtfd-tpu",
                 timeout_s: float = 30.0):
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._corr = 0

    def reconnect(self) -> None:
        """Re-dial after a broken connection (the idempotent producer's
        retry path: resend the SAME batch/sequence on the new socket)."""
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def request(self, api_key: int, api_version: int, body: bytes,
                expect_response: bool = True) -> Optional[Reader]:
        with self._lock:
            self._corr += 1
            corr = self._corr
            header = (
                Writer().i16(api_key).i16(api_version).i32(corr)
                .string(self.client_id).done()
            )
            frame = header + body
            self._sock.sendall(struct.pack(">i", len(frame)) + frame)
            if not expect_response:   # acks=0 Produce: broker sends nothing
                return None
            resp = self._recv_frame()
        r = Reader(resp)
        got_corr = r.i32()
        if got_corr != corr:
            raise RuntimeError(
                f"correlation mismatch: sent {corr}, got {got_corr}")
        return r

    def _recv_frame(self) -> bytes:
        header = self._recv_exact(4)
        (length,) = struct.unpack(">i", header)
        return self._recv_exact(length)

    def _recv_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("Kafka broker closed the connection")
            buf.extend(chunk)
        return bytes(buf)


# ---------------------------------------------------------------------------
# the transport adapter
# ---------------------------------------------------------------------------


class KafkaBroker:
    """Kafka-backed implementation of the framework's broker interface.

    Values are JSON dicts (the §2.5 payload contract), keys are UTF-8
    strings. Partitioning for keyed produces is done broker-side? No —
    Kafka clients partition; we hash the key exactly like InMemoryBroker
    (same key -> same partition -> per-key ordering).
    """

    def __init__(self, bootstrap: str = "127.0.0.1:9092",
                 client_id: str = "rtfd-tpu", acks: int = -1,
                 timeout_s: float = 30.0, idempotent: bool = False,
                 compression: Optional[str] = None,
                 retry_sleep=None):
        from realtime_fraud_detection_tpu.utils.backoff import (
            DeterministicBackoff,
            instance_seed,
        )

        host, _, port = bootstrap.partition(":")
        # produce-retry schedule: bounded exponential + deterministic
        # jitter, seeded per client INSTANCE (most callers share the
        # default client_id, and those are exactly the producers whose
        # retry storms must de-synchronize); ``retry_sleep`` is the
        # injected seam (tests / the chaos plane pass a recording or
        # virtual-clock sleep)
        self._backoff = DeterministicBackoff(
            base_s=0.05, mult=2.0, max_s=0.8,
            seed=instance_seed(client_id), sleep=retry_sleep)
        self.acks = acks                         # -1 == acks=all (reference)
        self.timeout_s = timeout_s
        # producer-side codec (reference compression.type=lz4,
        # producer.properties:11; we speak gzip — see encode_record_batch).
        # Applied on the RecordBatch v2 path, i.e. requires idempotent=True.
        if compression is not None and not idempotent:
            raise ValueError(
                "compression requires the RecordBatch v2 producer "
                "(idempotent=True); the legacy v1 message-set path stays "
                "uncompressed")
        self.compression = compression
        self._conn = KafkaConnection(host, int(port or 9092), client_id,
                                     timeout_s)
        self._coord: Optional[KafkaConnection] = None
        self._meta: Dict[str, List[int]] = {}    # topic -> partition ids
        self._rr: Dict[str, int] = {}
        # idempotent produce (producer.properties:8 enable.idempotence=true):
        # RecordBatch v2 stamped with (producer_id, epoch, base_sequence);
        # the broker dedupes a retried batch by sequence number, so a resend
        # after a lost ack cannot double-append.
        self.idempotent = idempotent
        if idempotent and acks == 0:
            raise ValueError("idempotent produce requires acks != 0")
        self._pid = -1
        self._pepoch = -1
        self._seq: Dict[Tuple[str, int], int] = {}   # (topic, part) -> next
        # _seq_lock guards only pid init + per-partition lock creation; the
        # network I/O (and its retries/backoff) runs under a PER-PARTITION
        # lock, so a wedged partition can't serialize the whole producer —
        # while same-partition produces stay strictly in sequence order.
        self._seq_lock = threading.Lock()
        self._part_locks: Dict[Tuple[str, int], threading.Lock] = {}

    def close(self) -> None:
        self._conn.close()
        if self._coord is not None and self._coord is not self._conn:
            self._coord.close()

    # ------------------------------------------------------------- metadata
    def _metadata(self, topic: str) -> List[int]:
        parts = self._meta.get(topic)
        if parts:
            return parts
        # LEADER_NOT_AVAILABLE (5) while an auto-created topic elects a
        # leader is transient — retry with backoff before giving up
        # rtfd-lint: allow[wall-clock] real-broker client: I/O deadlines and record timestamps
        deadline = time.monotonic() + min(self.timeout_s, 10.0)
        last_err = 3
        while True:
            body = Writer().array([topic], lambda w, t: w.string(t)).done()
            r = self._conn.request(API_METADATA, 1, body)
            r.array(lambda rr: (rr.i32(), rr.string(), rr.i32(), rr.string()))
            r.i32()                               # controller_id
            topics = r.array(lambda rr: (
                rr.i16(), rr.string(), rr.i8(),
                rr.array(lambda p: (
                    p.i16(), p.i32(), p.i32(),
                    p.array(Reader.i32), p.array(Reader.i32))),
            ))
            for err, name, _internal, partitions in topics:
                if err:
                    last_err = err
                    continue
                self._meta[name] = sorted(p[1] for p in partitions)
            parts = self._meta.get(topic)
            if parts:
                return parts
            # rtfd-lint: allow[wall-clock] real-broker client: I/O deadlines and record timestamps
            if last_err not in (5, 3) or time.monotonic() >= deadline:
                raise KafkaProtocolError("Metadata", last_err)
            time.sleep(0.1)

    def partitions(self, topic: str) -> int:
        return len(self._metadata(topic))

    # -------------------------------------------------------------- produce
    def _pick_partition(self, topic: str, key: Optional[str]) -> int:
        n = self.partitions(topic)
        if key is not None:
            # stable across processes (Python's str hash is salted per
            # process): same key -> same partition from every producer
            return zlib.crc32(key.encode()) % n
        cur = self._rr.get(topic, 0)
        self._rr[topic] = cur + 1
        return cur % n

    def produce(self, topic: str, value: Any, key: Optional[str] = None,
                timestamp: Optional[float] = None) -> Record:
        part = self._pick_partition(topic, key)
        # rtfd-lint: allow[wall-clock] real-broker client: I/O deadlines and record timestamps
        ts = timestamp if timestamp is not None else time.time()
        offset = self._produce_raw(topic, part, [(
            key.encode() if key is not None else None,
            json.dumps(value, separators=(",", ":")).encode(),
            int(ts * 1000),
        )])
        return Record(topic, part, offset, key, value, ts)

    def produce_batch(self, topic: str, values, key_fn=None) -> int:
        by_part: Dict[int, list] = {}
        # rtfd-lint: allow[wall-clock] real-broker client: I/O deadlines and record timestamps
        now_ms = int(time.time() * 1000)
        n = 0
        for v in values:
            key = key_fn(v) if key_fn else None
            part = self._pick_partition(topic, key)
            by_part.setdefault(part, []).append((
                key.encode() if key is not None else None,
                json.dumps(v, separators=(",", ":")).encode(), now_ms))
            n += 1
        for part, msgs in by_part.items():
            self._produce_raw(topic, part, msgs)
        return n

    def produce_batch_keyed(self, topic: str, items) -> int:
        """(key, value) pairs batched into per-partition RecordBatches —
        same wire efficiency as produce_batch, explicit keys."""
        by_part: Dict[int, list] = {}
        # rtfd-lint: allow[wall-clock] real-broker client: I/O deadlines and record timestamps
        now_ms = int(time.time() * 1000)
        n = 0
        for key, v in items:
            part = self._pick_partition(topic, key)
            by_part.setdefault(part, []).append((
                key.encode() if key is not None else None,
                json.dumps(v, separators=(",", ":")).encode(), now_ms))
            n += 1
        for part, msgs in by_part.items():
            self._produce_raw(topic, part, msgs)
        return n

    def _init_producer_id(self) -> None:
        """InitProducerId v0: acquire (producer_id, epoch) for idempotence."""
        body = Writer().string(None).i32(60_000).done()
        r = self._conn.request(API_INIT_PRODUCER_ID, 0, body)
        r.i32()                                   # throttle_time_ms
        err = r.i16()
        if err:
            raise KafkaProtocolError("InitProducerId", err)
        self._pid = r.i64()
        self._pepoch = r.i16()

    def _produce_raw(self, topic: str, partition: int,
                     messages: List[Tuple[Optional[bytes], Optional[bytes], int]]) -> int:
        if not self.idempotent:
            return self._produce_request(
                topic, partition, encode_message_set(messages), api_version=2)
        key = (topic, partition)
        with self._seq_lock:
            if self._pid < 0:
                self._init_producer_id()
            pid, pepoch = self._pid, self._pepoch
            plock = self._part_locks.setdefault(key, threading.Lock())
        with plock:
            with self._seq_lock:
                if self._pid != pid:       # identity reset by another thread
                    pid, pepoch = self._pid, self._pepoch
                    if pid < 0:
                        self._init_producer_id()
                        pid, pepoch = self._pid, self._pepoch
                seq = self._seq.get(key, 0)
            record_set = encode_record_batch(
                messages, producer_id=pid, producer_epoch=pepoch,
                base_sequence=seq, compression=self.compression)
            # Retry the SAME bytes (same baseSequence) across connection
            # failures: the broker recognizes a replayed sequence and
            # returns the original offset instead of double-appending —
            # this is what enable.idempotence=true means.
            last_exc: Optional[Exception] = None
            for attempt in range(3):
                try:
                    off = self._produce_request(
                        topic, partition, record_set, api_version=3)
                    with self._seq_lock:
                        self._seq[key] = seq + len(messages)
                    return off
                except (ConnectionError, OSError) as e:
                    last_exc = e
                    # The partition lock deliberately spans this retry wait
                    # (baseSequence must not interleave); the wait itself
                    # goes through the injected backoff seam — bounded
                    # exponential with deterministic jitter, virtualizable
                    # by tests/drills instead of a fixed bare sleep.
                    self._backoff.sleep(attempt)
                    try:
                        self._conn.reconnect()
                    except OSError:
                        continue
            # Retries exhausted with the batch's fate unknown: the broker
            # may have appended it. The sequence is now unresolvable — a
            # LATER batch reusing it would be silently deduped as a
            # "retry" and lost. Discard the producer identity; the next
            # produce re-runs InitProducerId for a fresh (pid, seq=0).
            with self._seq_lock:
                self._pid = -1
                self._pepoch = -1
                self._seq.clear()
            raise ConnectionError(
                f"produce to {topic}/{partition} failed after retries"
            ) from last_exc

    def _produce_request(self, topic: str, partition: int,
                         record_set: bytes, api_version: int) -> int:
        w = Writer()
        if api_version >= 3:
            w.string(None)                        # transactional_id
        body = (
            w.i16(self.acks).i32(int(self.timeout_s * 1000))
            .array([None], lambda ww, _:
                   ww.string(topic).array([None], lambda w2, _2:
                                          w2.i32(partition).bytes_(record_set)))
            .done()
        )
        r = self._conn.request(API_PRODUCE, api_version, body,
                               expect_response=self.acks != 0)
        if r is None:                             # acks=0: fire and forget
            return -1
        base_offset = -1
        for _ in range(r.i32()):                  # topics
            r.string()
            for _ in range(r.i32()):              # partitions
                _part, err, off = r.i32(), r.i16(), r.i64()
                r.i64()                           # log_append_time
                if err:
                    raise KafkaProtocolError("Produce", err)
                base_offset = off
        r.i32()                                   # throttle_time_ms
        return base_offset

    # --------------------------------------------------------------- fetch
    def read(self, topic: str, partition: int, start: int,
             limit: int) -> List[Record]:
        body = (
            Writer().i32(-1).i32(0).i32(1)        # replica=-1, wait=0, min=1
            .array([None], lambda w, _:
                   w.string(topic).array([None], lambda w2, _2:
                                         w2.i32(partition).i64(start)
                                         .i32(4 * 1024 * 1024)))
            .done()
        )
        r = self._conn.request(API_FETCH, 2, body)
        r.i32()                                   # throttle_time_ms
        out: List[Record] = []
        for _ in range(r.i32()):
            t = r.string()
            for _ in range(r.i32()):
                part, err = r.i32(), r.i16()
                r.i64()                           # high watermark
                record_set = r.bytes_() or b""
                if err == 1:                      # OFFSET_OUT_OF_RANGE: empty
                    continue
                if err:
                    raise KafkaProtocolError("Fetch", err)
                for off, key, value, ts in decode_message_set(record_set):
                    if off < start:               # log-compaction semantics
                        continue
                    out.append(Record(
                        t, part, off,
                        key.decode() if key is not None else None,
                        json.loads(value) if value else None,
                        ts / 1000.0))
                    if len(out) >= limit:
                        break
        return out[:limit]

    def end_offsets(self, topic: str) -> List[int]:
        parts = self._metadata(topic)
        body = (
            Writer().i32(-1)
            .array([None], lambda w, _:
                   w.string(topic).array(parts, lambda w2, p:
                                         w2.i32(p).i64(-1)))
            .done()
        )
        r = self._conn.request(API_LIST_OFFSETS, 1, body)
        ends = {p: 0 for p in parts}
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                part, err, _ts, off = r.i32(), r.i16(), r.i64(), r.i64()
                if err:
                    raise KafkaProtocolError("ListOffsets", err)
                ends[part] = off
        return [ends[p] for p in parts]

    # ------------------------------------------------------------- offsets
    def _coordinator(self, group: str) -> KafkaConnection:
        if self._coord is not None:
            return self._coord
        body = Writer().string(group).done()
        r = self._conn.request(API_FIND_COORDINATOR, 0, body)
        err = r.i16()
        if err:
            raise KafkaProtocolError("FindCoordinator", err)
        node, host, port = r.i32(), r.string(), r.i32()
        del node
        if (host, port) == (self._conn.host, self._conn.port):
            self._coord = self._conn
        else:
            self._coord = KafkaConnection(host, port, self._conn.client_id,
                                          self.timeout_s)
        return self._coord

    def _invalidate_coordinator(self) -> None:
        if self._coord is not None and self._coord is not self._conn:
            self._coord.close()
        self._coord = None

    def _with_coordinator(self, group: str, api: str, do):
        """Run a coordinator request; on NOT_COORDINATOR (16) or
        COORDINATOR_NOT_AVAILABLE (15) — a coordinator failover —
        re-discover once and retry."""
        try:
            return do(self._coordinator(group))
        except KafkaProtocolError as e:
            if e.code not in (15, 16):
                raise
            self._invalidate_coordinator()
            return do(self._coordinator(group))

    def commit(self, group: str, offsets: Mapping[tuple, int],
               generation_id: int = -1, member_id: str = "") -> None:
        """Commit offsets. ``generation_id``/``member_id`` default to simple
        consumer mode; a GroupConsumer passes its membership so the
        coordinator fences commits from a member evicted by a rebalance."""
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for (topic, part), off in offsets.items():
            by_topic.setdefault(topic, []).append((part, off))
        if not by_topic:
            return
        body = (
            Writer().string(group).i32(generation_id).string(member_id)
            .i64(-1)
            .array(sorted(by_topic.items()), lambda w, kv:
                   w.string(kv[0]).array(kv[1], lambda w2, po:
                                         w2.i32(po[0]).i64(po[1])
                                         .string(None)))
            .done()
        )

        def _do(conn: KafkaConnection) -> None:
            r = conn.request(API_OFFSET_COMMIT, 2, body)
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    _part, err = r.i32(), r.i16()
                    if err:
                        raise KafkaProtocolError("OffsetCommit", err)

        self._with_coordinator(group, "OffsetCommit", _do)

    def committed(self, group: str, topic: str, partition: int) -> int:
        body = (
            Writer().string(group)
            .array([None], lambda w, _:
                   w.string(topic).array([partition], Writer.i32))
            .done()
        )

        def _do(conn: KafkaConnection) -> int:
            r = conn.request(API_OFFSET_FETCH, 1, body)
            result = 0
            for _ in range(r.i32()):
                r.string()
                for _ in range(r.i32()):
                    _part, off = r.i32(), r.i64()
                    r.string()                    # metadata
                    err = r.i16()
                    if err:
                        raise KafkaProtocolError("OffsetFetch", err)
                    result = max(0, off)          # -1 == no commit yet
            return result

        return self._with_coordinator(group, "OffsetFetch", _do)

    def lag(self, group: str, topic: str) -> int:
        ends = self.end_offsets(topic)
        return sum(
            max(0, end - self.committed(group, topic, p))
            for p, end in enumerate(ends)
        )

    # ------------------------------------------------------------- consume
    def consumer(self, topics: Sequence[str], group_id: str,
                 faults: Optional[FaultInjector] = None,
                 group_managed: bool = False):
        """Static-assignment consumer by default; ``group_managed=True``
        returns a coordinator-managed member (JoinGroup/SyncGroup/Heartbeat,
        stream/kafka_group.py) so N StreamJob processes in one group split
        partitions and fail over automatically, like the reference's
        consumer group (consumer.properties:5)."""
        if group_managed:
            if faults is not None:
                raise ValueError(
                    "fault injection is not supported on group-managed "
                    "consumers; use the static consumer for chaos tests")
            from realtime_fraud_detection_tpu.stream.kafka_group import (
                KafkaGroupConsumer,
            )

            return KafkaGroupConsumer(self, list(topics), group_id)
        return Consumer(self, list(topics), group_id, faults)

    def create_topic(self, name: str, partitions: int) -> None:
        """Topic creation is an admin-plane operation (the reference uses
        scripts/setup/create-topics.sh); rely on broker auto-create or the
        admin CLI. Refresh our metadata cache so a newly-created topic is
        visible."""
        self._meta.pop(name, None)

"""Networked, durable transport: a standalone TCP log broker + client.

The reference's data backbone is an *external* Kafka cluster — the stream
job, simulator, and serving tier are separate processes joined by brokers
(docker-compose.yml, FraudDetectionJob.java:141-213). Round 1 of this
framework only had the in-process ``InMemoryBroker``; this module makes the
transport genuinely external without taking a client-library dependency:

- ``BrokerServer`` — a TCP server exposing the partitioned-log operations
  (produce / fetch / commit / committed / lag / end_offsets / create_topic)
  over a length-prefixed JSON protocol. State is an ``InMemoryBroker`` plus
  an optional write-ahead segment directory: every produce is appended to
  ``<log_dir>/<topic>-<partition>.jsonl`` and fsync'd before the ack (the
  acks=all analog of config/kafka/producer.properties), group offsets land
  in ``<log_dir>/offsets.json`` on commit, and a restarting server replays
  both — so the broker survives process death the way Kafka's log does.
- ``NetBrokerClient`` — speaks the same protocol from any process and
  implements the exact broker interface ``stream.transport.Consumer``
  consumes (committed/partitions/read/commit/lag), so
  ``StreamJob(broker=NetBrokerClient(...))`` runs unchanged against a
  remote broker. One TCP connection, pipelined request/response framing,
  thread-safe.

Replication (the RF/minISR story — reference runs 3 brokers with RF=3,
minISR=2, scripts/setup/create-topics.sh:9-12):

- A second ``BrokerServer`` started with ``role="replica"`` serves reads
  but refuses writes (``READONLY``). ``primary.add_replica(host, port)``
  catches it up (topic layout, record backlog, group offsets) and then
  ships every produce to it SYNCHRONOUSLY before the producer's ack —
  the acks=all analog. ``min_isr`` gates the ack: a produce that cannot
  reach ``min_isr`` in-sync copies (self included) fails loudly instead of
  pretending durability. A replica that errors is dropped from the ISR
  (exactly Kafka's shrink-then-ack behavior with minISR).
- Offset commits are forwarded to replicas too, so a promoted replica
  resumes every consumer group where the dead primary acked it.
- ``promote()`` (or the ``promote`` wire op) flips a replica to primary.
- ``HaBrokerClient([(h1, p1), (h2, p2)])`` is the client side of failover:
  on connection loss or READONLY it rotates to the next address and
  retries. A retried produce can duplicate (at-least-once, like any
  acks=all producer retry) — consumers dedupe by transaction id
  (stream/job.py dispatch_batch).

Acked-record guarantee: an acked produce is fsync'd on the primary's WAL
AND applied on min_isr-1 replicas (their WALs included) before the ack, so
SIGKILL of the primary loses nothing acked — pinned by the kill-the-primary
soak in tests/test_netbroker.py.

The wire format is 4-byte big-endian length + JSON — deliberately boring:
the contract (offsets, groups, keyed partitions, commit-after-fanout) is
what's load-bearing, and the contract tests run identically against
``InMemoryBroker`` and a live ``BrokerServer`` (tests/test_netbroker.py).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence

from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS, TopicSpec
from realtime_fraud_detection_tpu.stream.transport import (
    Consumer,
    FaultInjector,
    InMemoryBroker,
    Record,
)

__all__ = ["BrokerServer", "NetBrokerClient", "HaBrokerClient"]

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 * 1024 * 1024


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[Any]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds limit")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return json.loads(payload)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        server: BrokerServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        server._conns.add(sock)
        try:
            while True:
                try:
                    req = _recv_frame(sock)
                except (ConnectionError, ValueError, json.JSONDecodeError,
                        OSError):
                    return
                if req is None:
                    return
                try:
                    resp = server.dispatch(req)
                except Exception as e:  # noqa: BLE001 - per-request isolation
                    resp = {"error": f"{type(e).__name__}: {e}"}
                try:
                    _send_frame(sock, resp)
                except (ConnectionError, OSError):
                    return
        finally:
            server._conns.discard(sock)


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _ReplicaLink:
    """Primary-held connection to one replica server (the shipping lane)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.addr = (host, port)
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        with self._lock:
            _send_frame(self._sock, req)
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("replica closed the connection")
        if "error" in resp:
            raise RuntimeError(f"replica error: {resp['error']}")
        return resp

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class NotEnoughReplicasError(RuntimeError):
    """Produce could not reach min_isr in-sync copies (Kafka's
    NOT_ENOUGH_REPLICAS). The record may exist on the primary's log but was
    NOT acked — a retried producer may duplicate it (at-least-once)."""


class BrokerServer:
    """Serve an (optionally durable, optionally replicated) partitioned log
    over TCP. ``role="replica"`` starts read-only; ``min_isr`` counts the
    primary itself (min_isr=2 means "me plus at least one replica")."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Sequence[TopicSpec] = TOPIC_SPECS,
                 log_dir: Optional[str] = None,
                 role: str = "primary", min_isr: int = 1):
        if role not in ("primary", "replica"):
            raise ValueError(f"role must be primary|replica, got {role!r}")
        self.broker = InMemoryBroker(topics)
        self.log_dir = Path(log_dir) if log_dir else None
        self.role = role
        self.min_isr = int(min_isr)
        self._replicas: List[_ReplicaLink] = []
        self._conns: set = set()          # live handler sockets (for stop())
        self._seg_files: Dict[tuple, Any] = {}
        self._io_lock = threading.Lock()
        if self.log_dir is not None:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            self._replay()
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="broker-server", daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        # drop live connections so peers (clients, a primary's replica
        # link) observe the death immediately — a stopped server must not
        # keep acking replication traffic from a lingering handler thread
        for sock in list(self._conns):
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        with self._io_lock:
            for link in self._replicas:
                link.close()
            self._replicas.clear()
            for f in self._seg_files.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._seg_files.clear()

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ----------------------------------------------------------- durability
    def _segment(self, topic: str, partition: int):
        key = (topic, partition)
        f = self._seg_files.get(key)
        if f is None:
            path = self.log_dir / f"{topic}-{partition}.jsonl"
            f = open(path, "a", encoding="utf-8")
            self._seg_files[key] = f
        return f

    def _produce(self, topic: str, items: List[tuple]) -> List[Record]:
        """Produce with WAL-first durability + synchronous replication:
        partition is chosen, the WAL line is written + fsync'd, the record
        is published to the in-memory log, and it is shipped to every
        in-sync replica — the ack happens only once ``min_isr`` copies
        (self included) hold it. A WAL write failure errors the produce
        *before* any consumer could see the record; ``_io_lock`` serializes
        produces so WAL line order always matches log offset order per
        partition AND replicas receive offsets contiguously.
        ``items``: [(key, value, timestamp|None)].
        """
        b = self.broker
        with self._io_lock:
            planned = [
                (b.select_partition(topic, k), k, v,
                 ts if ts is not None else time.time())
                for k, v, ts in items
            ]
            if self.log_dir is not None:
                touched = set()
                for part, k, v, ts in planned:
                    f = self._segment(topic, part)
                    f.write(json.dumps({"k": k, "v": v, "ts": ts},
                                       separators=(",", ":")) + "\n")
                    touched.add(f)
                for f in touched:
                    f.flush()
                    os.fsync(f.fileno())
            recs = [b.append(topic, part, v, k, ts)
                    for part, k, v, ts in planned]
            self._replicate(topic, recs)
            return recs

    # ---------------------------------------------------------- replication
    def _replicate(self, topic: str, recs: List[Record]) -> None:
        """Ship freshly appended records to every replica, synchronously.
        Caller holds ``_io_lock``. A replica that errors is dropped from
        the ISR; if fewer than ``min_isr`` copies hold the records, the
        produce fails (the records stay on the local log unacked — a
        producer retry may duplicate them: at-least-once)."""
        acks = 1  # self: WAL already fsync'd (or in-memory by configuration)
        if self._replicas:
            parts: Dict[int, List[Dict[str, Any]]] = {}
            for r in recs:
                parts.setdefault(r.partition, []).append(
                    {"k": r.key, "v": r.value, "ts": r.timestamp,
                     "o": r.offset})
            req = {
                "op": "replicate", "topic": topic,
                # partition COUNT rides along: an auto-created topic must
                # have the same layout on the replica even for partitions
                # that never received a record, or key routing diverges
                # after a promote
                "n_parts": len(self.broker._logs(topic)),
                "parts": [{"p": p, "base": rows[0]["o"], "records": rows}
                          for p, rows in parts.items()],
            }
            alive = []
            for link in self._replicas:
                try:
                    link.call(req)
                    acks += 1
                    alive.append(link)
                except Exception:  # noqa: BLE001 — ISR shrink on any failure
                    link.close()
            self._replicas[:] = alive
        if acks < self.min_isr:
            raise NotEnoughReplicasError(
                f"produce reached {acks} in-sync copies < min_isr "
                f"{self.min_isr}; record NOT acked")

    def add_replica(self, host: str, port: int,
                    chunk: int = 500) -> None:
        """Attach a replica server: sync topic layout, push the record
        backlog and group offsets, then admit it to the ISR — every later
        produce ships to it before the producer's ack."""
        link = _ReplicaLink(host, port)
        with self._io_lock:
            b = self.broker
            for t in list(b._topics):
                logs = b._logs(t)
                link.call({"op": "sync_topic", "name": t,
                           "partitions": len(logs)})
                rends = link.call({"op": "end_offsets", "topic": t})["ends"]
                for p, log in enumerate(logs):
                    start = rends[p] if p < len(rends) else 0
                    while start < len(log.records):
                        rows = [
                            {"k": r.key, "v": r.value, "ts": r.timestamp,
                             "o": r.offset}
                            for r in log.records[start:start + chunk]
                        ]
                        link.call({"op": "replicate", "topic": t,
                                   "parts": [{"p": p, "base": rows[0]["o"],
                                              "records": rows}]})
                        start += len(rows)
            link.call({"op": "offsets_sync", "committed": {
                f"{g}\x00{t}\x00{p}": off
                for (g, t, p), off in b._committed.items()
            }})
            self._replicas.append(link)

    def _apply_replicated(self, topic: str, part: int, base: int,
                          rows: List[Mapping[str, Any]]) -> None:
        """Replica side: append shipped records at their primary offsets,
        WAL-first when durable. Idempotent for already-held offsets; a gap
        (shipped offset beyond local end) is refused loudly — the primary
        re-syncs via add_replica rather than silently diverging."""
        b = self.broker
        logs = b._logs(topic)
        if part >= len(logs):
            with b._lock:
                while len(logs) < part + 1:
                    logs.append(type(logs[0])())
        log = logs[part]
        with self._io_lock:
            local_end = len(log.records)
            fresh = [(base + j, d) for j, d in enumerate(rows)
                     if base + j >= local_end]
            if fresh and fresh[0][0] > local_end:
                raise RuntimeError(
                    f"replication gap on {topic}-{part}: local end "
                    f"{local_end}, shipped base {fresh[0][0]}")
            if self.log_dir is not None and fresh:
                f = self._segment(topic, part)
                for _, d in fresh:
                    f.write(json.dumps(
                        {"k": d.get("k"), "v": d.get("v"),
                         "ts": d.get("ts", 0.0)},
                        separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            for _, d in fresh:
                b.append(topic, part, d.get("v"), d.get("k"),
                         d.get("ts", 0.0))

    def _forward_commit(self, group: str, wire: Mapping[str, Any]) -> None:
        """Ship an offset commit to replicas so a promoted replica resumes
        every group where the primary acked it. A failing replica drops
        from the ISR (same policy as record shipping)."""
        with self._io_lock:
            if not self._replicas:
                return
            alive = []
            for link in self._replicas:
                try:
                    link.call({"op": "commit_sync", "group": group,
                               "offsets": dict(wire)})
                    alive.append(link)
                except Exception:  # noqa: BLE001
                    link.close()
            self._replicas[:] = alive

    def _grow_topic(self, name: str, partitions: int) -> None:
        """Ensure ``name`` exists with AT LEAST ``partitions`` partitions
        (replica-side layout sync; partition counts only ever grow)."""
        b = self.broker
        b.create_topic(name, partitions)
        logs = b._logs(name)
        if len(logs) < partitions:
            with b._lock:
                while len(logs) < partitions:
                    logs.append(type(logs[0])())

    def promote(self) -> None:
        """Replica -> primary: start accepting writes. The log, offsets and
        WAL carry over as-is (they were kept in sync by the shipping lane)."""
        self.role = "primary"

    def isr_size(self) -> int:
        with self._io_lock:
            return 1 + len(self._replicas)

    def _persist_offsets(self) -> None:
        if self.log_dir is None:
            return
        with self._io_lock:
            snap = {
                f"{g}\x00{t}\x00{p}": off
                for (g, t, p), off in self.broker._committed.items()
            }
            tmp = self.log_dir / "offsets.json.tmp"
            tmp.write_text(json.dumps(snap))
            tmp.replace(self.log_dir / "offsets.json")

    def _replay(self) -> None:
        for path in sorted(self.log_dir.glob("*-*.jsonl")):
            topic, _, part_s = path.stem.rpartition("-")
            try:
                part = int(part_s)
            except ValueError:
                continue
            logs = self.broker._logs(topic)
            if part >= len(logs):
                self.broker._topics[topic].extend(
                    type(logs[0])() for _ in range(part + 1 - len(logs)))
            log = self.broker._logs(topic)[part]
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    d = json.loads(line)
                    log.records.append(Record(
                        topic, part, len(log.records), d.get("k"),
                        d.get("v"), d.get("ts", 0.0)))
        off_path = self.log_dir / "offsets.json"
        if off_path.exists():
            for key, off in json.loads(off_path.read_text()).items():
                g, t, p = key.split("\x00")
                self.broker._committed[(g, t, int(p))] = int(off)

    # ------------------------------------------------------------- dispatch
    _WRITE_OPS = frozenset({"produce", "produce_batch", "commit",
                            "create_topic"})

    def dispatch(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        b = self.broker
        if self.role == "replica" and op in self._WRITE_OPS:
            # reads stay served (a replica is a warm standby + read scale-
            # out); writes go to the primary or wait for promote()
            return {"error": "READONLY: replica accepts reads and "
                             "replication traffic only; promote() to "
                             "accept writes"}
        if op == "replicate":
            n_parts = req.get("n_parts")
            if n_parts:
                self._grow_topic(req["topic"], int(n_parts))
            for blob in req["parts"]:
                self._apply_replicated(req["topic"], int(blob["p"]),
                                       int(blob["base"]), blob["records"])
            return {}
        if op == "sync_topic":
            self._grow_topic(req["name"], int(req["partitions"]))
            return {}
        if op == "commit_sync":
            offsets = {}
            for key, off in req["offsets"].items():
                t, _, p = key.rpartition(":")
                offsets[(t, int(p))] = int(off)
            b.commit(req["group"], offsets)
            self._persist_offsets()
            return {}
        if op == "offsets_sync":
            for key, off in req["committed"].items():
                g, t, p = key.split("\x00")
                b._committed[(g, t, int(p))] = int(off)
            self._persist_offsets()
            return {}
        if op == "promote":
            self.promote()
            return {"role": self.role}
        if op == "status":
            return {"role": self.role, "min_isr": self.min_isr,
                    "isr": self.isr_size()}
        if op == "produce":
            rec = self._produce(req["topic"], [(
                req.get("key"), req["value"], req.get("timestamp"))])[0]
            return {"partition": rec.partition, "offset": rec.offset}
        if op == "produce_batch":
            recs = self._produce(req["topic"], [
                (item.get("k"), item["v"], None) for item in req["records"]])
            return {"n": len(recs)}
        if op == "fetch":
            recs = b.read(req["topic"], req["partition"], req["offset"],
                          req["max_records"])
            return {"records": [
                {"p": r.partition, "o": r.offset, "k": r.key, "v": r.value,
                 "ts": r.timestamp} for r in recs]}
        if op == "commit":
            offsets = {}
            for key, off in req["offsets"].items():
                t, _, p = key.rpartition(":")
                offsets[(t, int(p))] = int(off)
            b.commit(req["group"], offsets)
            self._persist_offsets()
            self._forward_commit(req["group"], req["offsets"])
            return {}
        if op == "committed":
            return {"offset": b.committed(req["group"], req["topic"],
                                          req["partition"])}
        if op == "partitions":
            return {"n": b.partitions(req["topic"])}
        if op == "end_offsets":
            return {"ends": b.end_offsets(req["topic"])}
        if op == "lag":
            return {"lag": b.lag(req["group"], req["topic"])}
        if op == "create_topic":
            b.create_topic(req["name"], req["partitions"])
            # layout changes ship to replicas like records do: a topic
            # created after add_replica must exist with the same partition
            # count on the survivor, or key routing diverges post-promote
            with self._io_lock:
                alive = []
                for link in self._replicas:
                    try:
                        link.call({"op": "sync_topic", "name": req["name"],
                                   "partitions": req["partitions"]})
                        alive.append(link)
                    except Exception:  # noqa: BLE001
                        link.close()
                self._replicas[:] = alive
            return {}
        if op == "ping":
            return {"pong": True}
        raise ValueError(f"unknown op {op!r}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class NetBrokerClient:
    """Broker-interface client over one pipelined TCP connection.

    Implements the five methods ``transport.Consumer`` needs (committed /
    partitions / read / commit / lag) plus the producer surface, so every
    component that takes an ``InMemoryBroker`` takes one of these.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 9092,
                 timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._part_cache: Dict[str, int] = {}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            _send_frame(self._sock, req)
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("broker closed the connection")
        if "error" in resp:
            raise RuntimeError(f"broker error: {resp['error']}")
        return resp

    # ------------------------------------------------------------- produce
    def produce(self, topic: str, value: Any, key: Optional[str] = None,
                timestamp: Optional[float] = None) -> Record:
        r = self._call({"op": "produce", "topic": topic, "value": value,
                        "key": key, "timestamp": timestamp})
        return Record(topic, r["partition"], r["offset"], key, value,
                      timestamp or 0.0)

    def produce_batch(self, topic: str, values, key_fn=None) -> int:
        items = [{"v": v, "k": key_fn(v) if key_fn else None} for v in values]
        if not items:
            return 0
        return self._call({"op": "produce_batch", "topic": topic,
                           "records": items})["n"]

    def produce_batch_keyed(self, topic: str, items) -> int:
        """(key, value) pairs in ONE frame — the fan-out hot path
        (one TCP round trip instead of one per record)."""
        records = [{"v": v, "k": k} for k, v in items]
        if not records:
            return 0
        return self._call({"op": "produce_batch", "topic": topic,
                           "records": records})["n"]

    # ------------------------------------------------------------- consume
    def consumer(self, topics: Sequence[str], group_id: str,
                 faults: Optional[FaultInjector] = None) -> Consumer:
        return Consumer(self, list(topics), group_id, faults)

    def read(self, topic: str, partition: int, start: int,
             limit: int) -> List[Record]:
        resp = self._call({"op": "fetch", "topic": topic,
                           "partition": partition, "offset": start,
                           "max_records": limit})
        return [
            Record(topic, d["p"], d["o"], d.get("k"), d.get("v"),
                   d.get("ts", 0.0))
            for d in resp["records"]
        ]

    # ------------------------------------------------------------- offsets
    def committed(self, group: str, topic: str, partition: int) -> int:
        return self._call({"op": "committed", "group": group, "topic": topic,
                           "partition": partition})["offset"]

    def commit(self, group: str, offsets: Mapping[tuple, int]) -> None:
        wire = {f"{t}:{p}": off for (t, p), off in offsets.items()}
        self._call({"op": "commit", "group": group, "offsets": wire})

    def partitions(self, topic: str) -> int:
        n = self._part_cache.get(topic)
        if n is None:
            n = self._call({"op": "partitions", "topic": topic})["n"]
            self._part_cache[topic] = n
        return n

    def end_offsets(self, topic: str) -> List[int]:
        return self._call({"op": "end_offsets", "topic": topic})["ends"]

    def lag(self, group: str, topic: str) -> int:
        return self._call({"op": "lag", "group": group, "topic": topic})["lag"]

    def create_topic(self, name: str, partitions: int) -> None:
        self._part_cache.pop(name, None)
        self._call({"op": "create_topic", "name": name,
                    "partitions": partitions})

    def ping(self) -> bool:
        return bool(self._call({"op": "ping"}).get("pong"))

    def status(self) -> Dict[str, Any]:
        return self._call({"op": "status"})

    def promote(self) -> Dict[str, Any]:
        """Remote promote (the ops-script path for failover drills)."""
        return self._call({"op": "promote"})


class HaBrokerClient(NetBrokerClient):
    """Failover-aware client over an ordered broker list.

    On connection loss or a READONLY response (we were talking to a
    not-yet-promoted replica) the client rotates to the next address,
    reconnects, and retries the request. NOTE the produce-retry semantics:
    a produce whose ack was lost mid-failover may already be on the log,
    so a retry can duplicate it — at-least-once, exactly like a Kafka
    acks=all producer retrying across a leader change. Stream consumers
    dedupe by transaction id (stream/job.py dispatch_batch).
    """

    def __init__(self, addrs: Sequence[tuple], timeout_s: float = 30.0):
        if not addrs:
            raise ValueError("HaBrokerClient needs at least one address")
        self._addrs = [(str(h), int(p)) for h, p in addrs]
        self._which = 0
        self._timeout_s = timeout_s
        # construction must survive a dead first broker (a process started
        # AFTER the failover still lists the old primary first): try each
        # address in order
        last: Optional[Exception] = None
        for i, (host, port) in enumerate(self._addrs):
            try:
                super().__init__(host=host, port=port, timeout_s=timeout_s)
                self._which = i
                return
            except OSError as e:
                last = e
        raise ConnectionError(
            f"no broker in {self._addrs} reachable: {last}")

    def _rotate(self) -> None:
        self._which = (self._which + 1) % len(self._addrs)
        host, port = self._addrs[self._which]
        with self._lock:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = socket.create_connection(
                (host, port), timeout=self._timeout_s)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        last: Optional[Exception] = None
        for _ in range(2 * len(self._addrs)):
            try:
                return super()._call(req)
            except RuntimeError as e:
                if "READONLY" not in str(e):
                    raise
                last = e
            except (ConnectionError, OSError) as e:
                last = e
            try:
                self._rotate()
            except OSError as e:
                last = e
                time.sleep(0.05)
        raise ConnectionError(
            f"no broker in {self._addrs} reachable and writable: {last}")

"""Networked, durable transport: a standalone TCP log broker + client.

The reference's data backbone is an *external* Kafka cluster — the stream
job, simulator, and serving tier are separate processes joined by brokers
(docker-compose.yml, FraudDetectionJob.java:141-213). Round 1 of this
framework only had the in-process ``InMemoryBroker``; this module makes the
transport genuinely external without taking a client-library dependency:

- ``BrokerServer`` — a TCP server exposing the partitioned-log operations
  (produce / fetch / commit / committed / lag / end_offsets / create_topic)
  over a length-prefixed JSON protocol. State is an ``InMemoryBroker`` plus
  an optional write-ahead segment directory: every produce is appended to
  ``<log_dir>/<topic>-<partition>.jsonl`` and fsync'd before the ack (the
  acks=all analog of config/kafka/producer.properties), group offsets land
  in ``<log_dir>/offsets.json`` on commit, and a restarting server replays
  both — so the broker survives process death the way Kafka's log does.
- ``NetBrokerClient`` — speaks the same protocol from any process and
  implements the exact broker interface ``stream.transport.Consumer``
  consumes (committed/partitions/read/commit/lag), so
  ``StreamJob(broker=NetBrokerClient(...))`` runs unchanged against a
  remote broker. One TCP connection, pipelined request/response framing,
  thread-safe.

The wire format is 4-byte big-endian length + JSON — deliberately boring:
the contract (offsets, groups, keyed partitions, commit-after-fanout) is
what's load-bearing, and the contract tests run identically against
``InMemoryBroker`` and a live ``BrokerServer`` (tests/test_netbroker.py).
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import struct
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional, Sequence

from realtime_fraud_detection_tpu.stream.topics import TOPIC_SPECS, TopicSpec
from realtime_fraud_detection_tpu.stream.transport import (
    Consumer,
    FaultInjector,
    InMemoryBroker,
    Record,
)

__all__ = ["BrokerServer", "NetBrokerClient"]

_LEN = struct.Struct(">I")
_MAX_FRAME = 64 * 1024 * 1024


def _send_frame(sock: socket.socket, obj: Any) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> Optional[Any]:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > _MAX_FRAME:
        raise ValueError(f"frame of {length} bytes exceeds limit")
    payload = _recv_exact(sock, length)
    if payload is None:
        return None
    return json.loads(payload)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # one connection, many requests
        server: BrokerServer = self.server.outer  # type: ignore[attr-defined]
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                req = _recv_frame(sock)
            except (ConnectionError, ValueError, json.JSONDecodeError):
                return
            if req is None:
                return
            try:
                resp = server.dispatch(req)
            except Exception as e:  # noqa: BLE001 - fault isolation per request
                resp = {"error": f"{type(e).__name__}: {e}"}
            try:
                _send_frame(sock, resp)
            except ConnectionError:
                return


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class BrokerServer:
    """Serve an (optionally durable) partitioned log over TCP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 topics: Sequence[TopicSpec] = TOPIC_SPECS,
                 log_dir: Optional[str] = None):
        self.broker = InMemoryBroker(topics)
        self.log_dir = Path(log_dir) if log_dir else None
        self._seg_files: Dict[tuple, Any] = {}
        self._io_lock = threading.Lock()
        if self.log_dir is not None:
            self.log_dir.mkdir(parents=True, exist_ok=True)
            self._replay()
        self._tcp = _TCPServer((host, port), _Handler)
        self._tcp.outer = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._tcp.serve_forever, name="broker-server", daemon=True)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "BrokerServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()
        with self._io_lock:
            for f in self._seg_files.values():
                try:
                    f.close()
                except OSError:
                    pass
            self._seg_files.clear()

    @property
    def port(self) -> int:
        return self._tcp.server_address[1]

    # ----------------------------------------------------------- durability
    def _segment(self, topic: str, partition: int):
        key = (topic, partition)
        f = self._seg_files.get(key)
        if f is None:
            path = self.log_dir / f"{topic}-{partition}.jsonl"
            f = open(path, "a", encoding="utf-8")
            self._seg_files[key] = f
        return f

    def _produce(self, topic: str, items: List[tuple]) -> List[Record]:
        """Produce with WAL-first durability: partition is chosen, the WAL
        line is written + fsync'd, and only then is the record published to
        the in-memory log (one fsync per produce call — acks=all). A WAL
        write failure therefore errors the produce *before* any consumer
        could see the record; ``_io_lock`` serializes durable produces so
        WAL line order always matches log offset order per partition.
        ``items``: [(key, value, timestamp|None)].
        """
        b = self.broker
        if self.log_dir is None:
            return [b.produce(topic, v, k, ts) for k, v, ts in items]
        with self._io_lock:
            planned = [
                (b.select_partition(topic, k), k, v,
                 ts if ts is not None else time.time())
                for k, v, ts in items
            ]
            touched = set()
            for part, k, v, ts in planned:
                f = self._segment(topic, part)
                f.write(json.dumps({"k": k, "v": v, "ts": ts},
                                   separators=(",", ":")) + "\n")
                touched.add(f)
            for f in touched:
                f.flush()
                os.fsync(f.fileno())
            return [b.append(topic, part, v, k, ts)
                    for part, k, v, ts in planned]

    def _persist_offsets(self) -> None:
        if self.log_dir is None:
            return
        with self._io_lock:
            snap = {
                f"{g}\x00{t}\x00{p}": off
                for (g, t, p), off in self.broker._committed.items()
            }
            tmp = self.log_dir / "offsets.json.tmp"
            tmp.write_text(json.dumps(snap))
            tmp.replace(self.log_dir / "offsets.json")

    def _replay(self) -> None:
        for path in sorted(self.log_dir.glob("*-*.jsonl")):
            topic, _, part_s = path.stem.rpartition("-")
            try:
                part = int(part_s)
            except ValueError:
                continue
            logs = self.broker._logs(topic)
            if part >= len(logs):
                self.broker._topics[topic].extend(
                    type(logs[0])() for _ in range(part + 1 - len(logs)))
            log = self.broker._logs(topic)[part]
            with open(path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    d = json.loads(line)
                    log.records.append(Record(
                        topic, part, len(log.records), d.get("k"),
                        d.get("v"), d.get("ts", 0.0)))
        off_path = self.log_dir / "offsets.json"
        if off_path.exists():
            for key, off in json.loads(off_path.read_text()).items():
                g, t, p = key.split("\x00")
                self.broker._committed[(g, t, int(p))] = int(off)

    # ------------------------------------------------------------- dispatch
    def dispatch(self, req: Mapping[str, Any]) -> Dict[str, Any]:
        op = req.get("op")
        b = self.broker
        if op == "produce":
            rec = self._produce(req["topic"], [(
                req.get("key"), req["value"], req.get("timestamp"))])[0]
            return {"partition": rec.partition, "offset": rec.offset}
        if op == "produce_batch":
            recs = self._produce(req["topic"], [
                (item.get("k"), item["v"], None) for item in req["records"]])
            return {"n": len(recs)}
        if op == "fetch":
            recs = b.read(req["topic"], req["partition"], req["offset"],
                          req["max_records"])
            return {"records": [
                {"p": r.partition, "o": r.offset, "k": r.key, "v": r.value,
                 "ts": r.timestamp} for r in recs]}
        if op == "commit":
            offsets = {}
            for key, off in req["offsets"].items():
                t, _, p = key.rpartition(":")
                offsets[(t, int(p))] = int(off)
            b.commit(req["group"], offsets)
            self._persist_offsets()
            return {}
        if op == "committed":
            return {"offset": b.committed(req["group"], req["topic"],
                                          req["partition"])}
        if op == "partitions":
            return {"n": b.partitions(req["topic"])}
        if op == "end_offsets":
            return {"ends": b.end_offsets(req["topic"])}
        if op == "lag":
            return {"lag": b.lag(req["group"], req["topic"])}
        if op == "create_topic":
            b.create_topic(req["name"], req["partitions"])
            return {}
        if op == "ping":
            return {"pong": True}
        raise ValueError(f"unknown op {op!r}")


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class NetBrokerClient:
    """Broker-interface client over one pipelined TCP connection.

    Implements the five methods ``transport.Consumer`` needs (committed /
    partitions / read / commit / lag) plus the producer surface, so every
    component that takes an ``InMemoryBroker`` takes one of these.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 9092,
                 timeout_s: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._part_cache: Dict[str, int] = {}

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def _call(self, req: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            _send_frame(self._sock, req)
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("broker closed the connection")
        if "error" in resp:
            raise RuntimeError(f"broker error: {resp['error']}")
        return resp

    # ------------------------------------------------------------- produce
    def produce(self, topic: str, value: Any, key: Optional[str] = None,
                timestamp: Optional[float] = None) -> Record:
        r = self._call({"op": "produce", "topic": topic, "value": value,
                        "key": key, "timestamp": timestamp})
        return Record(topic, r["partition"], r["offset"], key, value,
                      timestamp or 0.0)

    def produce_batch(self, topic: str, values, key_fn=None) -> int:
        items = [{"v": v, "k": key_fn(v) if key_fn else None} for v in values]
        if not items:
            return 0
        return self._call({"op": "produce_batch", "topic": topic,
                           "records": items})["n"]

    # ------------------------------------------------------------- consume
    def consumer(self, topics: Sequence[str], group_id: str,
                 faults: Optional[FaultInjector] = None) -> Consumer:
        return Consumer(self, list(topics), group_id, faults)

    def read(self, topic: str, partition: int, start: int,
             limit: int) -> List[Record]:
        resp = self._call({"op": "fetch", "topic": topic,
                           "partition": partition, "offset": start,
                           "max_records": limit})
        return [
            Record(topic, d["p"], d["o"], d.get("k"), d.get("v"),
                   d.get("ts", 0.0))
            for d in resp["records"]
        ]

    # ------------------------------------------------------------- offsets
    def committed(self, group: str, topic: str, partition: int) -> int:
        return self._call({"op": "committed", "group": group, "topic": topic,
                           "partition": partition})["offset"]

    def commit(self, group: str, offsets: Mapping[tuple, int]) -> None:
        wire = {f"{t}:{p}": off for (t, p), off in offsets.items()}
        self._call({"op": "commit", "group": group, "offsets": wire})

    def partitions(self, topic: str) -> int:
        n = self._part_cache.get(topic)
        if n is None:
            n = self._call({"op": "partitions", "topic": topic})["n"]
            self._part_cache[topic] = n
        return n

    def end_offsets(self, topic: str) -> List[int]:
        return self._call({"op": "end_offsets", "topic": topic})["ends"]

    def lag(self, group: str, topic: str) -> int:
        return self._call({"op": "lag", "group": group, "topic": topic})["lag"]

    def create_topic(self, name: str, partitions: int) -> None:
        self._part_cache.pop(name, None)
        self._call({"op": "create_topic", "name": name,
                    "partitions": partitions})

    def ping(self) -> bool:
        return bool(self._call({"op": "ping"}).get("pong"))
